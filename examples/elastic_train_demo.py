"""Elastic train-through-failure demo — a rank dies, training finishes.

    python examples/elastic_train_demo.py

Self-launching: re-execs itself under ``tpurun -n 4 --enable-recovery``
with a chaos kill schedule (``kill:rank=2,step=7``) and tracing on.
Rank 2 is killed mid-training; the survivors run the recovery state
machine — revoke → ERA agree → shrink to the surviving membership →
respawn a replacement via ``MPI_Comm_spawn`` (verified against the
dynamic ``mpi://job/<id>`` pset) → restore from the last checkpoint →
resume — and the job completes at full width with parameters
**bit-exact** to a failure-free run restored from the same checkpoint
step (verified at the end against the pure-numpy oracle).

Inspect the merged timeline afterwards (chrome://tracing /
Perfetto): the ``elastic_detect`` → ``elastic_agree`` →
``elastic_shrink`` → ``elastic_respawn`` → ``elastic_restore`` →
``elastic_resume`` spans ARE the recovery, with wall-clock widths.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

STEPS, BATCH, DIMS = 15, 24, 12


def launch() -> int:
    work = tempfile.mkdtemp(prefix="otpu-elastic-demo-")
    ckpt = os.path.join(work, "ckpt")
    tdir = os.path.join(work, "trace")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "4",
           "--enable-recovery",
           "--mca", "otpu_chaos_spec", "kill:rank=2,step=7",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", tdir,
           sys.executable, os.path.abspath(__file__), ckpt]
    print("launching:", " ".join(cmd[2:]), flush=True)
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       timeout=600)
    sys.stdout.write(r.stdout)
    line = next((ln for ln in r.stdout.splitlines()
                 if "ELASTIC " in ln), None)
    if r.returncode or line is None:
        sys.stderr.write(r.stderr)
        print("demo FAILED", file=sys.stderr)
        return 1
    rep = json.loads(line.split("ELASTIC ", 1)[1])
    rec = rep["recoveries"][0]
    print(f"\nkilled rank 2 at step {rec['detect_step']}; recovery "
          f"{rec['total_ms']:.0f}ms "
          f"(agree {rec['agree_ms']:.0f} / shrink {rec['shrink_ms']:.0f}"
          f" / respawn {rec.get('respawn_ms', 0):.0f}), resumed from "
          f"step {rec['resume_step']} at width {rec['world_size']}")

    # the failure-free oracle, restored from the same checkpoint step
    import numpy as np

    from ompi_tpu.parallel import checkpoint
    from ompi_tpu.parallel.elastic import reference_run

    tree = checkpoint.load(
        os.path.join(ckpt, f"step{rec['resume_step']:06d}"))
    ref = reference_run(np.asarray(tree["w"]), rec["resume_step"],
                        STEPS, BATCH)
    ok = rep["w"] == ref.tolist()
    print("bit-exact vs failure-free restore:", "YES" if ok else "NO")
    print(f"merged timeline: {os.path.join(tdir, 'trace_merged.json')}")
    return 0 if ok else 1


def rank_main() -> int:
    import ompi_tpu
    from ompi_tpu.parallel.elastic import ElasticTrainer

    world = ompi_tpu.init()
    trainer = ElasticTrainer(world, ckpt_dir=sys.argv[1],
                             model_size=DIMS, global_batch=BATCH,
                             ckpt_every=5, respawn=True)
    trainer.train(STEPS)
    if trainer.comm.rank == 0:
        print("ELASTIC " + json.dumps(trainer.report()), flush=True)
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(rank_main() if "OTPU_RANK" in os.environ else launch())
