"""MoE/EP ragged dispatch with the true alltoallv — the workload the
counts-driven pallas kernels exist for.

Every routing step of a mixture-of-experts layer sends a DIFFERENT
number of tokens between each pair of ranks.  A padded ``all_to_all``
must move the worst-case count for every pair; the ragged kernel
(`ops.pallas_collectives.all_to_all_v`) takes the (n, n) counts table
as a runtime operand and moves only (chunk-rounded) real tokens — and
because the counts are data, ONE compiled program serves every routing
outcome, where a shape-specialized kernel would recompile per batch.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
         python examples/ragged_dispatch.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if os.environ.get("OTPU_EXAMPLE_EXECED") != "1":
    # the platform must be pinned in the BOOT environment — a site boot
    # hook may write its own JAX_PLATFORMS into os.environ, so a
    # setdefault cannot detect user intent; re-exec once with a marker
    # (OTPU_TOUR_PLATFORM=tpu to run on real chips)
    env = dict(os.environ, OTPU_EXAMPLE_EXECED="1",
               JAX_PLATFORMS=os.environ.get("OTPU_TOUR_PLATFORM", "cpu"))
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    os.execvpe(sys.executable, [sys.executable,
                                os.path.abspath(__file__)], env)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # a site boot hook may pin an accelerator via jax.config,
        # overriding the env var — restore env precedence
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ompi_tpu.ops.pallas_collectives import all_to_all_v

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("ep",))
    d_model = 256                 # token feature width (128-lane aligned)
    cap = 64                      # worst-case tokens per (src, dst) pair

    rng = np.random.default_rng(0)
    # a routing step: rank i holds cap-padded token blocks for each
    # expert home j, with counts[i, j] real tokens
    counts = rng.integers(4, cap + 1, (n, n)).astype(np.int32)
    tokens = rng.standard_normal((n, n, cap, d_model)).astype(np.float32)

    out = np.asarray(all_to_all_v(jnp.asarray(tokens), counts, mesh,
                                  "ep"))
    # rank j now holds out[j, i, :counts[i, j]] = rank i's tokens for it
    for j in range(n):
        for i in range(n):
            c = counts[i, j]
            np.testing.assert_array_equal(out[j, i, :c],
                                          tokens[i, j, :c])

    ideal = counts.sum() * d_model * 4
    chunk = 8
    ragged = (-(-counts // chunk) * chunk).sum() * d_model * 4
    padded = n * n * cap * d_model * 4
    print(f"dispatch verified on {n} ranks: ideal {ideal >> 10} KiB, "
          f"ragged wire {ragged >> 10} KiB "
          f"({ragged / ideal:.2f}x ideal), padded all_to_all would "
          f"move {padded >> 10} KiB ({padded / ideal:.2f}x)")

    # the inverse (combine) is the same kernel with transposed counts
    back = np.asarray(all_to_all_v(jnp.asarray(out), counts.T, mesh,
                                   "ep"))
    for i in range(n):
        for j in range(n):
            c = counts[i, j]
            np.testing.assert_array_equal(back[i, j, :c],
                                          tokens[i, j, :c])
    print("combine (inverse dispatch) verified: counts.T round-trips")


if __name__ == "__main__":
    main()
