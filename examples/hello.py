"""Hello world — the ``examples/hello_c.c`` equivalent.

Prints rank/size plus the node and transport facts a user checks first.
Run: ``python examples/hello.py`` (singleton / device world) or
``tpurun -n 4 python examples/hello.py``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ompi_tpu


def main() -> None:
    world = ompi_tpu.init()
    import ompi_tpu as pkg

    print(f"Hello, world, I am {world.rank} of {world.size} "
          f"(ompi_tpu {pkg.__version__}, comm {world.name})", flush=True)
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
