"""All-pairs connectivity check — ``examples/connectivity_c.c`` equivalent:
every rank exchanges a message with every other rank."""
import numpy as np

import ompi_tpu


def main() -> None:
    world = ompi_tpu.init()
    n = world.size
    if world.rte.is_device_world:
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                world.as_rank(i).send(np.array([i * n + j]), dest=j, tag=300)
        for j in range(n):
            for i in range(n):
                if i == j:
                    continue
                buf = np.zeros(1, np.int64)
                world.as_rank(j).recv(buf, source=i, tag=300)
                assert buf[0] == i * n + j
        print(f"connectivity OK: {n} ranks fully connected "
              f"({n * (n - 1)} messages)")
    else:
        rank = world.rank
        reqs = [world.isend(np.array([rank * n + j]), dest=j, tag=300)
                for j in range(n) if j != rank]
        for i in range(n):
            if i == rank:
                continue
            buf = np.zeros(1, np.int64)
            world.recv(buf, source=i, tag=300)
            assert buf[0] == i * n + rank
        from ompi_tpu.api.request import waitall

        waitall(reqs)
        world.barrier()
        if rank == 0:
            print(f"connectivity OK: {n} ranks")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
