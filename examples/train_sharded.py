"""Flagship demo: explicit-SPMD transformer training on a device mesh.

The parallel layer end to end — dp/pp/sp/tp(+ep) mesh, ring attention
over sp, Megatron-style tp matmuls, MoE alltoall dispatch, GPipe
microbatching over pp — with every cross-device exchange an explicit
mesh collective (the framework's device-side coll path).

Run on any device set:
  python examples/train_sharded.py            # real chip(s)
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      OTPU_DEMO_CPU=1 python examples/train_sharded.py   # 8-dev CPU mesh
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("OTPU_DEMO_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax  # noqa: E402


def main() -> None:
    from ompi_tpu.parallel.dryrun import run_training_step

    devices = jax.devices()
    print(f"training on {len(devices)} {devices[0].platform} device(s)")
    loss = run_training_step(devices)
    print(f"done; initial loss {loss:.4f}")


if __name__ == "__main__":
    main()
