"""Ring smoke test — the ``examples/ring_c.c`` equivalent (BASELINE config #1).

A token circulates the ring 10 times, decremented each pass through rank 0.
Runs in both process models:
- conductor/device-world (default): one process drives all ranks
- multi-process: ``tpurun -n 4 python examples/ring.py``
"""
import numpy as np

import ompi_tpu


def main() -> None:
    world = ompi_tpu.init()
    size = world.size
    tag = 201

    if world.rte.is_device_world:
        # conductor model: drive each rank explicitly
        token = np.array([10], dtype=np.int32)
        world.as_rank(0).send(token, dest=1 % size, tag=tag)
        passes = 0
        done = False
        while not done:
            for r in list(range(1, size)) + [0]:
                buf = np.zeros(1, np.int32)
                world.as_rank(r).recv(buf, source=(r - 1) % size, tag=tag)
                passes += 1
                if r == 0:
                    buf[0] -= 1
                    print(f"rank 0: token now {buf[0]}")
                    if buf[0] == 0:
                        done = True
                        break
                world.as_rank(r).send(buf, dest=(r + 1) % size, tag=tag)
        print(f"ring done: {passes} hops on {size} ranks")
    else:
        rank = world.rank
        token = np.array([10], dtype=np.int32)
        if rank == 0:
            world.send(token, dest=(rank + 1) % size, tag=tag)
        while True:
            world.recv(token, source=(rank - 1) % size, tag=tag)
            if rank == 0:
                token[0] -= 1
                print(f"rank 0: token now {token[0]}")
            if token[0] == 0 and rank == 0:
                # let the token die at rank 0 after telling the ring once more
                world.send(token, dest=(rank + 1) % size, tag=tag)
                break
            world.send(token, dest=(rank + 1) % size, tag=tag)
            if token[0] == 0:
                break
        print(f"rank {rank} exiting")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
