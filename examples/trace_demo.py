"""otpu-trace demo — run under tpurun with tracing enabled:

    python -m ompi_tpu.tools.tpurun -n 4 \
        --mca trace_enable 1 --mca trace_dir /tmp/otpu-trace \
        python examples/trace_demo.py

Each rank records pml/coll/osc spans into its ring buffer and writes
``trace_rank<r>.json`` (Chrome trace format — load in chrome://tracing
or Perfetto) at finalize; tpurun then gathers every rank's payload
through the CoordServer, aligns clocks against the coord server's
(mpisync min-RTT estimator), and writes ``trace_merged.json`` plus the
``trace_skew.txt`` straggler report into the trace directory.

Rank 0 also demonstrates the live MPI_T surface: the log2-size-binned
latency histogram pvars visible through ``otpu_info --pvars``.
"""
import contextlib
import io
import sys

import numpy as np

import ompi_tpu


def main() -> int:
    world = ompi_tpu.init()
    me, n = world.rank, world.size

    # collectives across a few log2 size bins (histogram fodder)
    for nbytes in (1 << 10, 1 << 14, 1 << 18):
        x = np.ones(nbytes // 4, np.float32) * (me + 1)
        for _ in range(3):
            world.allreduce(x)
    world.barrier()

    # a p2p ring (pml send/recv spans)
    buf = np.zeros(128, np.float32)
    if n > 1:
        right, left = (me + 1) % n, (me - 1) % n
        req = world.isend(np.full(128, me, np.float32), right, tag=7)
        world.recv(buf, left, tag=7)
        req.wait()

    # make rank n-1 a deliberate straggler so the skew report has a
    # clear "slowest rank" to name
    if me == n - 1:
        import time

        time.sleep(0.02)
    world.barrier()

    if me == 0:
        # the live MPI_T view: otpu_info --pvars in THIS process shows
        # the nonzero log2-binned latency histograms
        from ompi_tpu.tools import otpu_info

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            otpu_info.main(["--pvars", "--parsable"])
        hist_lines = [ln for ln in out.getvalue().splitlines()
                      if "trace_hist" in ln]
        print("live pvar histograms (otpu_info --pvars):")
        for ln in hist_lines:
            print(" ", ln)

    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
