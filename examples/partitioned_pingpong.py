"""MPI-4 partitioned communication demo — run under tpurun:

    python -m ompi_tpu.tools.tpurun -n 2 python examples/partitioned_pingpong.py

Rank 0 "produces" a large buffer one partition at a time (simulated
compute per partition) and releases each slice with ``Pready`` the
moment it is final — transfer of finished partitions overlaps the
computation of the rest, which is the contract behind bucketed gradient
overlap (``parallel_bucket_overlap``).  Rank 1 polls ``Parrived`` and
consumes partitions as they land instead of waiting for the whole
message.  Try ``--mca part_persist_min_partitions 4`` to watch N app
partitions travel as fewer wire messages (``otpu_info --pvars`` shows
the ``part_*`` SPC counters).
"""
import time

import numpy as np

import ompi_tpu


def main() -> int:
    world = ompi_tpu.init()
    if world.size < 2:
        print("needs 2 ranks")
        return 1
    me = world.rank
    parts, per = 8, 1 << 12                   # 8 x 4K-element partitions
    buf = np.zeros(parts * per, np.float64)

    if me == 0:
        req = world.psend_init(buf, parts, dest=1, tag=1)
        req.start()
        for p in range(parts):
            # "compute" partition p, then release it immediately
            buf[p * per:(p + 1) * per] = p + 1
            time.sleep(0.002)
            req.pready(p)
            print(f"[rank 0] partition {p} ready", flush=True)
        req.wait()
        print("[rank 0] all partitions sent", flush=True)
    elif me == 1:
        req = world.precv_init(buf, parts, source=0, tag=1)
        req.start()
        done = set()
        while len(done) < parts:
            for p in range(parts):
                if p not in done and req.parrived(p):
                    s = buf[p * per:(p + 1) * per].sum()
                    print(f"[rank 1] partition {p} arrived "
                          f"(sum {s:.0f})", flush=True)
                    done.add(p)
        req.wait()
        assert all(buf[p * per] == p + 1 for p in range(parts))
        print("[rank 1] complete", flush=True)

    from ompi_tpu.runtime import spc

    world.barrier()
    print(f"[rank {me}] part_msgs={spc.read('part_msgs'):.0f} "
          f"part_bytes={spc.read('part_bytes'):.0f}", flush=True)
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
