"""Fleet demo — the multi-tenant serving platform, two models end to
end.

    python examples/fleet_demo.py              # in-process loopback
    python -m ompi_tpu.tools.tpurun -n 5 \\
        --pool m_a:1,2 --pool m_b:3,4 python examples/fleet_demo.py

Two model pools share the job's workers; two weighted tenants (ten_a
2:1 over ten_b) drive mixed Poisson traffic whose prompts share prefix
templates — the shape that makes prefix-cache-aware routing pay.  The
demo prints what the fleet delivered per tenant (p50/p99 out of each
tenant's OWN otpu-trace histogram family, tokens/sec) and what the
prefix cache saved (worker-verified hits vs full prefill passes).

In-process, the four workers run their serve loops on threads over
``Comm.as_rank`` views and the fleet resolves its pools from explicit
:class:`~ompi_tpu.serving.fleet.PoolSpec` tables; under tpurun the
SAME controller resolves them from the ``--pool``-published
``mpi://serving/pool/<model>`` process sets.
"""
import os

if "OTPU_RANK" not in os.environ:
    # standalone loopback: 8 virtual CPU devices, like the test harness
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import ompi_tpu
from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                              PoolSpec, ShardWorker)
from ompi_tpu.serving.worker import toy_token

WORKLOAD = {
    "ten_a": dict(model="m_a", rate_rps=400.0, n_requests=32,
                  prompt_lens=(8, 48), decode_lens=(4, 16),
                  prefixes=3, prefix_len=32),
    "ten_b": dict(model="m_b", rate_rps=250.0, n_requests=20,
                  prompt_lens=(8, 48), decode_lens=(4, 16),
                  prefixes=2, prefix_len=16),
}


def main() -> int:
    world = ompi_tpu.init()
    inproc = "OTPU_RANK" not in os.environ

    if inproc or world.rank == 0:
        threads = []
        if inproc:
            workers = [ShardWorker(world.as_rank(r), router=0)
                       for r in (1, 2, 3, 4)]
            threads = [threading.Thread(target=w.serve, daemon=True)
                       for w in workers]
            for t in threads:
                t.start()
            fleet = FleetController(
                world.as_rank(0),
                pools=[PoolSpec("m_a", [1, 2], max_batch=6,
                                max_batch_tokens=1 << 13),
                       PoolSpec("m_b", [3, 4], max_batch=6,
                                max_batch_tokens=1 << 13)],
                tenants={"ten_a": 2, "ten_b": 1})
        else:
            # pools come from the tpurun --pool psets
            fleet = FleetController(world,
                                    tenants={"ten_a": 2, "ten_b": 1})
        print(f"fleet pools: {fleet.pool_workers()}", flush=True)
        rep = MixedPoissonDriver(WORKLOAD, seed=11).run(
            fleet, max_wall_s=120)
        for req in fleet.completed():      # every token verifies
            assert req.tokens == [toy_token(req.rid, i)
                                  for i in range(req.max_new_tokens)]
        print(f"\n{rep['requests']} requests, "
              f"{rep['tokens_per_s']} tokens/s aggregate")
        print(f"{'tenant':>8}  {'reqs':>5}  {'p50 ms':>8}  "
              f"{'p99 ms':>8}  {'tokens/s':>9}")
        for name, tr in sorted(rep["tenants"].items()):
            print(f"{name:>8}  {tr['requests']:>5}  "
                  f"{tr['p50_ms']:>8}  {tr['p99_ms']:>8}  "
                  f"{tr['tokens_per_s']:>9}")
        print(f"\nprefix cache: {rep['prefix_hits']} verified hits vs "
              f"{rep['prefills']} full prefills "
              f"(hit rate {100.0 * rep['prefix_hit_rate']:.0f}% — "
              "hits prefill only the uncached suffix)")
        st = fleet.stats()
        for pool, entry in sorted(st["pools"].items()):
            print(f"pool {pool}: {entry['workers']} worker(s), "
                  f"prefix {entry['prefix']}")
        fleet.shutdown()
        for t in threads:
            t.join(timeout=10)
        print("FLEET DEMO OK", flush=True)
    else:
        ShardWorker(world, router=0).serve()
    if not inproc:
        ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
