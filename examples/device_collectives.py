"""Device-collective tour: coll/xla, coll/pallas, and the fused GEMM.

Runs in the conductor/device-world model (one process drives every
device rank over the local mesh).  Shows the three device transports a
user can select between:

1. **coll/xla** (default): compiler-scheduled `lax.psum`-family
   collectives — the right default.
2. **coll/pallas** (`--mca coll_pallas_priority 95` or the in-process
   override below): explicit remote-DMA ring schedules, with segmented
   HBM kernels above the VMEM crossover and a pipelined bcast.
3. **ops/pallas_overlap**: the fused collective matmul — per-block
   compute overlapping each ring step's DMA.

Under the axon hook this sees the real TPU; on a dev box run with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
for an 8-virtual-device mesh.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import ompi_tpu  # noqa: E402


def main() -> None:
    world = ompi_tpu.init()
    n = world.size
    print(f"device world: {n} rank(s)")
    rng = np.random.default_rng(0)

    # -- 1. coll/xla (the default owner of the *_array slots) ----------
    x = rng.standard_normal((n, 1024)).astype(np.float32)
    out = np.asarray(world.allreduce_array(x))
    np.testing.assert_allclose(out, x.sum(0), rtol=1e-4, atol=1e-5)
    owner = world.c_coll["allreduce_array"].__self__.__class__.__name__
    print(f"allreduce via {owner}: ok")

    # -- 2. coll/pallas (explicit remote-DMA rings) --------------------
    if n == 1:
        print("SKIPPED: rings need >1 device — run with "
              "JAX_PLATFORMS=cpu "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "for a virtual mesh")
    if n > 1:
        from ompi_tpu.base.var import registry
        from ompi_tpu.runtime import init as rt

        var = registry.lookup("otpu_coll_pallas_priority")
        if var is None:
            raise SystemExit("coll/pallas did not register its vars "
                             "(component excluded?)")
        old = var.value
        var.set(95)       # the MPI_T-style cvar write API
        rt.reset_for_testing()
        try:
            w2 = ompi_tpu.init()
            owner = w2.c_coll["allreduce_array"].__self__ \
                .__class__.__name__
            out = np.asarray(w2.allreduce_array(x))
            np.testing.assert_allclose(out, x.sum(0), rtol=1e-4,
                                       atol=1e-5)
            b = np.asarray(w2.bcast_array(x, root=n - 1))
            np.testing.assert_allclose(
                b, np.broadcast_to(x[n - 1], x.shape), rtol=1e-6)
            print(f"allreduce + pipelined bcast via {owner}: ok")
        finally:
            var.set(old)
            rt.reset_for_testing()
            ompi_tpu.init()

    # -- 3. the fused collective matmul --------------------------------
    if n > 1:
        import jax
        from jax.sharding import Mesh

        from ompi_tpu.ops import pallas_overlap as po

        devs = jax.devices()[:n]
        mesh = Mesh(np.array(devs), ("x",))
        M, K, N = 64, 16 * n, 32
        a = rng.standard_normal((n, M, K // n)).astype(np.float32)
        bb = rng.standard_normal((n, K // n, N)).astype(np.float32)
        interp = not all(getattr(d, "platform", "") == "tpu"
                         for d in devs)
        y = np.asarray(po.matmul_allreduce(
            jax.device_put(a), jax.device_put(bb), mesh, "x",
            interpret=interp))
        np.testing.assert_allclose(
            y, sum(a[i] @ bb[i] for i in range(n)), rtol=1e-3, atol=1e-3)
        print("fused matmul+allreduce (compute overlaps the ring DMA): ok")

    # -- 4. duplex + torus schedules ------------------------------------
    if n >= 4 and n % 2 == 0:
        import jax
        from jax.sharding import Mesh

        from ompi_tpu.ops import pallas_collectives as pc

        devs = jax.devices()[:n]
        interp = not all(getattr(d, "platform", "") == "tpu"
                         for d in devs)
        mesh1 = Mesh(np.array(devs), ("x",))
        g = rng.standard_normal((n, 256)).astype(np.float32)
        y = np.asarray(pc.all_gather(jax.device_put(g), mesh1, "x",
                                     interpret=interp, variant="bidi"))
        np.testing.assert_allclose(y, g, rtol=1e-6)
        print("bidirectional all-gather (duplex ICI, ceil((n-1)/2) "
              "steps): ok")
        mesh2 = Mesh(np.array(devs).reshape(2, n // 2), ("x", "y"))
        x2 = rng.standard_normal((n, n, 128)).astype(np.float32)
        r = np.asarray(pc.reduce_scatter_torus(jax.device_put(x2),
                                               mesh2,
                                               interpret=interp))
        np.testing.assert_allclose(r, x2.sum(0), rtol=1e-4, atol=1e-5)
        a2 = np.asarray(pc.all_gather_torus(jax.device_put(g), mesh2,
                                            interpret=interp))
        np.testing.assert_allclose(a2, g, rtol=1e-6)
        print("2D-torus reduce-scatter + all-gather (per-dimension "
              "sub-rings): ok")

    ompi_tpu.finalize()
    print("DEVICE COLLECTIVES OK")


if __name__ == "__main__":
    main()
