"""otpu-top demo — watch a live job from outside it.

Self-launching: run this script directly (no tpurun needed) and it

1. starts a 3-rank tpurun job on a fixed coord port with the telemetry
   sampler on (``--mca otpu_telemetry_interval_ms 150``) and a
   rank-scoped chaos delay so rank 2 is a designed straggler,
2. attaches ``otpu_top`` to the running job and prints a few live
   per-rank tables (msg/s, bytes/s, allreduce p50/p99, queue depths,
   chaos fault totals, stale flags),
3. after the job ends, runs ``otpu_analyze`` over the merged timeline
   and prints the straggler/skew report — which names rank 2.

Inside a job you can instead attach by hand::

    python -m ompi_tpu.tools.otpu_top --coord 127.0.0.1:PORT --watch
"""
import os
import socket
import subprocess
import sys
import tempfile
import time


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    from ompi_tpu.tools import otpu_analyze, otpu_top

    port = _free_port()
    tdir = tempfile.mkdtemp(prefix="otpu-top-demo-")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_SECS="5.0")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    worker = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "telemetry_worker.py")
    job = subprocess.Popen(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
         "--coord-port", str(port),
         "--mca", "otpu_telemetry_interval_ms", "150",
         "--mca", "otpu_chaos_spec", "delay:ms=5,p=1,rank=2,site=step",
         "--mca", "otpu_trace_enable", "1",
         "--mca", "otpu_trace_dir", tdir,
         sys.executable, worker],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    print(f"job launched (coord 127.0.0.1:{port}); attaching otpu_top…")
    time.sleep(1.5)                       # let the samplers warm up
    for _ in range(3):
        otpu_top.main(["--coord", f"127.0.0.1:{port}"])
        print()
        time.sleep(0.8)
    job.wait(timeout=120)
    merged = os.path.join(tdir, "trace_merged.json")
    if os.path.exists(merged):
        print("job ended; otpu_analyze over the merged timeline:")
        otpu_analyze.main([merged])
    return 0


if __name__ == "__main__":
    sys.exit(main())
