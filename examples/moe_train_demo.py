"""Expert-parallel MoE training demo — with a deliberately hot expert.

    python examples/moe_train_demo.py

Self-launching: re-execs itself under ``tpurun -n 3`` with tracing on
and a gate skewed toward expert 5 (``hot_expert=5, hot_boost=0.8``),
which homes on rank 2 of the 3-way expert partition.  Each step the
ranks gate their local tokens with the shared deterministic plan,
dispatch int8-quantizable payload rows through the ragged
``alltoallv``, apply the owned experts (paced so received load is
wall-clock), and combine through the ragged ``allgatherv``.

Afterwards the launcher feeds the merged trace to ``otpu_analyze
--critical-path`` and prints the load-imbalance report: the per-expert
token loads from the gating plan, the drop count reconciled against
the capacity factor, and the critical-path attribution — which should
blame rank 2 (the hot expert's home) for nearly every step.
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CONF = {"steps": 10, "n_experts": 6, "expert_dim": 8,
        "tokens_per_step": 48, "capacity_factor": 3.0,
        "hot_expert": 5, "hot_boost": 0.8,
        "compute_us_per_token": 2000, "ckpt_every": 50, "seed": 0}


def launch() -> int:
    from ompi_tpu.parallel.moe import partition, plan_step
    from ompi_tpu.tools import otpu_analyze as oa

    work = tempfile.mkdtemp(prefix="otpu-moe-demo-")
    tdir = os.path.join(work, "trace")
    conf = dict(CONF, ckpt_dir=os.path.join(work, "ckpt"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", tdir,
           sys.executable, "-m", "ompi_tpu.parallel.moe",
           json.dumps(conf)]
    print("launching:", " ".join(cmd[2:]), flush=True)
    r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                       timeout=600)
    sys.stdout.write(r.stdout)
    line = next((ln for ln in r.stdout.splitlines() if "MOE " in ln),
                None)
    if r.returncode or line is None:
        sys.stderr.write(r.stderr)
        print("demo FAILED", file=sys.stderr)
        return 1
    rep = json.loads(line.split("MOE ", 1)[1])

    # the imbalance report: per-expert loads from the (shared,
    # deterministic) gating plan, plus the homes from the partition
    E, n = rep["n_experts"], rep["world_size"]
    plan = plan_step(rep["step"] - 1, CONF["tokens_per_step"], E,
                     rep["top_k"], CONF["capacity_factor"],
                     seed=CONF["seed"], hot_expert=CONF["hot_expert"],
                     hot_boost=CONF["hot_boost"])
    homes = {e: next(rk for rk in range(n)
                     if partition(rk, n, E)[0] <= e
                     < partition(rk, n, E)[1]) for e in range(E)}
    print(f"\nfinal-step expert loads (capacity {plan.capacity}, "
          f"max/mean imbalance {plan.imbalance():.2f}):")
    for e, load in enumerate(plan.loads):
        bar = "#" * (load * 40 // max(plan.loads))
        hot = "  <- hot" if e == CONF["hot_expert"] else ""
        print(f"  expert {e} @ rank {homes[e]}: {load:4d} {bar}{hot}")
    print(f"dispatched {rep['dispatched']} tokens, dropped "
          f"{rep['dropped']} (capacity factor "
          f"{CONF['capacity_factor']})")

    events, profiles, meta = oa.load_run([tdir])
    cp = oa.analyze(events, profiles=profiles, meta=meta,
                    critical_path=True)["critical_path"]
    bb = cp["bound_by"]
    print(f"critical path: rank {bb['rank']} bounds "
          f"{bb['fraction']:.0%} of {len(cp['steps'])} steps "
          f"(hot expert {CONF['hot_expert']} homes on rank "
          f"{homes[CONF['hot_expert']]})")
    ok = bb["rank"] == homes[CONF["hot_expert"]]
    print("hot-expert rank blamed:", "YES" if ok else "NO")
    print(f"merged timeline: {os.path.join(tdir, 'trace_merged.json')}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(launch())
