"""Continuous-batching serving demo — the loopback stack end to end.

    python examples/serving_demo.py            # in-process loopback
    python -m ompi_tpu.tools.tpurun -n 3 python examples/serving_demo.py

In-process, the conductor model hosts every rank (``Comm.as_rank``
views) with the two workers running their serve loops on threads:
rank 0 routes, rank 1 prefills, rank 2 decodes — each finished
sequence's KV block travels prefill → decode over an MPI-4 partitioned
slab (one ``Pready`` per sequence, aggregated tail flush), and a
Poisson open-loop driver reports p50/p99 request latency out of the
otpu-trace log2 histograms plus decoded tokens/sec.

Under tpurun the SAME code serves across real processes; add
``--router-ranks 0 --worker-ranks 1,2`` to place roles by pset instead
of the default lowest-rank-routes split.
"""
import os

if "OTPU_RANK" not in os.environ:
    # standalone loopback: 8 virtual CPU devices, like the test harness
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import ompi_tpu
from ompi_tpu.serving import ContinuousBatchScheduler, Router, ShardWorker
from ompi_tpu.serving.driver import PoissonDriver
from ompi_tpu.serving.worker import toy_token


def main() -> int:
    world = ompi_tpu.init()
    inproc = "OTPU_RANK" not in os.environ

    if inproc or world.rank == 0:
        router_comm = world.as_rank(0) if inproc else world
        threads = []
        if inproc:
            pre = ShardWorker(world.as_rank(1), router=0, role="prefill",
                              peer=2, slots=8, kv_elems=128)
            dec = ShardWorker(world.as_rank(2), router=0, role="decode",
                              peer=1, slots=8, kv_elems=128,
                              kv_partitions=16)   # mismatched counts: OK
            threads = [threading.Thread(target=w.serve, daemon=True)
                       for w in (pre, dec)]
            for t in threads:
                t.start()
        router = Router(
            router_comm,
            scheduler=ContinuousBatchScheduler(max_batch=8,
                                               max_batch_tokens=1 << 13,
                                               slots=8),
            # in-process the conductor world has 8 ranks but only ranks
            # 1/2 run worker loops — the table must say so explicitly
            workers=[1, 2] if inproc else None,
            stages=True, decode_chunk=4, kv_elems=128)
        report = PoissonDriver(rate_rps=400.0, n_requests=32,
                               prompt_lens=(8, 48), decode_lens=(4, 16),
                               seed=7).run(router, max_wall_s=120)
        router.shutdown()
        for t in threads:
            t.join(timeout=10)
        for req in router.completed():       # bit-exact decode check
            assert req.tokens == [toy_token(req.rid, i)
                                  for i in range(req.max_new_tokens)]
        print("serving report:")
        for k, v in report.items():
            print(f"  {k:>14}: {v}")
    elif world.rank == 1:
        ShardWorker(world, router=0, role="prefill", peer=2,
                    slots=8, kv_elems=128).serve()
    elif world.rank == 2:
        ShardWorker(world, router=0, role="decode", peer=1,
                    slots=8, kv_elems=128, kv_partitions=16).serve()
    else:
        ShardWorker(world, router=0).serve()  # extra ranks: colocated
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
