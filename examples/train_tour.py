"""Flagship training-step tour: every parallel-layer knob, one run each.

Runs the composed dp/pp/sp/tp training step on 8 virtual CPU devices
(or real chips when present) under each configuration the framework
exposes, printing the one-step loss so the effect of each knob is
visible:

  baseline   f32, dense attention, store-all activations, allreduce dp
  causal     autoregressive masking at global sequence positions
  remat      per-block rematerialization (jax.checkpoint)
  bf16       bfloat16 compute precision (f32 master storage + loss)
  zero1      ZeRO-1: reduce-scattered grads + dp-sharded momentum
  the works  all of the above composed

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
         python examples/train_tour.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if os.environ.get("OTPU_TOUR_EXECED") != "1":
    # the platform must be pinned in the BOOT environment: a site boot
    # hook may not only ignore in-process pins but also WRITE its own
    # JAX_PLATFORMS into os.environ, so an unset-check cannot detect
    # the user's intent — re-exec once with an explicit marker.
    # OTPU_TOUR_PLATFORM=tpu runs the tour on real chips.
    env = dict(os.environ, OTPU_TOUR_EXECED="1",
               JAX_PLATFORMS=os.environ.get("OTPU_TOUR_PLATFORM",
                                            "cpu"))
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    os.execvpe(sys.executable, [sys.executable,
                                os.path.abspath(__file__)], env)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def main() -> None:
    import jax

    from ompi_tpu.base.jaxenv import apply_platform_env

    apply_platform_env()   # explicit JAX_PLATFORMS beats the boot hook
    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel.dryrun import parse_spec, run_training_step

    devs = jax.devices()[:8]
    spec = parse_spec("dp=2,pp=2,sp=2,tp=1")
    knobs = {
        "otpu_parallel_causal": False,
        "otpu_parallel_remat": False,
        "otpu_parallel_compute_dtype": "float32",
        "otpu_parallel_zero1": False,
        "otpu_parallel_momentum": 0.0,
    }
    saved = {k: registry.lookup(k).value for k in knobs}

    def run(tag, **over):
        for k, dv in knobs.items():
            registry.lookup(k).set(over.get(k, dv))
        loss = run_training_step(devs, spec)
        print(f"{tag:10s} loss {float(loss):10.4f}")

    try:
        run("baseline")
        run("causal", otpu_parallel_causal=True)
        run("remat", otpu_parallel_remat=True)
        run("bf16", otpu_parallel_compute_dtype="bfloat16")
        run("zero1", otpu_parallel_zero1=True,
            otpu_parallel_momentum=0.9)
        run("the works", otpu_parallel_causal=True,
            otpu_parallel_remat=True,
            otpu_parallel_compute_dtype="bfloat16",
            otpu_parallel_zero1=True, otpu_parallel_momentum=0.9)
    finally:
        for k, v in saved.items():
            registry.lookup(k).set(v)
    print("TRAIN TOUR OK")


if __name__ == "__main__":
    main()
