"""PGAS ring over the OpenSHMEM-style layer: each PE writes a token into
its right neighbor's symmetric slot, then the reduction closes the loop.

Run:  python -m ompi_tpu.tools.tpurun -n 4 python examples/pgas_ring.py
"""
import numpy as np

import ompi_tpu.shmem as shmem

shmem.init()
me, n = shmem.my_pe(), shmem.n_pes()

slot = shmem.array(1, np.int64)
slot.local[0] = -1
shmem.barrier_all()

# put my id into my right neighbor's slot
shmem.p(slot, me, (me + 1) % n)
shmem.barrier_all()

left = (me - 1) % n
assert slot.local[0] == left, (me, slot.local)

# atomic ring accounting on PE 0
counter = shmem.array(1, np.int64)
counter.local[0] = 0
shmem.barrier_all()
shmem.atomic_add(counter, me + 1, 0)
shmem.barrier_all()
if me == 0:
    total = counter.local[0]
    assert total == n * (n + 1) // 2, total
    print(f"pgas ring OK: {n} PEs, counter {total}")
shmem.barrier_all()
