"""otpu-prof demo — where does a message's latency actually go?

Self-launching: run this script directly (no tpurun needed) and it

1. runs a 3-rank loopback allreduce job with the per-message stage
   clocks and the sampling profiler armed (``--mca otpu_profile_stages
   1 --mca otpu_profile_interval_ms 10``), collectives routed over the
   pml/btl datapath the clocks instrument,
2. runs ``otpu_analyze`` over the trace directory and prints the
   per-rank host-overhead table: the per-message
   pack/queue/wire/parse/deliver breakdown, the exposed-host fraction,
   and the profiler's phase/GIL estimates,
3. demonstrates the perf-history plane: two ``bench.py --history``-style
   runs into a temp BENCH_HISTORY.jsonl with an injected slowdown on
   the second, then ``otpu_perf --diff`` flagging the regression
   (nonzero exit).

Inside a real job the same data is produced by::

    tpurun -n N --mca otpu_profile_stages 1 ... app.py
    python -m ompi_tpu.tools.otpu_analyze <otpu_trace_dir>
    python bench.py --history && python -m ompi_tpu.tools.otpu_perf --diff
"""
import json
import os
import subprocess
import sys
import tempfile
import time


def main() -> int:
    from ompi_tpu.tools import otpu_analyze, otpu_perf

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "telemetry_worker.py")
    tdir = tempfile.mkdtemp(prefix="otpu-prof-demo-")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_ITERS="30")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)

    print("== 1. 3-rank loopback allreduce job, stage clocks armed ==")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
         "--mca", "otpu_trace_enable", "1",
         "--mca", "otpu_trace_dir", tdir,
         "--mca", "otpu_profile_stages", "1",
         "--mca", "otpu_profile_interval_ms", "10",
         "--mca", "otpu_coll_sm_coll_priority", "0",
         sys.executable, worker],
        env=env, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        print(r.stdout + r.stderr)
        return 1

    print("== 2. per-message breakdown (otpu_analyze) ==")
    otpu_analyze.main([tdir])

    print()
    print("== 3. perf-history plane (otpu_perf --diff) ==")
    hist = os.path.join(tdir, "BENCH_HISTORY.jsonl")
    with open(hist, "w") as f:
        t = time.time()
        for run, lat in (("clean", 910.0), ("slow", 5410.0)):
            for key, v in (("allreduce_4096b_n2", lat),
                           ("pingpong_4096b_n2", lat * 1.3)):
                f.write(json.dumps(
                    {"v": 1, "kind": "bench", "run": run, "t": t,
                     "topology": "host_sm_n2", "key": key,
                     "lat_us": v, "k": 6}) + "\n")
            t += 1.0
    rc = otpu_perf.main([hist, "--diff"])
    print(f"otpu_perf --diff exit code: {rc} (nonzero = regression "
          "gate trips; in a clean tree run `python bench.py --history` "
          "then `python -m ompi_tpu.tools.otpu_perf --diff`)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
