"""otpu_info introspection tool + monitoring interposition components."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent


def _run_info(*args, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_info", *args],
        capture_output=True, text=True, timeout=60, cwd=REPO, env=env)


def test_info_all_lists_components_and_vars():
    r = _run_info("--all")
    assert r.returncode == 0, r.stderr
    # frameworks + components with priorities
    for needle in ("mca coll: tuned (priority 30)",
                   "mca coll: xla (priority 90)",
                   "mca btl: sm",
                   "mca pml: ob1",
                   "mca io: ompio",
                   "mca coll: han (priority 40)"):
        assert needle in r.stdout, needle
    # vars with values and sources
    assert "otpu_coll_tuned_allreduce_algorithm" in r.stdout
    assert "source default" in r.stdout


def test_info_param_filter_and_source_tracking():
    r = _run_info("--param", "coll", "tuned",
                  env_extra={"OTPU_MCA_coll_tuned_priority": "77"})
    assert r.returncode == 0, r.stderr
    assert "otpu_coll_tuned_priority: 77" in r.stdout.replace("  ", " ") \
        or "77 (type int, source env" in r.stdout
    # filtered: no btl vars in coll/tuned output
    assert "otpu_btl_sm" not in r.stdout


def test_info_parsable():
    r = _run_info("--all", "--parsable")
    assert r.returncode == 0
    assert any(line.startswith("mca coll:") for line in r.stdout.splitlines())


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_monitoring_p2p_matrix_and_coll_counters(tmp_path):
    """pml/coll monitoring records per-peer byte matrices the way the
    reference's common/monitoring does (common_monitoring.h:48-91)."""
    script = tmp_path / "mon.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.runtime import monitoring
        w = ompi_tpu.init()
        r = w.rank
        assert monitoring.enabled()
        # directed traffic: rank 0 -> 1 (two msgs), 1 -> 0 (one msg)
        if r == 0:
            w.send(np.zeros(100, np.uint8), 1, tag=1)
            w.send(np.zeros(28, np.uint8), 1, tag=2)
            buf = np.zeros(4, np.uint8)
            w.recv(buf, 1, tag=3)
        else:
            b1 = np.zeros(100, np.uint8); w.recv(b1, 0, tag=1)
            b2 = np.zeros(28, np.uint8); w.recv(b2, 0, tag=2)
            w.send(np.zeros(4, np.uint8), 0, tag=3)
        w.allreduce(np.ones(16, np.float32))
        msgs, byts = monitoring.p2p_matrix(2)
        if r == 0:
            assert msgs[0, 1] >= 2 and byts[0, 1] >= 128, (msgs, byts)
        else:
            assert msgs[1, 0] >= 1 and byts[1, 0] >= 4, (msgs, byts)
        colls = monitoring.coll_counters()
        assert colls.get("allreduce", (0, 0))[0] == 1, colls
        assert colls["allreduce"][1] == 64   # 16 x float32
        print(f"monitoring OK rank {r}")
    """))
    r = _tpurun(2, [sys.executable, str(script)],
                extra=("--mca", "monitoring_enable", "1"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("monitoring OK") == 2
    # the otpu-top satellite: each rank publishes its matrices into the
    # coord KV at finalize and tpurun prints ONE job-wide matrix — both
    # directions summed into the same table, coll totals across ranks
    assert "job-wide p2p matrix" in r.stderr, r.stderr
    assert "0 -> 1:" in r.stderr and "1 -> 0:" in r.stderr, r.stderr
    assert "coll allreduce: 2 calls" in r.stderr, r.stderr


def test_monitoring_disabled_by_default(tmp_path):
    script = tmp_path / "nomon.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.runtime import monitoring
        w = ompi_tpu.init()
        assert not monitoring.enabled()
        w.allreduce(np.ones(1))
        assert monitoring.coll_counters() == {}
        print("nomon OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("nomon OK") == 2


def test_info_telemetry_lists_schema_and_vars():
    """--telemetry enumerates the declared sample schema, the sampler
    vars, and the flight-recorder settings (registry-enumerated, also
    under --all/--parsable)."""
    from ompi_tpu.runtime import telemetry

    r = _run_info("--telemetry")
    assert r.returncode == 0, r.stderr
    for key in telemetry.SCHEMA:
        assert f"telemetry key {key}:" in r.stdout, key
    for var in ("otpu_telemetry_interval_ms", "otpu_telemetry_jitter",
                "otpu_flight_enable", "otpu_flight_dir",
                "otpu_flight_events"):
        assert var in r.stdout, var
    # under --all and --parsable too
    r_all = _run_info("--all", "--parsable")
    assert r_all.returncode == 0
    assert "telemetry key spc:" in r_all.stdout
    assert "telemetry var otpu_flight_dir:" in r_all.stdout


def test_info_trace_lists_categories_and_vars():
    """--trace enumerates the declared span categories, the flow-key
    categories, and the ring/export/flow vars (registry-enumerated,
    also under --all/--parsable)."""
    from ompi_tpu.runtime import trace

    r = _run_info("--trace")
    assert r.returncode == 0, r.stderr
    for cat in trace.CATEGORIES:
        assert f"trace category {cat}:" in r.stdout, cat
    for fcat in trace.FLOW_CATEGORIES:
        assert f"trace flow key {fcat}:" in r.stdout, fcat
    for var in ("otpu_trace_enable", "otpu_trace_dir",
                "otpu_trace_buffer_events", "otpu_trace_flow"):
        assert var in r.stdout, var
    # under --all and --parsable too
    r_all = _run_info("--all", "--parsable")
    assert r_all.returncode == 0
    assert "trace category pml:" in r_all.stdout
    assert "trace flow key pml_msg:" in r_all.stdout
    assert "trace var otpu_trace_flow:" in r_all.stdout


def test_topo_explicit_only():
    """--all must NOT boot the accelerator runtime for topology; --topo
    opts in (regression guard for the lazy-init guarantee)."""
    r_all = _run_info("--all")
    assert "topo: host" not in r_all.stdout   # "mca topo:" rows still list
    r_topo = _run_info("--topo")
    assert "topo: host" in r_topo.stdout
