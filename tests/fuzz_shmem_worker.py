"""Randomized SHMEM shake: seed-deterministic plan of puts/gets/
atomics/collectives over a symmetric array, checked against a
replicated numpy model.  Epochs separate with barrier_all (puts are
remotely visible after the barrier's quiet)."""
import os

import numpy as np

import ompi_tpu.shmem as sh

seed = int(os.environ["SF_SEED"])
epochs = int(os.environ.get("SF_EPOCHS", "10"))
sh.init()
me, n = sh.my_pe(), sh.n_pes()
SLOTS = 4 * n
sym = sh.array(SLOTS, np.float64)
sym.local[:] = 0.0
model = np.zeros((n, SLOTS))
rng = np.random.default_rng(seed)
sh.barrier_all()

for ep in range(epochs):
    plan = []
    for origin in range(n):
        kind = rng.choice(["put", "add", "inc", "set", "iput"])
        target = int(rng.integers(0, n))
        base = origin * 4            # disjoint per-origin region
        vals = rng.standard_normal(4)
        plan.append((origin, str(kind), target, base, vals))
    for origin, kind, target, base, vals in plan:
        if origin != me:
            continue
        if kind == "put":
            sh.put(sym, vals.copy(), target, index=base)
        elif kind == "add":
            sh.atomic_add(sym, float(vals[0]), target, index=base)
        elif kind == "inc":
            sh.atomic_inc(sym, target, index=base)
        elif kind == "set":
            sh.atomic_set(sym, float(vals[1]), target, index=base + 1)
        elif kind == "iput":
            # strided: every other slot of my region
            sh.iput(sym, vals[:4].copy(), 2, 2, 2, target, index=base)
    for origin, kind, target, base, vals in plan:
        if kind == "put":
            model[target, base:base + 4] = vals
        elif kind == "add":
            model[target, base] += vals[0]
        elif kind == "inc":
            model[target, base] += 1
        elif kind == "set":
            model[target, base + 1] = vals[1]
        elif kind == "iput":
            model[target, base] = vals[0]
            model[target, base + 2] = vals[2]
    sh.barrier_all()
    np.testing.assert_allclose(np.asarray(sym.local), model[me],
                               atol=1e-9)
    sh.barrier_all()          # epoch separation (see RMA fuzz)

# collectives against the model state (sum_to_all reduces IN PLACE)
sh.sum_to_all(sym)
np.testing.assert_allclose(np.asarray(sym.local), model.sum(0),
                           atol=1e-9)
if me == 0:
    print("shmem fuzz ok", flush=True)
sh.finalize()
