"""MCA framework/component selection tests (SURVEY.md §2.1 MCA base)."""
import pytest

from ompi_tpu.base.mca import Component, Framework
from ompi_tpu.base.var import registry


class _C(Component):
    def __init__(self, name, priority, openable=True, queryable=True):
        self.name = name
        self.priority = priority
        self._openable = openable
        self._queryable = queryable
        super().__init__()
        self.closed = False

    def open(self):
        return self._openable

    def init_query(self):
        return self if self._queryable else None

    def close(self):
        self.closed = True


@pytest.fixture
def fw(fresh_registry):
    f = Framework("tfw" + str(id(object())))  # unique name per test
    yield f
    f.close()


def test_priority_selection(fw):
    lo = fw.register(_C("lo", 10))
    hi = fw.register(_C("hi", 50))
    assert fw.select() is hi
    assert fw.select_all() == [hi, lo]


def test_failed_open_disqualifies(fw):
    fw.register(_C("bad", 90, openable=False))
    good = fw.register(_C("good", 10))
    assert fw.select() is good


def test_query_none_disqualifies(fw):
    fw.register(_C("shy", 90, queryable=False))
    good = fw.register(_C("good", 10))
    assert fw.select() is good


def test_include_list(fresh_registry):
    f = Framework("tfwinc")
    a, b = f.register(_C("a", 10)), f.register(_C("b", 90))
    f.select_var.set("a")
    assert f.select() is a
    f.close()


def test_exclude_list(fresh_registry):
    f = Framework("tfwexc")
    a, b = f.register(_C("a", 10)), f.register(_C("b", 90))
    f.select_var.set("^b")
    assert f.select() is a
    f.close()


def test_mixed_include_exclude_rejected(fresh_registry):
    f = Framework("tfwmix")
    f.register(_C("a", 10))
    f.select_var.set("a,^b")
    with pytest.raises(ValueError):
        f.open()


def test_close_calls_components(fresh_registry):
    f = Framework("tfwcls")
    c = f.register(_C("a", 10))
    f.select()
    f.close()
    assert c.closed and not f.opened


def test_verbose_var_registered(fresh_registry):
    Framework("tfwverb")
    assert registry.lookup("otpu_tfwverb_base_verbose") is not None
