"""ULFM fault tolerance: failure state, revoke/shrink/agree, detector,
recovery-mode launcher (SURVEY.md §3.5/§5.3)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.ft import state as ft_state
from ompi_tpu.runtime import init as rt

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def world():
    from ompi_tpu.api.errhandler import ERRORS_RETURN

    rt.reset_for_testing()
    w = ompi_tpu.init()
    w.set_errhandler(ERRORS_RETURN)  # ULFM apps opt out of abort-on-error
    yield w
    rt.reset_for_testing()


class TestFailureState:
    def test_mark_and_listeners(self):
        ft_state.reset_for_testing()
        seen = []
        ft_state.on_failure(seen.append)
        ft_state.mark_failed(3)
        ft_state.mark_failed(3)  # dedup
        assert ft_state.is_failed(3)
        assert ft_state.failed_ranks() == frozenset({3})
        assert seen == [3]
        ft_state.reset_for_testing()

    def test_revoked_cids_epoch_scoped(self):
        ft_state.reset_for_testing()
        ft_state.mark_revoked(5, epoch=0)
        assert ft_state.is_comm_revoked(5, 0)
        assert not ft_state.is_comm_revoked(5, 1)  # reused CID, new epoch
        ft_state.reset_for_testing()


class TestDeviceWorldFt:
    def test_send_to_failed_rank_raises(self, world):
        from ompi_tpu.api.errors import ProcFailedError

        if world.size < 2:
            pytest.skip("needs >= 2 ranks in device world")
        ft_state.mark_failed(world.world_rank(1))
        with pytest.raises(ProcFailedError):
            world.as_rank(0).send(np.zeros(1), dest=1)
        assert world.get_failed().size == 1

    def test_revoke_then_ops_raise(self, world):
        from ompi_tpu.api.errors import RevokedError

        dup = world.dup()
        dup.revoke()
        assert dup.is_revoked()
        # a facade of the same comm (another "rank") sees the revocation
        # through the global FT state even though the flag was set on dup
        other = dup.as_rank(min(1, world.size - 1))
        other.revoked = False
        with pytest.raises(RevokedError):
            other.barrier()

    def test_shrink_excludes_failed(self, world):
        if world.size < 2:
            pytest.skip("needs >= 2 ranks")
        dead = world.world_rank(world.size - 1)
        ft_state.mark_failed(dead)
        s = world.shrink()
        assert s.size == world.size - 1
        assert dead not in s.group.world_ranks
        assert s.epoch == world.epoch + 1
        # shrunken comm is fully operational (conductor model: leading axis
        # indexes ranks)
        out = s.allreduce(np.ones((s.size, 4)))
        assert out.tolist() == [float(s.size)] * 4

    def test_ack_failed(self, world):
        if world.size < 2:
            pytest.skip("needs >= 2 ranks")
        ft_state.mark_failed(world.world_rank(1))
        assert world.ack_failed() == 1


def _tpurun(n, script, timeout=180, recovery=False, mca=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n)]
    if recovery:
        cmd.append("--enable-recovery")
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


class TestMultiprocessFt:
    def test_launcher_detects_death_survivors_shrink(self, tmp_path):
        script = tmp_path / "ft.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            import numpy as np
            import ompi_tpu
            from ompi_tpu.ft import state as ft_state

            w = ompi_tpu.init()
            w.barrier()
            if w.rank == 1:
                os._exit(13)  # sudden death, no cleanup
            deadline = time.time() + 60
            while not ft_state.is_failed(1):
                if time.time() > deadline:
                    sys.exit("failure of rank 1 never detected")
                time.sleep(0.05)
            assert w.get_failed().size == 1
            s = w.shrink()
            assert s.size == 3, s.size
            assert s.epoch == 1
            out = s.allreduce(np.array([float(s.rank + 1)]))
            assert out[0] == 6.0, out
            if s.rank == 0:
                print("FT SHRINK OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script, recovery=True)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "FT SHRINK OK" in r.stdout

    def test_agree_with_failure_and_ack(self, tmp_path):
        script = tmp_path / "agree.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            import ompi_tpu
            from ompi_tpu.api.errors import ProcFailedError
            from ompi_tpu.api.errhandler import ERRORS_RETURN
            from ompi_tpu.ft import state as ft_state

            w = ompi_tpu.init()
            w.set_errhandler(ERRORS_RETURN)
            # first: agreement with everyone alive ANDs the flags
            got = w.agree(0b1110 if w.rank else 0b0111)
            assert got == 0b0110, got
            w.barrier()
            if w.rank == 2:
                os._exit(7)
            deadline = time.time() + 60
            while not ft_state.is_failed(2):
                if time.time() > deadline:
                    sys.exit("no detection")
                time.sleep(0.05)
            # unacknowledged failure -> uniform ProcFailedError, flag agreed
            try:
                w.agree(0b11)
                sys.exit("expected ProcFailedError")
            except ProcFailedError as e:
                assert e.flag == 0b11, e.flag
            w.ack_failed()
            assert w.agree(0b11) == 0b11
            if w.rank == 0:
                print("FT AGREE OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(3, script, recovery=True)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "FT AGREE OK" in r.stdout

    def test_revoke_propagates_between_processes(self, tmp_path):
        script = tmp_path / "revoke.py"
        script.write_text(textwrap.dedent("""
            import sys, time
            import ompi_tpu
            from ompi_tpu.api.errors import RevokedError
            from ompi_tpu.api.errhandler import ERRORS_RETURN

            w = ompi_tpu.init()
            w.set_errhandler(ERRORS_RETURN)
            d = w.dup()
            if w.rank == 0:
                d.revoke()
            deadline = time.time() + 60
            while not d.is_revoked():
                if time.time() > deadline:
                    sys.exit("revocation never arrived")
                time.sleep(0.05)
            try:
                d.barrier()
                sys.exit("expected RevokedError")
            except RevokedError:
                pass
            w.barrier()  # parent comm unaffected
            if w.rank == 0:
                print("FT REVOKE OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(3, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "FT REVOKE OK" in r.stdout

    def test_heartbeat_detector_finds_silent_peer(self, tmp_path):
        script = tmp_path / "hb.py"
        script.write_text(textwrap.dedent("""
            import sys, time
            import ompi_tpu
            from ompi_tpu.ft import state as ft_state
            from ompi_tpu.ft import propagator

            w = ompi_tpu.init()
            w.barrier()
            if w.rank == 1:
                # simulate a hang: the process stays alive (so the launcher
                # sees nothing) but its heartbeats stop.  Halt the emitter
                # thread WITHOUT the clean-finalize tombstone that stop()
                # would write -- a hang leaves no tombstone.
                propagator._detector._stop.set()
                # stay silent past detector_timeout (1.5s) + detection slack
                time.sleep(4)
                sys.exit(0)
            deadline = time.time() + 60
            while not ft_state.is_failed(1):
                if time.time() > deadline:
                    sys.exit("heartbeat detector never fired")
                time.sleep(0.05)
            if w.rank == 0:
                print("FT DETECTOR OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(3, script, recovery=True, timeout=120,
                    mca=[("ft_detector", "true"),
                         ("ft_detector_period", "0.2"),
                         ("ft_detector_timeout", "1.5")])
        assert "FT DETECTOR OK" in r.stdout, r.stdout + r.stderr
        assert r.returncode == 0, r.stdout + r.stderr


class TestCoordFreeAgreement:
    def test_agree_survives_root_death_with_coord_gagged(self, tmp_path):
        """ERA p2p agreement: the tree ROOT (rank 0) dies mid-agreement
        while every survivor's coordination-service KV ops are gagged —
        decisions must ride only the p2p carrier (takeover root gathers
        pledge replies, decides, broadcasts).  The coord stays restricted
        to wire-up, matching ``coll_ftagree_earlyreturning.c``'s
        no-central-arbiter property."""
        script = tmp_path / "rootdeath.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            import ompi_tpu
            from ompi_tpu.ft import state as ft_state

            w = ompi_tpu.init()
            w.barrier()
            if w.rank == 0:
                time.sleep(0.3)
                os._exit(11)   # the agreement tree's root dies
            # gag the shared coord client's KV surface: any decision-path
            # use of the coordination service now fails loudly
            client = w.rte.client
            def _gagged(*a, **k):
                raise AssertionError("agreement touched the coord service")
            client.get = _gagged
            client.put_new = _gagged
            client.delete = _gagged
            got = w.agree(0b1101 if w.rank == 1 else 0b0111)
            assert got == 0b0101, got
            # the agreed failed-set is uniform too: everyone saw rank 0
            deadline = time.time() + 60
            while not ft_state.is_failed(0):
                if time.time() > deadline:
                    sys.exit("root death never detected")
                time.sleep(0.05)
            w.ack_failed()
            got2 = w.agree(0b11)
            assert got2 == 0b11, got2
            print(f"ROOTDEATH OK {w.rank}", flush=True)
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script, recovery=True, timeout=150,
                    mca=[("ft_detector", "true"),
                         ("ft_detector_period", "0.2"),
                         ("ft_detector_timeout", "1.5"),
                         ("ft_detector_startup_grace", "2.0")])
        assert r.stdout.count("ROOTDEATH OK") == 3, r.stdout + r.stderr
        assert r.returncode == 0, r.stdout + r.stderr

    def test_revoke_floods_with_event_bus_down(self, tmp_path):
        """Revocation propagation must not depend on the coordination
        service's event bus: stop the event poller on every rank, revoke,
        and require the p2p flood (``comm_ft_revoke.c`` resilient
        broadcast analog) to deliver it."""
        script = tmp_path / "revflood.py"
        script.write_text(textwrap.dedent("""
            import sys, time
            import ompi_tpu
            from ompi_tpu.api.errors import RevokedError
            from ompi_tpu.api.errhandler import ERRORS_RETURN
            from ompi_tpu.ft import propagator
            from ompi_tpu.runtime.progress import progress

            w = ompi_tpu.init()
            w.set_errhandler(ERRORS_RETURN)
            d = w.dup()
            # kill the event-bus leg everywhere: only the p2p flood remains
            propagator._poller.stop()
            w.barrier()
            if w.rank == 0:
                d.revoke()
            deadline = time.time() + 60
            while not d.is_revoked():
                if time.time() > deadline:
                    sys.exit("revocation never arrived over p2p")
                progress()   # a rank blocked in MPI drives the engine;
                             # the CTL flood rides it
                time.sleep(0.002)
            try:
                d.barrier()
                sys.exit("expected RevokedError")
            except RevokedError:
                pass
            print(f"REVFLOOD OK {w.rank}", flush=True)
            ompi_tpu.finalize()
        """))
        r = _tpurun(3, script)
        assert r.stdout.count("REVFLOOD OK") == 3, r.stdout + r.stderr
        assert r.returncode == 0, r.stdout + r.stderr


class TestMultiFailure:
    def test_detector_survives_double_failure(self, tmp_path):
        """TWO adjacent ranks die; the ring rotates past both and every
        survivor learns both failures (observer rotation,
        ``comm_ft_detector.c`` + the propagator flood)."""
        script = tmp_path / "double.py"
        script.write_text(textwrap.dedent("""
            import os, sys, time
            import ompi_tpu
            from ompi_tpu.ft import state as ft_state

            w = ompi_tpu.init()
            w.barrier()
            if w.rank in (1, 2):
                time.sleep(0.5)
                os._exit(1)          # both die abruptly, no tombstone
            deadline = time.time() + 60
            while not (ft_state.is_failed(1) and ft_state.is_failed(2)):
                if time.time() > deadline:
                    sys.exit("double failure never fully detected")
                time.sleep(0.05)
            print(f"DOUBLE OK {w.rank}", flush=True)
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script, recovery=True, timeout=150,
                    mca=[("ft_detector", "true"),
                         ("ft_detector_period", "0.2"),
                         ("ft_detector_timeout", "1.5"),
                         ("ft_detector_startup_grace", "2.0")])
        assert r.stdout.count("DOUBLE OK") == 2, r.stdout + r.stderr


class TestAgreementAlgorithms:
    def test_alternate_algorithms_agree(self, tmp_path):
        """The non-default agreement algorithms ('tree' = p2p reduce with
        KV-anchored decision, 'kv' = coordinator-decides) stay correct."""
        script = tmp_path / "alg.py"
        script.write_text(textwrap.dedent("""
            import ompi_tpu

            w = ompi_tpu.init()
            got = w.agree(0b1011 if w.rank % 2 else 0b1110)
            assert got == 0b1010, bin(got)
            print(f"ALG OK {w.rank}", flush=True)
            ompi_tpu.finalize()
        """))
        for alg in ("tree", "kv"):
            r = _tpurun(3, script,
                        mca=[("coll_ftagree_algorithm", alg)])
            assert r.stdout.count("ALG OK") == 3, (alg, r.stdout + r.stderr)
            assert r.returncode == 0, (alg, r.stdout + r.stderr)
