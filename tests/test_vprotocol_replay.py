"""vprotocol/pessimist replay — a restarted rank is re-driven from the
message logs to its pre-failure state, then continues live with peers
(``ompi/mca/vprotocol/pessimist`` re-delivery semantics).

Scenario: 3 ranks run a deterministic ring recurrence with full
sender-based logging; rank 1 dies MID-iteration (after its sends, before
its recvs).  A second job replays every rank from the logs: suppressed
sends where delivery is proven by the receiver's log, a live re-send for
the in-flight message the dead rank never received, pinned-source recvs
satisfied from the senders' logged payloads — then the log runs dry and
live execution finishes the remaining iterations.  Final states must
match the failure-free recurrence computed locally.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NITER_TOTAL = 5
DIE_ROUND = 2   # rank 1 dies in round 2 after sending, before receiving

_PROGRAM = """
import os, sys
import numpy as np
import ompi_tpu

niter = int(os.environ["VP_NITER"])
die = os.environ.get("VP_DIE", "") == "1"
w = ompi_tpu.init()
n, r = w.size, w.rank
state = np.full(4, float(r + 1), np.float64)
for it in range(niter):
    req = w.isend(state.copy(), dest=(r + 1) % n, tag=7)
    if die and r == 1 and it == {die_round}:
        os._exit(9)     # mid-iteration: sent but never received
    inbuf = np.empty_like(state)
    w.recv(inbuf, source=(r - 1) % n, tag=7)
    req.wait()
    state = 0.5 * state + 0.5 * inbuf + float(it)
np.save(os.environ["VP_OUT"] + f".{{r}}.npy", state)
print(f"DONE {{r}} " + " ".join(f"{{x:.6f}}" for x in state), flush=True)
ompi_tpu.finalize()
"""


def _run(n, script, env_extra, mca=(), timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           "--enable-recovery"]
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


def _expected(niter, n=3):
    states = [np.full(4, float(r + 1), np.float64) for r in range(n)]
    for it in range(niter):
        prev = [s.copy() for s in states]
        for r in range(n):
            states[r] = 0.5 * prev[r] + 0.5 * prev[(r - 1) % n] + float(it)
    return states


def test_replay_after_midround_death(tmp_path):
    logdir = tmp_path / "logs"
    prog = tmp_path / "prog.py"
    prog.write_text(textwrap.dedent(
        _PROGRAM.format(die_round=DIE_ROUND)))

    # phase A: run up to the crash boundary; rank 1 dies mid-round
    ra = _run(3, prog,
              {"VP_NITER": str(DIE_ROUND + 1), "VP_DIE": "1",
               "VP_OUT": str(tmp_path / "a")},
              mca=[("vprotocol_pessimist_log", str(logdir)),
                   ("vprotocol_pessimist_log_payloads", "1"),
                   ("ft_detector", "true"),
                   ("ft_detector_period", "0.2"),
                   ("ft_detector_timeout", "1.5")])
    assert ra.stdout.count("DONE") == 2, ra.stdout + ra.stderr
    assert not (tmp_path / f"a.1.npy").exists()   # rank 1 really died
    for r in (0, 2):
        assert (tmp_path / f"a.{r}.npy").exists(), ra.stdout + ra.stderr

    # phase B: "respawn" — every rank re-driven from the logs, the dead
    # rank catching the in-flight re-send live, then all finish the
    # remaining rounds live
    rb = _run(3, prog,
              {"VP_NITER": str(NITER_TOTAL), "VP_DIE": "0",
               "VP_OUT": str(tmp_path / "b")},
              mca=[("vprotocol_pessimist_replay", str(logdir))])
    assert rb.returncode == 0, rb.stdout + rb.stderr
    assert rb.stdout.count("DONE") == 3, rb.stdout + rb.stderr

    want = _expected(NITER_TOTAL)
    for r in range(3):
        got = np.load(tmp_path / f"b.{r}.npy")
        np.testing.assert_allclose(got, want[r], rtol=1e-12, err_msg=(
            f"rank {r} state diverged after replay"))


_TWO_COMM_PROGRAM = """
import os, sys
import numpy as np
import ompi_tpu

niter = int(os.environ["VP_NITER"])
die = os.environ.get("VP_DIE", "") == "1"
w = ompi_tpu.init()
d = w.dup()
r = w.rank
peer = 1 - r
state = np.full(4, float(r + 1), np.float64)
for it in range(niter):
    a = 0.5 * state + float(it)        # the w-channel payload
    b = 0.25 * state - float(it)       # the d-channel payload
    if r == 0:
        q1 = w.isend(a, dest=peer, tag=5)
        q2 = d.isend(b, dest=peer, tag=5)
        inA = np.empty_like(state); inB = np.empty_like(state)
        # peer emitted d-then-w: consume w-then-d (cross-channel
        # interleave both directions)
        w.recv(inA, source=peer, tag=5)
        d.recv(inB, source=peer, tag=5)
    else:
        q2 = d.isend(b, dest=peer, tag=5)
        q1 = w.isend(a, dest=peer, tag=5)
        inA = np.empty_like(state); inB = np.empty_like(state)
        # peer emitted w-then-d: consume d-then-w
        d.recv(inB, source=peer, tag=5)
        if die and r == 1 and it == {die_round}:
            os._exit(9)   # w message of this round in flight
        w.recv(inA, source=peer, tag=5)
    q1.wait(); q2.wait()
    # asymmetric in A/B: a swapped pairing corrupts the state
    state = 0.3 * state + 0.6 * inA - 0.2 * inB + float(it)
np.save(os.environ["VP_OUT"] + f".{{r}}.npy", state)
print(f"DONE {{r}}", flush=True)
ompi_tpu.finalize()
"""


def _expected_two_comm(niter, n=2):
    states = [np.full(4, float(r + 1), np.float64) for r in range(n)]
    for it in range(niter):
        prev = [s.copy() for s in states]
        for r in range(n):
            in_a = 0.5 * prev[1 - r] + float(it)
            in_b = 0.25 * prev[1 - r] - float(it)
            states[r] = (0.3 * prev[r] + 0.6 * in_a - 0.2 * in_b
                         + float(it))
    return states


def test_replay_two_comm_interleaved(tmp_path):
    """Event-clock pairing (``vprotocol_pessimist_event.h`` analog):
    concurrent traffic on TWO communicators between the same pair, with
    each side consuming channels in the OPPOSITE order of the peer's
    emission — per-(cid,tag) channel clocks must pair every payload
    exactly; global send-order pairing would swap the A/B payloads and
    corrupt the recurrence.  Rank 1 dies between its two recvs, leaving
    the w-channel message of that round in flight."""
    logdir = tmp_path / "logs"
    prog = tmp_path / "prog2.py"
    prog.write_text(textwrap.dedent(
        _TWO_COMM_PROGRAM.format(die_round=DIE_ROUND)))

    ra = _run(2, prog,
              {"VP_NITER": str(DIE_ROUND + 1), "VP_DIE": "1",
               "VP_OUT": str(tmp_path / "a")},
              mca=[("vprotocol_pessimist_log", str(logdir)),
                   ("vprotocol_pessimist_log_payloads", "1"),
                   ("ft_detector", "true"),
                   ("ft_detector_period", "0.2"),
                   ("ft_detector_timeout", "1.5")])
    assert ra.stdout.count("DONE") == 1, ra.stdout + ra.stderr
    assert not (tmp_path / "a.1.npy").exists()

    rb = _run(2, prog,
              {"VP_NITER": str(NITER_TOTAL), "VP_DIE": "0",
               "VP_OUT": str(tmp_path / "b")},
              mca=[("vprotocol_pessimist_replay", str(logdir))])
    assert rb.returncode == 0, rb.stdout + rb.stderr
    assert rb.stdout.count("DONE") == 2, rb.stdout + rb.stderr

    want = _expected_two_comm(NITER_TOTAL)
    for r in range(2):
        got = np.load(tmp_path / f"b.{r}.npy")
        np.testing.assert_allclose(got, want[r], rtol=1e-12, err_msg=(
            f"rank {r} state diverged after two-comm replay"))


def test_replay_divergence_detected(tmp_path):
    """A re-execution that does not match the log must fail loudly, not
    silently corrupt recovery (envelope verification)."""
    logdir = tmp_path / "logs"
    prog = tmp_path / "prog.py"
    prog.write_text(textwrap.dedent(
        _PROGRAM.format(die_round=DIE_ROUND)))
    ra = _run(3, prog,
              {"VP_NITER": "2", "VP_DIE": "0",
               "VP_OUT": str(tmp_path / "a")},
              mca=[("vprotocol_pessimist_log", str(logdir)),
                   ("vprotocol_pessimist_log_payloads", "1")])
    assert ra.returncode == 0, ra.stdout + ra.stderr

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        try:
            # logged program used tag=7; this diverges
            w.send(np.zeros(4), dest=(w.rank + 1) % w.size, tag=99)
        except Exception as e:
            assert type(e).__name__ == "ReplayDivergence", e
            print(f"DIVERGED {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    rb = _run(3, bad, {"VP_OUT": str(tmp_path / "x")},
              mca=[("vprotocol_pessimist_replay", str(logdir))])
    assert rb.stdout.count("DIVERGED") == 3, rb.stdout + rb.stderr
