"""AOT compile-contract test: every coll/pallas kernel must lower
through the real Mosaic TPU compiler (offline, against a v5e-8
topology) — the CI teeth behind PALLAS_AOT.json.

The interpreter suite (test_pallas_coll.py) proves the *schedules*;
this proves the *lowering*: semaphore allocation, VMEM/HBM placement,
collective_id barrier plumbing, (rows, 128) tiling.  A kernel that
fails here would fail on a live pod — the compile-time analog of the
reference's hardware-proven transport contract
(``/root/reference/opal/mca/btl/btl.h:878-1078``).
"""
import json
import os
import subprocess
import sys
import tempfile

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("OTPU_SKIP_AOT", "") not in ("", "0"),
    reason="AOT gate disabled by OTPU_SKIP_AOT")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scrubbed_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or REPO
    return env


def _run_aot_subprocess() -> dict:
    """Run the AOT gate in bounded subprocesses with a scrubbed env,
    like bench.py's ``_pallas_aot_gate``: libtpu's PJRT plugin init can
    hang on a site boot hook's pinned accelerator tunnel
    (answer-then-stall mode), and an in-process call then stalls the
    WHOLE tier-1 suite past its timeout — every test file sorting after
    this one never runs.  A cheap probe pays for the stall detection
    (the hang point is topology construction, not compilation), so a
    dead tunnel costs ~90s and a skip; with a live plugin the full gate
    runs with a compile-sized budget.  A real lowering failure still
    fails loudly from the result file."""
    env = _scrubbed_env()
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "from ompi_tpu.tools.pallas_aot import build_meshes; "
             "build_meshes()"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
    except subprocess.TimeoutExpired:
        pytest.skip("pallas AOT gate stalled building the offline "
                    "topology (accelerator plugin unresponsive) — "
                    "compile contract not measurable")
    if probe.returncode:
        pytest.skip("offline AOT topology unavailable: "
                    f"{probe.stderr.strip().splitlines()[-1:]!r}")
    out = os.path.join(tempfile.mkdtemp(prefix="otpu_aot_"),
                       "pallas_aot.json")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.tools.pallas_aot",
             "--out", out],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=240)
    except subprocess.TimeoutExpired:
        # on a healthy host the 31 compiles finish well inside this; a
        # degraded plugin can also stall MID-compile, and tier-1's
        # overall budget cannot absorb an unbounded wait
        pytest.skip("pallas AOT gate exceeded its tier-1 budget "
                    "(degraded accelerator plugin) — compile contract "
                    "not measurable")
    if proc.returncode not in (0, 1) or not os.path.exists(out):
        raise RuntimeError(
            f"pallas_aot gate crashed (rc={proc.returncode}):\n"
            f"{proc.stderr[-1500:]}")
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
def test_all_kernels_aot_compile():
    try:
        import libtpu  # noqa: F401
    except ImportError:
        pytest.skip("libtpu not installed — no offline Mosaic compiler")

    res = _run_aot_subprocess()
    if not res.get("rows") and res.get("error"):
        # the gate never reached compilation (offline topology/plugin
        # unavailable) — an environment outage, not a lowering failure
        pytest.skip(f"AOT topology unavailable: {res['error'][:160]}")
    bad = [r for r in res["rows"] if not r.get("compiled")]
    assert res["rows"], "AOT produced no kernel rows"
    assert not bad, (
        "kernels failed Mosaic AOT compile:\n"
        + json.dumps(bad, indent=1))
    # the full inventory: 10 ring variants + torus + both fused GEMMs
    names = {r["kernel"] for r in res["rows"]}
    for expect in ("right_permute", "all_gather", "reduce_scatter_fused",
                   "reduce_scatter_seg", "all_reduce_fused",
                   "all_reduce_seg", "all_reduce_bidi",
                   "all_reduce_seg_bidi", "all_reduce_max", "all_reduce_wire16", "reduce_scatter_wire16",
                   "all_to_all", "all_to_all_v_ragged", "all_gather_v_ragged", "bcast",
                   "all_gather_bidi", "all_reduce_torus", "matmul_allreduce",
                   "matmul_reduce_scatter",
                   # single-chip hot kernels (the MFU path)
                   "flash_attention_bf16_2k", "vpu_combine2_sum",
                   "vpu_reduce_stack_max"):
        assert expect in names, f"AOT case list lost {expect}"
