"""AOT compile-contract test: every coll/pallas kernel must lower
through the real Mosaic TPU compiler (offline, against a v5e-8
topology) — the CI teeth behind PALLAS_AOT.json.

The interpreter suite (test_pallas_coll.py) proves the *schedules*;
this proves the *lowering*: semaphore allocation, VMEM/HBM placement,
collective_id barrier plumbing, (rows, 128) tiling.  A kernel that
fails here would fail on a live pod — the compile-time analog of the
reference's hardware-proven transport contract
(``/root/reference/opal/mca/btl/btl.h:878-1078``).
"""
import json
import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("OTPU_SKIP_AOT", "") not in ("", "0"),
    reason="AOT gate disabled by OTPU_SKIP_AOT")


def test_all_kernels_aot_compile():
    try:
        import libtpu  # noqa: F401
    except ImportError:
        pytest.skip("libtpu not installed — no offline Mosaic compiler")
    from ompi_tpu.tools import pallas_aot

    res = pallas_aot.run(verbose=False)
    bad = [r for r in res["rows"] if not r.get("compiled")]
    assert res["rows"], "AOT produced no kernel rows"
    assert not bad, (
        "kernels failed Mosaic AOT compile:\n"
        + json.dumps(bad, indent=1))
    # the full inventory: 10 ring variants + torus + both fused GEMMs
    names = {r["kernel"] for r in res["rows"]}
    for expect in ("right_permute", "all_gather", "reduce_scatter_fused",
                   "reduce_scatter_seg", "all_reduce_fused",
                   "all_reduce_seg", "all_reduce_bidi",
                   "all_reduce_seg_bidi", "all_reduce_max", "all_reduce_wire16", "reduce_scatter_wire16",
                   "all_to_all", "all_to_all_v_ragged", "all_gather_v_ragged", "bcast",
                   "all_gather_bidi", "all_reduce_torus", "matmul_allreduce",
                   "matmul_reduce_scatter",
                   # single-chip hot kernels (the MFU path)
                   "flash_attention_bf16_2k", "vpu_combine2_sum",
                   "vpu_reduce_stack_max"):
        assert expect in names, f"AOT case list lost {expect}"
