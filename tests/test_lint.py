"""otpu-lint analyzer tests: every pass fires on its known-bad fixture
and stays quiet on the known-good twin, the suppressions file round-trips,
the AST cache parses each file once, and the tool surfaces (CLI --list,
otpu_info --lint) enumerate the registry."""
import os
import subprocess
import sys
from pathlib import Path

import pytest

from ompi_tpu import analysis

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def run_pass(name, *paths):
    res = analysis.lint([str(p) for p in paths], select=[name])
    assert not res.errors, res.errors
    return res.findings


# -- one bad/good pair per pass ----------------------------------------

def test_buffer_ownership_escapes():
    bad = run_pass("buffer-ownership", FIXTURES / "buf_escape" / "bad.py")
    assert len(bad) == 4, bad
    assert all(f.rule == "buffer-ownership" for f in bad)
    msgs = " | ".join(f.message for f in bad)
    assert "stored on 'self'" in msgs
    assert "is returned" in msgs
    assert "queued on" in msgs
    assert not run_pass("buffer-ownership",
                        FIXTURES / "buf_escape" / "good.py")


def test_buffer_ownership_staging_pairing():
    bad = run_pass("buffer-ownership", FIXTURES / "buf_staging" / "bad.py")
    assert len(bad) == 2, bad
    msgs = " | ".join(f.message for f in bad)
    assert "never released" in msgs
    assert "skips the release" in msgs
    assert not run_pass("buffer-ownership",
                        FIXTURES / "buf_staging" / "good.py")


def test_lock_discipline_mutations():
    bad = run_pass("lock-discipline", FIXTURES / "lock_mut" / "bad.py")
    # module global, subscript store, augassign, alias pop, post-lock clear
    assert len(bad) == 5, bad
    symbols = {f.symbol for f in bad}
    assert "register" in symbols
    assert "Pool.put" in symbols
    assert "Pool.pop_alias" in symbols
    assert "Pool.drop" in symbols
    assert not run_pass("lock-discipline", FIXTURES / "lock_mut" / "good.py")


def test_lock_discipline_blocking_calls():
    bad = run_pass("lock-discipline", FIXTURES / "lock_block" / "bad.py")
    assert len(bad) == 2, bad
    msgs = " | ".join(f.message for f in bad)
    assert "_rpc" in msgs            # depth-1 transitive helper
    assert "sleep" in msgs
    assert not run_pass("lock-discipline",
                        FIXTURES / "lock_block" / "good.py")


def test_lock_discipline_conflicting_declarations():
    bad = run_pass("lock-discipline", FIXTURES / "lock_conflict" / "bad.py")
    assert len(bad) == 1, bad
    assert "ambiguous _guarded_by" in bad[0].message
    # same attr under the SAME lock in two classes is not a conflict
    assert not run_pass("lock-discipline", FIXTURES / "lock_mut" / "good.py")


def test_lock_discipline_order_cycle():
    bad = run_pass("lock-discipline", FIXTURES / "lock_order" / "bad.py")
    assert any("cycle" in f.message for f in bad), bad
    assert not run_pass("lock-discipline",
                        FIXTURES / "lock_order" / "good.py")


def test_hot_path_budget():
    bad = run_pass("hot-path", FIXTURES / "hot" / "bad.py")
    msgs = " | ".join(f.message for f in bad)
    for what in ("pickle.dumps", "pickle.loads", "f-string", "str.format",
                 "'%'-formatting", "list concatenation", "struct.error"):
        assert what in msgs, (what, msgs)
    assert len(bad) == 8, bad
    assert not run_pass("hot-path", FIXTURES / "hot" / "good.py")


def test_observability_contracts():
    bad = run_pass("observability", FIXTURES / "obs" / "bad.py",
                   FIXTURES / "obs" / "spc.py",
                   FIXTURES / "obs" / "telemetry.py",
                   FIXTURES / "obs" / "profile.py",
                   FIXTURES / "obs" / "trace.py")
    assert len(bad) == 17, bad
    msgs = " | ".join(f.message for f in bad)
    assert "moe_dispatch_tokenz" in msgs      # the moe counter twin
    assert "moe_extra" in msgs                # the moe SCHEMA-key twin
    assert "no matching register_help" in msgs
    assert "not declared in runtime/spc.py" in msgs
    assert "quant_encodez" in msgs            # the quant counter twin
    assert "quant.encooode" in msgs           # the quant stage twin
    assert "req_tracez" in msgs               # the otpu-req counter twin
    assert "slo_breachez" in msgs             # the SLO counter twin
    assert "slo_extra" in msgs                # the slo SCHEMA-key twin
    assert "serve_reqz" in msgs               # the request-flow twin
    assert "never consumed" in msgs
    assert "not a key of runtime/telemetry.py SCHEMA" in msgs
    assert "no registered help-flight template" in msgs
    assert "not declared in runtime/profile.py STAGES" in msgs
    assert "not declared in runtime/trace.py FLOW_CATEGORIES" in msgs
    assert not run_pass("observability", FIXTURES / "obs" / "good.py",
                        FIXTURES / "obs" / "spc.py",
                        FIXTURES / "obs" / "telemetry.py",
                        FIXTURES / "obs" / "profile.py",
                        FIXTURES / "obs" / "trace.py")


def test_mca_conformance():
    bad = run_pass("mca-conformance", FIXTURES / "mca_case")
    msgs = " | ".join(f.message for f in bad)
    assert "no module-level COMPONENT" in msgs
    assert "required btl-framework slot 'send'" in msgs
    assert "'name' class attribute" in msgs
    assert "os.environ" in msgs
    assert "group 'transport'" in msgs
    # the coll twin: the quant-shaped component must implement its
    # framework's query slot even when it always declines selection
    assert "required coll-framework slot 'comm_query'" in msgs
    # the good components in the same tree contribute nothing
    assert not any("good_btl" in f.path for f in bad)
    assert not any("good_coll" in f.path for f in bad)
    assert len(bad) == 6, bad


# -- suppressions ------------------------------------------------------

def test_suppressions_round_trip(tmp_path):
    findings = run_pass("hot-path", FIXTURES / "hot" / "bad.py")
    assert findings
    text = analysis.Suppressions.render(findings)
    sup = analysis.Suppressions.parse(text)
    res = analysis.lint([str(FIXTURES / "hot" / "bad.py")],
                        select=["hot-path"], suppressions=sup)
    assert not res.findings, res.findings
    assert len(res.suppressed) == len(findings)
    assert not sup.unused()
    # and the rendered file parses identically after a disk round trip
    p = tmp_path / "baseline.txt"
    p.write_text(text)
    sup2 = analysis.Suppressions.load(str(p))
    assert [(e.rule, e.path, e.symbol) for e in sup2.entries] \
        == [(e.rule, e.path, e.symbol) for e in sup.entries]


def test_suppressions_unused_entries_reported():
    sup = analysis.Suppressions.parse(
        "hot-path nonexistent/file.py:nowhere  # stale\n")
    res = analysis.lint([str(FIXTURES / "hot" / "good.py")],
                        select=["hot-path"], suppressions=sup)
    assert res.clean
    assert len(sup.unused()) == 1


def test_suppressions_reject_malformed():
    with pytest.raises(ValueError):
        analysis.Suppressions.parse("too many words on this line\n")


def test_partial_runs_do_not_flag_out_of_scope_suppressions():
    """Linting one file (or a pass subset) with the repo baseline must
    not demand baseline edits the run cannot justify: entries whose
    rule didn't run or whose file wasn't linted are out of scope."""
    sup = analysis.Suppressions.load(str(REPO / "lint_suppressions.txt"))
    res = analysis.lint([str(REPO / "ompi_tpu" / "rte" / "coord.py")],
                        suppressions=sup)
    assert res.clean
    assert res.unused_suppressions(sup) == []        # out of scope
    # and the CLI agrees: single-file run with the default baseline
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint",
         "ompi_tpu/rte/coord.py"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    # a full-scope run that stops matching DOES prove staleness
    stale = analysis.Suppressions.parse(
        "observability ompi_tpu/rte/coord.py  # stale\n")
    res = analysis.lint([str(REPO / "ompi_tpu" / "rte" / "coord.py")],
                        suppressions=stale)
    assert len(res.unused_suppressions(stale)) == 1


# -- framework plumbing ------------------------------------------------

def test_registry_has_all_eight_passes():
    names = [p.name for p in analysis.all_passes()]
    assert names == ["buffer-ownership", "lock-discipline", "hot-path",
                     "observability", "mca-conformance", "view-escape",
                     "mpi-typestate", "collective-matching"]
    assert all(p.description for p in analysis.all_passes())


def test_ast_cache_parses_each_file_once(monkeypatch):
    import ast as ast_mod

    from ompi_tpu import analysis as an

    an._ast_cache.clear()
    calls = []
    real_parse = ast_mod.parse
    monkeypatch.setattr(
        ast_mod, "parse",
        lambda *a, **kw: calls.append(1) or real_parse(*a, **kw))
    target = str(FIXTURES / "hot")
    an.lint([target])                    # all passes share one parse
    first = len(calls)
    assert first == 2                    # bad.py + good.py
    an.lint([target])                    # second run: pure cache hits
    assert len(calls) == first


def test_cli_list_and_exit_codes(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint", "--list"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    for name in ("buffer-ownership", "lock-discipline", "hot-path",
                 "observability", "mca-conformance", "view-escape",
                 "mpi-typestate", "collective-matching"):
        assert name in r.stdout
    # findings -> exit 1; baseline generated via --write-suppressions
    # then fed back -> exit 0
    bad_dir = str(FIXTURES / "hot")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint", bad_dir,
         "--no-suppressions"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 1
    assert "[hot-path]" in r.stdout
    base = tmp_path / "base.txt"
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint", bad_dir,
         "--write-suppressions", str(base)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint", bad_dir,
         "--suppressions", str(base)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_otpu_info_lists_lint_passes(capsys):
    from ompi_tpu.tools import otpu_info

    assert otpu_info.main(["--lint"]) == 0
    out = capsys.readouterr().out
    for name in ("buffer-ownership", "lock-discipline", "hot-path",
                 "observability", "mca-conformance", "view-escape",
                 "mpi-typestate", "collective-matching"):
        assert f"lint pass {name}" in out
    assert otpu_info.main(["--all", "--parsable"]) == 0
    out = capsys.readouterr().out
    assert "lint pass buffer-ownership:" in out
