"""otpu-prof — stage clocks, the sampling profiler, and the analyzer's
host-overhead decomposition.

Four layers of coverage:

* stage-clock unit: declared-table enforcement, histogram math,
  snapshot/delta semantics, disabled identity;
* sampling-profiler unit: phases bucket through the @hot_path registry,
  GIL estimates are fractions, stop() restores the no-thread state;
* analyzer unit: decomposition buckets, exposed-host fraction, and
  stage-sum vs e2e reconciliation over a synthetic profile payload;
* THE acceptance run — a 3-rank loopback allreduce job with the stage
  clocks + profiler armed: the otpu_analyze report carries a per-rank
  exposed-host fraction and a pack/queue/wire/parse/deliver breakdown
  whose stage sums reconcile with the measured end-to-end collective
  latency (0 < stage_sum/e2e <= 1.25 — stages are work segments inside
  the e2e window; the remainder is progress-loop wait.  The upper slack
  absorbs cross-thread overlap: parse/deliver run on the progress
  thread inside the same window).
"""
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "telemetry_worker.py"


@pytest.fixture
def stage_clocks():
    from ompi_tpu.runtime import profile

    profile.reset_for_testing()
    profile._set_enabled(True)
    yield profile
    profile.reset_for_testing()


# ------------------------------------------------------ stage-clock unit

def test_stage_table_is_closed(stage_clocks):
    profile = stage_clocks
    t0 = profile.now()
    profile.stage_span("send.pack", t0)
    with pytest.raises(ValueError):
        profile.stage_span("not.a.stage", profile.now())
    with pytest.raises(ValueError):
        profile.stage_mark("not.a.stage")
    # every documented decomposition stage is declared
    for stage in ("send.pack", "send.staging", "send.queue", "send.wire",
                  "recv.parse", "recv.deliver", "recv.complete",
                  "coll.decide", "coll.alg"):
        assert stage in profile.STAGES, stage


def test_stage_histogram_math(stage_clocks):
    profile = stage_clocks
    base = profile.now()
    for us in (10, 20, 40):
        profile.stage_span("send.pack", base - us * 1000, base)
    stats = profile.stage_stats()["send.pack"]
    assert stats["n"] == 3
    assert stats["sum_us"] == pytest.approx(70.0, abs=0.5)
    assert stats["min_us"] == pytest.approx(10.0, abs=0.5)
    assert stats["max_us"] == pytest.approx(40.0, abs=0.5)
    assert stats["min_us"] <= stats["p50_us"] <= stats["p99_us"] \
        <= stats["max_us"]
    # delta API: only new occurrences appear, populations never reset
    snap = profile.stage_snapshot()
    profile.stage_span("send.pack", base - 5000, base)
    d = profile.stage_delta_stats(snap, profile.stage_snapshot())
    assert d["send.pack"]["n"] == 1
    assert profile.stage_stats()["send.pack"]["n"] == 4
    assert profile.stage_delta_stats(
        profile.stage_snapshot(), profile.stage_snapshot()) == {}


def test_stage_clock_disabled_identity():
    from ompi_tpu.runtime import profile

    profile.reset_for_testing()
    assert profile.enabled is False
    # disabled: nothing records, even with a bogus name (no table walk)
    profile.stage_span("not.a.stage", 12345)
    profile.stage_mark("not.a.stage")
    assert profile.stage_snapshot() == {}
    # a begin captured before a mid-run enable must not record garbage
    profile._set_enabled(True)
    try:
        profile.stage_span("send.pack", 0)
        assert profile.stage_snapshot() == {}
    finally:
        profile.reset_for_testing()


# ------------------------------------------------- sampling-profiler unit

def test_profiler_phases_and_gil_estimates():
    import threading

    from ompi_tpu.runtime import hotpath, profile

    profile.reset_for_testing()

    @hotpath.hot_path
    def _prof_test_spin():
        deadline = time.monotonic() + 0.6
        x = 0
        while time.monotonic() < deadline:
            x += 1
        return x

    p = profile.HostProfiler(rank=0, interval_ms=5)
    with profile._lock:
        profile._profiler = p
    try:
        p.start()
        t = threading.Thread(target=_prof_test_spin)
        t.start()
        t.join()
        time.sleep(0.05)
        stats = profile.profiler_stats()
        assert stats is not None and stats["samples"] > 10
        # the spin thread's frames bucket under its @hot_path name
        assert any("_prof_test_spin" in k for k in stats["phases"]), \
            stats["phases"]
        assert 0.0 <= stats["gil_released"] <= 1.0
        assert 0.0 <= stats["gil_wait"] <= 1.0
        # the pytest main thread sits in threading.join -> GIL released
        assert stats["phases"].get("idle", 0) > 0, stats["phases"]
    finally:
        profile.reset_for_testing()
    assert not [th for th in threading.enumerate()
                if th.name == "otpu-prof"], "profiler thread survived"


def test_profiler_stop_clears_slot_for_reinit():
    """stop() must clear the profiler slot (the telemetry.stop
    discipline): a finalize/init cycle re-arms a FRESH sampler instead
    of early-returning against a dead thread whose frozen estimates
    would read as live."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import profile

    profile.reset_for_testing()
    registry.lookup("otpu_profile_interval_ms").set(5)

    class _Rte:
        my_world_rank = 0

    try:
        assert profile.start(_Rte()) is True
        p1 = profile._profiler
        assert p1 is not None
        profile.stop()
        assert profile._profiler is None
        assert profile.start(_Rte()) is True
        p2 = profile._profiler
        assert p2 is not p1 and p2._thread.is_alive()
    finally:
        registry.lookup("otpu_profile_interval_ms").set(0)
        profile.reset_for_testing()


def test_export_payload_shape(stage_clocks):
    profile = stage_clocks
    assert profile.export_payload() is not None   # stages armed, empty
    profile.stage_span("coll.alg", profile.now() - 1000)
    payload = profile.export_payload()
    assert "stages" in payload and "coll.alg" in payload["stages"]
    # the armed plane reports its own covered window — the analyzer's
    # ring-overwrite-immune exposed-host denominator
    assert payload["elapsed_us"] > 0
    # JSON-serializable end to end (rides in trace metadata / flight)
    json.dumps(payload)


# ------------------------------------------------------- analyzer unit

def _mk_profile(scale=1.0):
    mk = lambda n, mean: {"n": n, "sum_us": round(n * mean * scale, 1),
                          "mean_us": round(mean * scale, 2),
                          "min_us": 1.0, "max_us": 2 * mean}
    return {"stages": {
        "send.pack": mk(10, 8.0), "send.queue": mk(10, 5.0),
        "send.wire": mk(12, 30.0), "recv.parse": mk(10, 15.0),
        "recv.deliver": mk(10, 35.0), "recv.complete": mk(10, 4.0),
    }, "profiler": {"samples": 40, "phases": {"idle": 30},
                    "gil_released": 0.7, "gil_wait": 0.1}}


def _synthetic_events(rounds=10, ranks=3, dur=600.0):
    events = []
    t = 0.0
    for _ in range(rounds):
        for r in range(ranks):
            events.append({"ph": "X", "cat": "coll", "name": "allreduce",
                           "ts": t + r * 10.0, "dur": dur, "pid": r,
                           "args": {"cid": 0, "nbytes": 4096}})
        t += 5000.0
    return sorted(events, key=lambda e: e["ts"])


def test_analyze_host_overhead_decomposition():
    from ompi_tpu.tools import otpu_analyze

    events = _synthetic_events()
    profiles = {r: _mk_profile() for r in range(3)}
    rep = otpu_analyze.analyze(events, profiles=profiles)
    oh = rep["host_overhead"]
    assert set(oh) == {"0", "1", "2"}
    row = oh["0"]
    d = row["decomposition"]
    assert set(d) == {"pack", "queue", "wire", "parse", "deliver"}
    assert d["pack"]["mean_us"] == pytest.approx(8.0)
    assert d["deliver"]["total_us"] == pytest.approx(390.0)  # 350+40
    # host stages exclude the wire bucket
    assert row["host_stage_us"] == pytest.approx(
        row["stage_sum_us"] - d["wire"]["total_us"])
    # reconciliation: e2e = 10 rounds x 600us
    assert row["coll_e2e_us"] == pytest.approx(6000.0)
    assert 0.0 < row["stage_over_e2e"] <= 1.25
    assert 0.0 < row["exposed_host_fraction"] < 1.0
    assert row["profiler"]["gil_released"] == 0.7
    # the profile's own covered window wins over the ring-limited
    # trace window (long-run honesty: stage totals span the whole run,
    # the surviving trace events may not)
    prof_w = _mk_profile()
    prof_w["elapsed_us"] = 1e9
    rep_w = otpu_analyze.analyze(events, profiles={0: prof_w})
    assert rep_w["host_overhead"]["0"]["exposed_host_fraction"] < \
        row["exposed_host_fraction"]
    # diff flags exposed-host movement
    rep2 = otpu_analyze.analyze(
        events, profiles={r: _mk_profile(scale=2.0) for r in range(3)})
    delta = otpu_analyze.diff_reports(rep, rep2)
    assert delta["exposed_host_delta"]["0"] > 0
    # both render modes carry the section
    text = otpu_analyze.render_text(rep)
    assert "host-overhead decomposition" in text
    parsable = otpu_analyze.render_text(rep, parsable=True)
    assert any(ln.startswith("exposed_host:0:")
               for ln in parsable.splitlines())
    assert any(ln.startswith("host_stage:0:pack:")
               for ln in parsable.splitlines())


def test_load_run_collects_profiles(tmp_path):
    from ompi_tpu.tools import otpu_analyze

    events = _synthetic_events(rounds=3)
    for r in range(3):
        mine = [e for e in events if e["pid"] == r]
        (tmp_path / f"trace_rank{r}.json").write_text(json.dumps(
            {"traceEvents": mine,
             "metadata": {"rank": r, "clock_offset_us": 0.0,
                          "profile": _mk_profile()}}))
    # a merged file alongside: events prefer it, profiles still load
    (tmp_path / "trace_merged.json").write_text(
        json.dumps({"traceEvents": events}))
    ev, profiles, _meta = otpu_analyze.load_run([str(tmp_path)])
    assert len(ev) == len(events)
    assert set(profiles) == {0, 1, 2}
    rep = otpu_analyze.analyze(ev, profiles=profiles)
    assert set(rep["host_overhead"]) == {"0", "1", "2"}


# ------------------------------------------------- THE acceptance run

def test_stage_breakdown_reconciles_on_loopback_allreduce(tmp_path):
    """3-rank loopback allreduce job, stage clocks + profiler armed:
    the analyzer report has a per-rank exposed-host fraction and a
    five-bucket decomposition whose stage sums reconcile with measured
    end-to-end latency (see module docstring for the band)."""
    tdir = tmp_path / "trace"
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_ITERS="30")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", str(tdir),
           "--mca", "otpu_profile_stages", "1",
           "--mca", "otpu_profile_interval_ms", "10",
           # coll/sm below tuned so the collectives cross the pml/btl
           # datapath the stage clocks instrument
           "--mca", "otpu_coll_sm_coll_priority", "0",
           sys.executable, str(WORKER)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    from ompi_tpu.tools import otpu_analyze

    events, profiles, _meta = otpu_analyze.load_run([str(tdir)])
    assert set(profiles) == {0, 1, 2}, (sorted(profiles), out)
    rep = otpu_analyze.analyze(events, profiles=profiles)
    assert rep["rounds_total"] >= 25, rep["rounds_total"]
    oh = rep["host_overhead"]
    assert set(oh) == {"0", "1", "2"}
    for rank, row in oh.items():
        d = row["decomposition"]
        # every bucket of the per-message breakdown is populated
        for bucket in ("pack", "queue", "wire", "parse", "deliver"):
            assert bucket in d, (rank, sorted(d))
            assert d[bucket]["n"] >= 25, (rank, bucket, d[bucket])
            assert d[bucket]["mean_us"] > 0
        # reconciliation: stage sums are work inside the e2e window
        assert row["coll_e2e_us"] > 0
        assert 0.0 < row["stage_over_e2e"] <= 1.25, (rank, row)
        # exposed-host fraction present and sane
        assert 0.0 < row["exposed_host_fraction"] < 1.0, (rank, row)
        # the sampling profiler rode along
        assert row["profiler"]["samples"] > 0, (rank, row)
        assert 0.0 <= row["profiler"]["gil_released"] <= 1.0
