"""Multi-host launch path: tpurun --hostfile drives one child launcher
per host (the ssh/rsh plm analog, ``ompi/tools/mpirun/Makefile.am:3-7``
→ prte remote daemons).  ``--launch-agent local`` runs the identical
head→child→coord protocol as plain subprocesses — real child
launchers, distinct node ids, ranks joining one world through the
head's coord service — without needing sshd in CI.
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(extra, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", *extra],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)


def test_hostfile_ring_end_to_end(tmp_path):
    """The VERDICT done-criterion: tpurun --hostfile h.txt -n 8
    examples/ring.py works end-to-end."""
    hf = tmp_path / "h.txt"
    hf.write_text("nodeA slots=4\nnodeB slots=4\n")
    r = _tpurun(["--hostfile", str(hf), "--launch-agent", "local",
                 "-n", "8", sys.executable,
                 os.path.join(REPO, "examples", "ring.py")])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "token now 0" in r.stdout
    assert r.stdout.count("exiting") == 8


def test_hostfile_node_ids_and_world(tmp_path):
    """Ranks land on their assigned hosts (byslot), see distinct node
    ids, and still form ONE world through the head's coord service."""
    hf = tmp_path / "hosts.txt"
    hf.write_text(textwrap.dedent("""\
        # two emulated nodes
        alpha slots=2
        beta  slots=2
    """))
    script = tmp_path / "whoami.py"
    script.write_text(textwrap.dedent("""
        import os
        import numpy as np
        import ompi_tpu
        w = ompi_tpu.init()
        node = os.environ.get("OTPU_NODE_ID")
        out = w.allgather(np.array([w.rank], np.int64))
        print(f"RANK {w.rank} NODE {node} SUM "
              f"{int(np.asarray(out).sum())}")
        ompi_tpu.finalize()
    """))
    r = _tpurun(["--hostfile", str(hf), "--launch-agent", "local",
                 "-n", "4", sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    lines = sorted(ln.split("] ", 1)[1] for ln in r.stdout.splitlines()
                   if "RANK" in ln)
    # byslot: ranks 0,1 -> alpha; 2,3 -> beta; allgather sum proves one
    # world across both child launchers
    assert lines == [
        "RANK 0 NODE alpha SUM 6", "RANK 1 NODE alpha SUM 6",
        "RANK 2 NODE beta SUM 6", "RANK 3 NODE beta SUM 6"], lines


def test_hostfile_slot_guard_and_oversubscribe(tmp_path):
    hf = tmp_path / "small.txt"
    hf.write_text("one slots=1\ntwo slots=1\n")
    script = tmp_path / "ok.py"
    script.write_text("import ompi_tpu; w = ompi_tpu.init(); "
                      "print('R', w.rank); ompi_tpu.finalize()")
    # 4 ranks > 2 slots: refused, like mpirun without --oversubscribe
    r = _tpurun(["--hostfile", str(hf), "--launch-agent", "local",
                 "-n", "4", sys.executable, str(script)])
    assert r.returncode != 0
    assert "oversubscribe" in (r.stdout + r.stderr)
    # with the flag the ranks wrap around the hosts
    r = _tpurun(["--hostfile", str(hf), "--launch-agent", "local",
                 "-n", "4", "--oversubscribe",
                 sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("R ") == 4


def test_fake_ssh_agent_contract(tmp_path):
    """The DEFAULT multi-host path (``--launch-agent ssh``) exercised
    without sshd: a fake-ssh shim stands in for ssh and asserts the
    exact contract tpurun's head relies on —

    * argv shape ``<agent words…> <host> <ONE shell command>`` (exactly
      what ``ssh host "cmd"`` accepts);
    * the command cd's into the launch cwd first (ssh starts in $HOME);
    * the child launcher is fully self-described on its command line
      (``--child-of`` coord address, ``--ranks``, ``-n``, ``--node-id``)
      with NO environment marshalling — ssh forwards none, so any env
      dependence would only fail on real clusters;

    then execs the command locally through a SCRUBBED environment (PATH/
    HOME only, like a fresh login shell), proving the remote side works
    from the command line + cwd alone."""
    shim = tmp_path / "fakessh.py"
    shim.write_text(textwrap.dedent("""
        import os, subprocess, sys

        def fail(msg):
            print("FAKESSH ASSERT:", msg, file=sys.stderr, flush=True)
            sys.exit(99)

        args = sys.argv[1:]
        # tpurun split the agent string into words; ours ends with the
        # ssh-style option so the full ssh argv shape is exercised
        if args[:2] != ["-o", "BatchMode=yes"]:
            fail(f"agent words not forwarded: {args[:2]}")
        if len(args) != 4:
            fail(f"expected '<opts> <host> <command>', got {args}")
        host, command = args[2], args[3]
        if host not in ("ghostA", "ghostB"):
            fail(f"unexpected host {host}")
        wdir = os.environ["FAKESSH_WDIR"]
        if not command.startswith(f"cd {wdir} && "):
            fail(f"command must cd into the launch cwd: {command[:80]}")
        for needle in ("-m ompi_tpu.tools.tpurun", "--child-of",
                       "--ranks", "--node-id " + host):
            if needle not in command:
                fail(f"{needle!r} missing from: {command}")
        if "OTPU_" in command:
            fail("identity must ride flags, not exported env")
        with open(os.environ["FAKESSH_LOG"], "a") as log:
            print(host, file=log, flush=True)
        # exec like sshd: fresh login-ish env, nothing marshalled
        env = {k: v for k, v in os.environ.items()
               if k in ("PATH", "HOME", "LANG")}
        sys.exit(subprocess.run(["/bin/sh", "-c", command],
                                env=env).returncode)
    """))
    hf = tmp_path / "hosts.txt"
    hf.write_text("ghostA slots=2\nghostB slots=2\n")
    log = tmp_path / "shim.log"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FAKESSH_LOG=str(log), FAKESSH_WDIR=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun",
         "--hostfile", str(hf),
         "--launch-agent",
         f"{sys.executable} {shim} -o BatchMode=yes",
         "--remote-python", sys.executable,
         "-n", "4", sys.executable,
         os.path.join(REPO, "examples", "ring.py")],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=env)
    assert "FAKESSH ASSERT" not in r.stderr, r.stderr
    assert r.returncode == 0, r.stdout + r.stderr
    assert "token now 0" in r.stdout, r.stdout
    assert r.stdout.count("exiting") == 4
    # one agent invocation per remote host
    assert sorted(log.read_text().split()) == ["ghostA", "ghostB"]


def test_hostfile_child_failure_tears_down(tmp_path):
    hf = tmp_path / "hosts.txt"
    hf.write_text("n1 slots=2\nn2 slots=2\n")
    script = tmp_path / "die.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        import ompi_tpu
        w = ompi_tpu.init()
        if w.rank == 3:
            sys.exit(7)        # a rank on the SECOND child dies
        time.sleep(30)         # others would hang forever
    """))
    r = _tpurun(["--hostfile", str(hf), "--launch-agent", "local",
                 "-n", "4", sys.executable, str(script)], timeout=120)
    # the child reports exit 7, the head tears the whole job down
    assert r.returncode != 0
    assert "terminated" in r.stderr or r.returncode == 7
