"""Sharded checkpoint/restore (SURVEY §5.4 — the gap the reference leaves)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent


def test_device_world_save_load_reshard(tmp_path):
    """A pytree of sharded jax arrays round-trips and restores onto a
    DIFFERENT sharding (the elasticity property)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ompi_tpu.parallel import checkpoint as ckpt

    devs = np.array(jax.devices()[:8])
    mesh8 = Mesh(devs, ("x",))
    sh8 = NamedSharding(mesh8, P("x"))
    tree = {
        "layer0": {"w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8), sh8),
            "b": jax.device_put(np.ones(8, np.float32), sh8)},
        "step": np.int64(7),
    }
    d = str(tmp_path / "ck")
    ckpt.save(d, tree)
    assert os.path.exists(os.path.join(d, "manifest.json"))

    # restore as plain numpy
    back = ckpt.load(d)
    assert np.array_equal(back["layer0"]["w"],
                          np.arange(64, dtype=np.float32).reshape(8, 8))
    assert int(back["step"]) == 7

    # restore onto a 2x4 mesh with a different partitioning
    mesh24 = Mesh(devs.reshape(2, 4), ("a", "b"))
    sh24 = NamedSharding(mesh24, P("a", "b"))

    def shard_for(path):
        return sh24 if path.endswith("/w") else NamedSharding(mesh24, P())

    back2 = ckpt.load(d, sharding=shard_for)
    w2 = back2["layer0"]["w"]
    assert isinstance(w2, jax.Array) and w2.sharding == sh24
    assert np.array_equal(np.asarray(w2),
                          np.arange(64, dtype=np.float32).reshape(8, 8))


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_multiprocess_sharded_save(tmp_path):
    """4 ranks each contribute their Shard through collective I/O; the
    dense checkpoint restores in a plain single process."""
    d = tmp_path / "mpck"
    script = tmp_path / "saver.py"
    script.write_text(textwrap.dedent(f"""
        import numpy as np, ompi_tpu
        from ompi_tpu.parallel import checkpoint as ckpt
        w = ompi_tpu.init()
        r = w.rank
        gi, gj = divmod(r, 2)
        block = np.full((3, 5), float(r), np.float64)
        tree = {{
            "w": ckpt.Shard(block, [gi * 3, gj * 5], [6, 10]),
            "lr": np.float64(0.25),     # replicated leaf
        }}
        ckpt.save({str(d)!r}, tree, comm=w)
        print(f"saved rank {{r}}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("saved") == 4

    from ompi_tpu.parallel import checkpoint as ckpt

    back = ckpt.load(str(d))
    w = back["w"]
    assert w.shape == (6, 10)
    for rr in range(4):
        gi, gj = divmod(rr, 2)
        blk = w[gi * 3:(gi + 1) * 3, gj * 5:(gj + 1) * 5]
        assert np.all(blk == float(rr)), (rr, blk)
    assert float(back["lr"]) == 0.25
