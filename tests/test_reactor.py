"""Native-reactor progress engine: the epoll loop in otpu_native owns
the btl fds (drain, framing, fast-frame parse) and Python only sees
completed records through one ctypes drain per progress tick.

The tests here pin the tentpole's contracts:

- differential fuzz: the native framing/parsing twin delivers the EXACT
  frag stream the pure-Python ``_drain``/``_parse_frame`` lane does,
  over fuzzed split boundaries and mixed fast/pickle/crc-armed headers;
- lane routing: anything that is not a plain fast header (crc bits,
  pickle, unknown kind byte) reaches Python as verbatim RAW bytes;
- the completed-record plumbing: doorbell drain, writability records,
  oversize parking, EOF, desync, idle-wait wakeup via the notify fd;
- engagement gating: otpu_progress_native=0 and the sanitizer keep the
  reactor off entirely;
- progress.idle_wait survives a waiter unregistered/closed mid-wait
  (the regression that used to burn the full timeout in a blind sleep).

Everything skips cleanly when the native toolchain is unavailable —
the pure-Python lane is the behavior baseline, not a degraded mode.
"""
import os
import pickle
import random
import socket
import struct
import threading
import time
import zlib
from functools import partial

import numpy as np
import pytest

from ompi_tpu.mca.btl import tcp as tcp_mod
from ompi_tpu.mca.btl.base import CTL, FRAG, MATCH, RNDV, Frag
from ompi_tpu.runtime import progress, reactor

needs_reactor = pytest.mark.skipif(
    not reactor.available(),
    reason="otpu_native reactor not built in this environment")

_LEN = tcp_mod._LEN
_FAST = tcp_mod._FAST
_CKSUM = tcp_mod._CKSUM


@pytest.fixture
def clean_engine():
    """Every test leaves the process-wide reactor/progress singletons
    exactly as it found them (instance teardown's reset path)."""
    yield
    progress.reset_for_testing()


def encode(frag: Frag, cksum: bool = False) -> bytes:
    """Wire-encode one fragment exactly the way TcpBtl.send frames it
    (the test_btl_wire encode twin, plus the crc-armed variant)."""
    payload = frag.data
    if not isinstance(payload, (bytes, bytearray, memoryview)):
        payload = memoryview(payload)
    if isinstance(payload, memoryview) and (
            payload.ndim != 1 or payload.itemsize != 1):
        payload = payload.cast("B")
    hdr = tcp_mod._fast_header(frag)
    if hdr is not None:
        htype = tcp_mod._H_FAST
    else:
        hdr = pickle.dumps(
            (frag.cid, frag.src, frag.dst, frag.tag, frag.seq, frag.kind,
             frag.total_len, frag.offset, frag.meta),
            protocol=pickle.HIGHEST_PROTOCOL)
        hdr = _LEN.pack(len(hdr)) + hdr
        htype = tcp_mod._H_PICKLE
    if cksum:
        crc = zlib.crc32(payload, zlib.crc32(hdr))
        fl = 1 + _CKSUM.size + len(hdr) + len(payload)
        return (_LEN.pack(fl) + bytes((htype | tcp_mod._H_CK_BASE,))
                + _CKSUM.pack(crc) + hdr + bytes(payload))
    fl = 1 + len(hdr) + len(payload)
    return _LEN.pack(fl) + bytes((htype,)) + hdr + bytes(payload)


def _mixed_frags(rng: random.Random, n=32) -> list:
    """Fragments alternating fast-header, pickle, and crc-armed lanes."""
    frags = []
    for i in range(n):
        payload = np.frombuffer(
            bytes(rng.randrange(256)
                  for _ in range(rng.randrange(0, 300))), np.uint8)
        pick = i % 4
        if pick == 0:       # eager MATCH, empty meta -> fast lane
            f = Frag(3, 0, 1, rng.randrange(1000), i, MATCH, payload,
                     total_len=len(payload))
        elif pick == 1:     # FRAG continuation -> fast lane (req_id)
            f = Frag(3, 1, 0, -1, 0, FRAG, payload,
                     total_len=1 << 20, offset=rng.randrange(1 << 20),
                     meta={"req_id": rng.randrange(1 << 40)})
        elif pick == 2:     # RNDV rich meta -> pickle (RAW lane)
            f = Frag(3, 0, 1, rng.randrange(1000), i, RNDV, payload,
                     total_len=len(payload) + 512,
                     meta={"req_id": i, "window": [1, 2]})
        else:               # CTL proto -> pickle (RAW lane)
            f = Frag(3, 1, 0, -1, 0, CTL, payload,
                     meta={"proto": "ob1_rget_done", "req_id": i})
        frags.append((f, pick == 3 and i % 8 == 7 or i % 5 == 4))
    return frags


def _own(frag: Frag) -> tuple:
    return (frag.cid, frag.src, frag.dst, frag.tag, frag.seq, frag.kind,
            frag.total_len, frag.offset, dict(frag.meta),
            bytes(memoryview(np.ascontiguousarray(frag.data))))


def _stream_pair():
    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(True)
    return a, b


def _drain_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond() and time.monotonic() < deadline:
        reactor.drain()
        time.sleep(0.002)
    assert cond(), "reactor records did not arrive in time"


# -- engagement gating -------------------------------------------------

@needs_reactor
def test_engage_is_idempotent_and_shutdown_resets(clean_engine):
    assert reactor.engage()
    assert reactor.active()
    h = reactor._handle
    assert reactor.engage()          # second engage: same reactor
    assert reactor._handle == h
    assert progress.callback_count() >= 1   # drain rides as a callback
    reactor.shutdown()
    assert not reactor.active()
    assert reactor._handle == 0


@needs_reactor
def test_var_off_keeps_reactor_disengaged(clean_engine):
    from ompi_tpu.base.var import registry

    var = registry.lookup("otpu_progress_native")
    saved = var.value
    var.set(False)
    try:
        assert not reactor.configured()
        assert not reactor.engage()
        assert not reactor.active()
    finally:
        var.set(saved)


@needs_reactor
def test_sanitizer_keeps_reactor_disengaged(clean_engine, monkeypatch):
    from ompi_tpu.runtime import sanitizer

    monkeypatch.setattr(sanitizer, "enabled", True)
    assert not reactor.engage()
    assert not reactor.active()


# -- differential fuzz: native framing twin vs the Python lane ---------

@needs_reactor
@pytest.mark.parametrize("seed", range(4))
def test_differential_fuzz_native_vs_python(clean_engine, seed):
    """The acceptance fuzz: identical byte streams — mixed fast/pickle
    headers, crc-armed frames, fuzzed split boundaries — through the
    native reactor and through the pure-Python ``_drain`` twin must
    deliver byte-identical frag streams."""
    rng = random.Random(seed)
    frags = _mixed_frags(rng)
    stream = b"".join(encode(f, cksum=ck) for f, ck in frags)

    # Python reference lane
    btl_py = tcp_mod.TcpBtl()
    got_py = []
    btl_py.set_recv_callback(lambda f: got_py.append(_own(f)))
    pyconn = tcp_mod._Conn(None, rank=7)
    pos = 0
    while pos < len(stream):
        step = rng.choice((1, 2, 3, 5, 7, 13, 64, 1024))
        pyconn.inbuf += stream[pos:pos + step]
        pos += step
        btl_py._drain(pyconn)
    assert not pyconn.inbuf

    # native reactor lane, same stream re-chunked with the same rng
    assert reactor.engage()
    a, b = _stream_pair()
    btl_nat = tcp_mod.TcpBtl()
    got_nat = []
    btl_nat.set_recv_callback(lambda f: got_nat.append(_own(f)))
    conn = tcp_mod._Conn(a, rank=7)
    conn.fd = a.fileno()
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       partial(btl_nat._reactor_event, conn))
    rng2 = random.Random(seed + 1000)

    def feed():
        p = 0
        while p < len(stream):
            step = rng2.choice((1, 2, 3, 5, 7, 13, 64, 1024))
            b.sendall(stream[p:p + step])
            p += step
            if step < 8:
                time.sleep(0)   # let the epoll thread see odd splits
        b.close()

    t = threading.Thread(target=feed)
    t.start()
    _drain_until(lambda: len(got_nat) >= len(got_py))
    t.join()
    reactor.remove(a.fileno())
    a.close()

    assert len(got_py) == len(frags)
    assert got_nat == got_py


@needs_reactor
def test_unknown_kind_byte_diverts_to_raw_lane(clean_engine):
    """A fast-header frame whose kind byte is outside the known codes
    must NOT be parsed natively: it arrives as verbatim RAW bytes so
    the Python lane fails on it exactly like the fallback would."""
    assert reactor.engage()
    hdr = _FAST.pack(7, 1, 2, 42, 9, 6, 5, 0, -1)   # kind code 6: unknown
    frame = _LEN.pack(1 + len(hdr) + 5) + bytes((1,)) + hdr + b"xxxxx"
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append((et, bytes(pl))) or 1)
    b.sendall(frame)
    _drain_until(lambda: records)
    assert records[0][0] == reactor.REC_RAW
    assert records[0][1] == frame[_LEN.size:]
    # and the Python parse of those bytes raises the same KeyError the
    # selector lane raises for an unknown kind code
    btl = tcp_mod.TcpBtl()
    with pytest.raises(KeyError):
        btl._parse_frame(tcp_mod._Conn(None, rank=1), records[0][1])
    reactor.remove(a.fileno())
    a.close()
    b.close()


@needs_reactor
def test_crc_armed_frames_take_raw_lane_and_verify(clean_engine):
    assert reactor.engage()
    payload = np.arange(64, dtype=np.uint8)
    frame = encode(Frag(3, 0, 1, 5, 9, MATCH, payload, total_len=64),
                   cksum=True)
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append((et, bytes(pl))) or 1)
    b.sendall(frame)
    _drain_until(lambda: records)
    assert records[0][0] == reactor.REC_RAW     # crc bit -> slow lane
    btl = tcp_mod.TcpBtl()
    frag = btl._parse_frame(tcp_mod._Conn(None, rank=0), records[0][1])
    assert bytes(memoryview(frag.data)) == bytes(payload)
    reactor.remove(a.fileno())
    a.close()
    b.close()


# -- completed-record plumbing -----------------------------------------

@needs_reactor
def test_oversize_frame_parks_and_resumes(clean_engine):
    """A frame above the oversize limit parks its stream; take_oversize
    fetches the whole frame and the stream resumes with the trailing
    bytes intact."""
    assert reactor.engage()
    big = os.urandom(5 << 20)        # > the 4MB default oversize limit
    bighdr = _FAST.pack(7, 1, 2, 42, 10, 0, len(big), 0, -1)
    bigframe = _LEN.pack(1 + len(bighdr) + len(big)) \
        + bytes((1,)) + bighdr + big
    tail = encode(Frag(3, 0, 1, 5, 11, MATCH,
                       np.arange(9, dtype=np.uint8), total_len=9))
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append((et, bytes(pl))) or 1)
    t = threading.Thread(target=lambda: b.sendall(bigframe + tail))
    t.start()
    _drain_until(lambda: records)
    assert records[0][0] == reactor.REC_OVERSIZE
    (flen,) = struct.unpack("<Q", records[0][1])
    assert flen == len(bigframe) - _LEN.size
    got = reactor.take_oversize(a.fileno())
    assert bytes(got) == bigframe[_LEN.size:]
    _drain_until(lambda: len(records) >= 2)
    t.join()
    assert records[1][0] == reactor.REC_FAST
    assert records[1][1] == tail[_LEN.size + 1:]
    reactor.remove(a.fileno())
    a.close()
    b.close()


@needs_reactor
def test_desync_record_fails_loudly(clean_engine):
    """A zero-length frame on the wire is a framing desync: the reactor
    emits DESYNC and the btl dispatch raises SanitizeError (the
    selector lane's sanitizer does the same check in _on_bytes)."""
    from ompi_tpu.runtime import sanitizer

    assert reactor.engage()
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append((et, bytes(pl))) or 1)
    b.sendall(_LEN.pack(0))
    _drain_until(lambda: records)
    assert records[0][0] == reactor.REC_DESYNC
    btl = tcp_mod.TcpBtl()
    conn = tcp_mod._Conn(a, rank=3)
    with pytest.raises(sanitizer.SanitizeError):
        btl._reactor_event(conn, reactor.REC_DESYNC, records[0][1])
    reactor.remove(a.fileno())
    a.close()
    b.close()


@needs_reactor
def test_doorbell_drain_mode_consumes_dgrams(clean_engine):
    """MODE_DRAIN: the epoll thread consumes doorbell dgrams (the sm
    wakeup) and surfaces one DOORBELL record — Python never loops on
    recv(512)."""
    assert reactor.engage()
    rx, tx = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
    rx.setblocking(False)
    records = []
    assert reactor.add(rx.fileno(), reactor.MODE_DRAIN,
                       lambda et, pl: records.append(et) or 1)
    for _ in range(3):
        tx.send(b"x")
    _drain_until(lambda: records)
    assert records[0] == reactor.REC_DOORBELL
    time.sleep(0.05)
    with pytest.raises(BlockingIOError):
        rx.recv(512)                 # dgrams already consumed natively
    reactor.remove(rx.fileno())
    rx.close()
    tx.close()


@needs_reactor
def test_writable_record_after_want_write(clean_engine):
    """EPOLLOUT interest is oneshot-by-contract: one WRITABLE record
    per want_write arm, auto-cleared on fire."""
    assert reactor.engage()
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append(et) or 1)
    assert reactor.want_write(a.fileno(), True)
    _drain_until(lambda: records)
    assert records[0] == reactor.REC_WRITABLE
    time.sleep(0.05)
    reactor.drain()
    assert records.count(reactor.REC_WRITABLE) == 1   # interest cleared
    reactor.remove(a.fileno())
    a.close()
    b.close()


@needs_reactor
def test_notify_fd_wakes_idle_wait(clean_engine):
    assert reactor.engage()
    a, b = _stream_pair()
    got = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: got.append(et) or 1)
    reactor.drain()                  # settle any startup records

    def poke():
        time.sleep(0.1)
        b.sendall(encode(Frag(3, 0, 1, 5, 9, MATCH,
                              np.arange(4, dtype=np.uint8), total_len=4)))

    t = threading.Thread(target=poke)
    t.start()
    t0 = time.monotonic()
    woke = progress.idle_wait(3.0)
    dt = time.monotonic() - t0
    t.join()
    assert woke, "native completion must wake the idle waiter"
    assert dt < 1.0, f"woke after {dt:.3f}s — notify fd not registered?"
    reactor.remove(a.fileno())
    a.close()
    b.close()


@needs_reactor
def test_eof_record_and_stats(clean_engine):
    assert reactor.engage()
    a, b = _stream_pair()
    records = []
    assert reactor.add(a.fileno(), reactor.MODE_STREAM,
                       lambda et, pl: records.append(et) or 1)
    b.close()
    _drain_until(lambda: records)
    assert records[-1] == reactor.REC_EOF
    st = reactor.stats()
    assert st["active"] and st["records"] >= 1
    reactor.remove(a.fileno())
    a.close()


# -- progress.idle_wait teardown race (the satellite regression) -------

def test_idle_wait_retries_after_waiter_unregistered_mid_wait():
    """A waiter whose fd dies mid-select must not burn the full timeout:
    idle_wait prunes the dead registration and keeps waiting on the
    survivors, which can still wake it early."""
    dead_a, dead_b = socket.socketpair()
    live_a, live_b = socket.socketpair()
    progress.register_waiter(dead_a)
    progress.register_waiter(live_a)

    def chaos_then_wake():
        time.sleep(0.1)
        # teardown race: the fd closes while idle_wait is in select()
        dead_a.close()
        dead_b.close()
        time.sleep(0.1)
        live_b.sendall(b"!")

    t = threading.Thread(target=chaos_then_wake)
    t.start()
    try:
        t0 = time.monotonic()
        woke = progress.idle_wait(5.0)
        dt = time.monotonic() - t0
        assert woke, "the surviving waiter's byte must wake idle_wait"
        assert dt < 4.0, \
            f"idle_wait burned {dt:.2f}s — blind-sleep regression"
    finally:
        t.join()
        progress.unregister_waiter(dead_a)
        progress.unregister_waiter(live_a)
        live_a.close()
        live_b.close()


def test_idle_wait_select_oserror_prunes_and_retries(monkeypatch):
    """Drive the OSError branch directly (selector backends differ in
    when they raise): the first select blows up, the dead registration
    is pruned, and the retry on the survivor still wakes early."""
    live_a, live_b = socket.socketpair()
    dead_a, dead_b = socket.socketpair()
    progress.register_waiter(live_a)
    progress.register_waiter(dead_a)
    # close the raw fd out from under the selector (what _drop_conn's
    # concurrent teardown does) so the registration is stale
    os.close(dead_a.fileno())
    real_select = progress._waiter_sel.select
    calls = {"n": 0}

    def flaky_select(timeout=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(9, "Bad file descriptor")
        return real_select(timeout)

    monkeypatch.setattr(progress._waiter_sel, "select", flaky_select)
    threading.Timer(0.1, lambda: live_b.sendall(b"!")).start()
    t0 = time.monotonic()
    woke = progress.idle_wait(5.0)
    dt = time.monotonic() - t0
    assert woke and dt < 4.0, (woke, dt)
    assert calls["n"] >= 2, "select was not retried after the OSError"
    monkeypatch.undo()
    progress.unregister_waiter(live_a)
    progress.unregister_waiter(dead_a)
    live_a.close()
    detached = dead_a.detach()      # fd already closed above
    dead_b.close()
    assert detached >= 0


def test_idle_wait_all_waiters_dead_sleeps_remaining(monkeypatch):
    """When every registration is dead the retry loop must degrade to
    the bounded sleep, never raise or spin."""
    dead_a, dead_b = socket.socketpair()
    before = progress._waiter_count
    progress.register_waiter(dead_a)
    os.close(dead_a.fileno())
    real_select = progress._waiter_sel.select

    def flaky_select(timeout=None):
        raise OSError(9, "Bad file descriptor")

    monkeypatch.setattr(progress._waiter_sel, "select", flaky_select)
    t0 = time.monotonic()
    woke = progress.idle_wait(0.3)
    dt = time.monotonic() - t0
    assert not woke
    assert 0.1 < dt < 2.0, dt
    assert progress._waiter_count == before, "dead waiter was not pruned"
    monkeypatch.undo()
    assert real_select is not None
    dead_a.detach()
    dead_b.close()


# -- fallback lane identity --------------------------------------------

def test_drain_is_identity_when_disengaged():
    """With no reactor engaged, drain() is two attribute loads and a
    return — no ctypes, no native call (the perf-guard identity pin
    leans on this)."""
    assert not reactor.active()
    assert reactor.drain() == 0


@needs_reactor
def test_tcp_btl_reports_native_counters(clean_engine):
    """The spc counters that attribute the two lanes exist and the
    reactor stats surface through reactor.stats()."""
    from ompi_tpu.runtime import spc

    spc.init()
    for name in ("progress_native_drains", "fastpath_native_frags",
                 "fastpath_native_raw"):
        assert name in spc._COUNTERS
    st = reactor.stats()
    assert {"configured", "available", "active"} <= set(st)
