"""osc/device — HBM window semantics on the 8-device CPU mesh."""
import numpy as np

import ompi_tpu
from ompi_tpu.api.win import Win


def _world():
    return ompi_tpu.init()


def test_device_window_put_get_accumulate():
    w = _world()
    if not w.rte.is_device_world or w.size < 2:
        import pytest

        pytest.skip("needs a device world")
    win = Win.create(w, size=8, dtype=np.float32, device=True)
    assert type(win.module).__name__ == "DeviceModule"
    assert win.device_array.shape == (w.size, 8)

    win.put(np.array([3.5, 4.5], np.float32), 1, offset=2)
    got = win.get(2, 1, offset=2)
    assert got.tolist() == [3.5, 4.5]

    win.accumulate(np.array([1.0], np.float32), 1, offset=2)
    assert win.get(1, 1, offset=2)[0] == 4.5

    old = win.get_accumulate(np.array([10.0], np.float32), 0, offset=0)
    assert old[0] == 0.0
    assert win.get(1, 0, offset=0)[0] == 10.0

    old = win.compare_and_swap(7.0, 10.0, 0, offset=0)
    assert old == 10.0 and win.get(1, 0, offset=0)[0] == 7.0

    # the window stays a device array (HBM residency)
    import jax

    assert isinstance(win.device_array, jax.Array)
    win.fence()
    win.free()
