"""Multi-process integration: tpurun + coordination service + btl/sm+tcp +
coll/basic — the ``mpirun -n N`` smoke tests of SURVEY §4."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra_env=None):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n), *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_mp_ring():
    r = _tpurun(4, [sys.executable, str(REPO / "examples" / "ring.py")])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "token now 0" in r.stdout


def test_mp_connectivity_sm_and_tcp_only():
    r = _tpurun(4, [sys.executable, str(REPO / "examples" / "connectivity.py")])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "connectivity OK: 4 ranks" in r.stdout
    # force the tcp path (exclude shared memory)
    r2 = _tpurun(3, ["--mca", "btl", "^sm",
                     sys.executable, str(REPO / "examples" / "connectivity.py")])
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "connectivity OK: 3 ranks" in r2.stdout


def test_mp_collectives_and_split(tmp_path):
    script = tmp_path / "coll.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        r = w.rank
        assert w.allreduce(np.array([float(r + 1)]))[0] == 10.0
        g = w.allgather(np.array([r * 10]))
        assert g.ravel().tolist() == [0, 10, 20, 30]
        assert w.scan(np.array([1]))[0] == r + 1
        assert w.exscan(np.array([1]))[0] == r
        a2a = w.alltoall(np.arange(4, dtype=np.int64) + 100 * r)
        assert a2a.ravel().tolist() == [r, 100 + r, 200 + r, 300 + r], a2a
        b = w.bcast(np.array([7.5]) if r == 2 else np.zeros(1), root=2)
        assert b[0] == 7.5
        sub = w.split(color=r % 2, key=-r)
        assert sub.size == 2
        # key=-r reverses rank order inside each color
        assert sub.rank == (1 if r < 2 else 0)
        rs = w.reduce_scatter(np.ones(8, np.float32))
        assert rs.tolist() == [4.0, 4.0]
        w.barrier()
        if r == 0:
            print("MP COLLECTIVES OK")
        ompi_tpu.finalize()
    """))
    r = _tpurun(4, [sys.executable, str(script)], timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MP COLLECTIVES OK" in r.stdout


def test_mp_rendezvous_large_message(tmp_path):
    script = tmp_path / "big.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        n = 1 << 18  # 2MB float64 >> sm/tcp eager limits -> RNDV path
        if w.rank == 0:
            data = np.arange(n, dtype=np.float64)
            w.send(data, dest=1, tag=5)
        elif w.rank == 1:
            buf = np.zeros(n, np.float64)
            st = w.recv(buf, source=0, tag=5)
            assert st._nbytes == n * 8
            assert buf[0] == 0 and buf[-1] == n - 1
            assert np.all(buf == np.arange(n))
            print("RNDV OK")
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, [sys.executable, str(script)], timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RNDV OK" in r.stdout


def test_tpurun_failure_teardown(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text(textwrap.dedent("""
        import sys, time, os
        if int(os.environ["OTPU_RANK"]) == 1:
            sys.exit(3)
        time.sleep(30)
    """))
    r = _tpurun(3, [sys.executable, str(script)], timeout=60)
    assert r.returncode == 3
    assert "terminated with exit code 3" in r.stderr


def test_mp_alltoallv_typed_and_alltoallw(tmp_path):
    """Host alltoallv returns rank r's block typed as sendbufs[r].dtype
    (regression: remote blocks used to come back as raw uint8 while the
    self block stayed typed); alltoallw retypes per peer."""
    script = tmp_path / "a2av.py"
    script.write_text("""
import numpy as np
import ompi_tpu

ompi_tpu.init()
w = ompi_tpu.COMM_WORLD
me, n = w.rank, w.size
rng = np.random.default_rng(5)              # same plan on every rank
base = rng.standard_normal((n, n, 40))
cnts = rng.integers(0, 40, (n, n))
send = [base[me, j, : cnts[me][j]].astype(np.float32) for j in range(n)]
got = w.alltoallv(send)
for src in range(n):
    blk = got[src]
    assert blk.dtype == np.float32, (src, blk.dtype)
    assert np.allclose(blk, base[src, me, : cnts[src][me]]
                       .astype(np.float32)), src
# w-variant: heterogeneous per-peer dtypes via recvtypes
send_w = [np.arange(4 + me, dtype=np.int64) if (me + j) % 2 == 0
          else np.arange(4 + me, dtype=np.float32) for j in range(n)]
rts = [np.int64 if (j + me) % 2 == 0 else np.float32 for j in range(n)]
got_w = w.alltoallw(send_w, rts)
for src in range(n):
    assert got_w[src].dtype == np.dtype(rts[src]), (src, got_w[src].dtype)
    assert np.allclose(got_w[src].astype(np.float64),
                       np.arange(4 + src)), src
if me == 0:
    print("a2av typed ok", flush=True)
ompi_tpu.finalize()
""")
    r = _tpurun(3, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "a2av typed ok" in r.stdout
