"""MPI_T tool-interface tests (``ompi/mpi/tool`` analog)."""
import numpy as np
import pytest

from ompi_tpu.api import tool
from ompi_tpu.api.errors import MpiError


@pytest.fixture(scope="module", autouse=True)
def world():
    """MPI init populates the registry (frameworks register their vars at
    open, exactly like the reference's lazy var registration)."""
    import ompi_tpu
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


@pytest.fixture(autouse=True)
def t_init():
    tool.init_thread()
    yield
    tool.finalize()


def test_requires_init():
    tool.finalize()           # undo the fixture's init
    with pytest.raises(MpiError):
        tool.cvar_get_num()
    tool.init_thread()        # restore for the fixture's finalize


def test_cvar_enumerate_read_write():
    n = tool.cvar_get_num()
    assert n > 0
    i = tool.cvar_get_index("otpu_coll_tuned_allreduce_algorithm")
    var = tool.cvar_get_info(i)
    assert var.name == "otpu_coll_tuned_allreduce_algorithm"
    old = tool.cvar_read(i)
    tool.cvar_write(i, "ring")
    assert tool.cvar_read(i) == "ring"
    assert var.source_detail == "MPI_T"
    tool.cvar_write(i, old or "")


def test_pvar_session_delta_semantics(world):
    w = world
    i = tool.pvar_get_index("otpu_runtime_spc_device_collectives")
    s1 = tool.pvar_session_create()
    s2 = tool.pvar_session_create()
    h1 = s1.handle_alloc(i)
    h1.start()
    w.allreduce_array(np.ones((w.size, 8), np.float32))
    # a second session's handle started later sees only ITS delta
    h2 = s2.handle_alloc(i)
    h2.start()
    w.allreduce_array(np.ones((w.size, 8), np.float32))
    assert h1.read() >= 2
    assert h2.read() >= 1
    assert h1.read() > h2.read()
    s1.handle_free(h1)
    tool.pvar_session_free(s2)


def test_categories_are_frameworks():
    n = tool.category_get_num()
    assert n > 0
    names = [tool.category_get_info(i)[0] for i in range(n)]
    assert "coll" in names
    cname, _desc, cvars = tool.category_get_info(names.index("coll"))
    assert any("coll" in v for v in cvars)


def test_bad_indices_raise():
    with pytest.raises(MpiError):
        tool.cvar_get_info(10 ** 9)
    with pytest.raises(MpiError):
        tool.pvar_get_info(-5)
    with pytest.raises(MpiError):
        tool.cvar_get_index("no_such_var_xyz")
