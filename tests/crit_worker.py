"""otpu-crit test worker: a fixed number of step-spanned rounds, each
one chaos-paceable ('delay:ms=8,rank=2,site=step' designs ONE slow
rank), mixing a collective with a p2p ring exchange so the merged
timeline carries both barrier edges (coll round keys) and message
edges (pml flow keys)."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.ft import chaos
from ompi_tpu.runtime import trace

w = ompi_tpu.init()
x = np.ones(1024, np.float32)          # 4KB payload
inbuf = np.empty_like(x)
right = (w.rank + 1) % w.size
left = (w.rank - 1) % w.size

for i in range(int(os.environ.get("CW_ITERS", "20"))):
    t0 = trace.now() if trace.enabled else 0
    if chaos.enabled:
        # the designed-straggler pacing point: the delay lands INSIDE
        # the step window, so the critical path must attribute the
        # step to the paced rank's own timeline
        chaos.pace("step")
    w.allreduce(x, op.SUM)
    w.sendrecv(x, right, inbuf, source=left, sendtag=5, recvtag=5)
    if trace.enabled:
        trace.span("step", "step", t0, args={"step": i})
print(f"CRIT WORKER DONE {w.rank}", flush=True)
ompi_tpu.finalize()
