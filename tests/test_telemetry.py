"""otpu-top — the live telemetry plane, flight recorder, and analyzer.

Five layers of coverage:

* trace snapshot/delta API: sampling never disturbs the live histogram
  populations;
* the sampler unit: schema'd samples, per-interval deltas, source
  registry semantics, zero-thread identity when off;
* otpu_top: table/parsable rendering and stale-rank flagging from
  canned samples, plus THE acceptance run — ``otpu_top --json``
  attached to a live 3-rank tpurun job observes per-rank counter
  deltas advancing within two sampling intervals;
* flight recorder: dump triggers and payload shape in-process, plus
  the acceptance run — a chaos ``kill:rank=2,step=7`` job leaves a
  gathered bundle whose clock-aligned event order places the victim's
  last events before the survivors' recovery spans;
* otpu_analyze: last-arrival attribution and skew on synthetic
  timelines, plus the acceptance run — a rank-scoped chaos ``delay``
  makes the analyzer name the designed-slow rank as straggler for
  >= 90% of collectives.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "telemetry_worker.py"


# ------------------------------------------------ trace snapshot/delta

def test_hist_snapshot_delta_never_resets():
    from ompi_tpu.runtime import trace

    trace.hist_reset("teletest")
    trace.hist_record("teletest", 4096, 1_000_000)
    snap1 = trace.hist_snapshot()
    trace.hist_record("teletest", 4096, 2_000_000)
    trace.hist_record("teletest", 64, 4_000_000)
    snap2 = trace.hist_snapshot()
    d = trace.hist_delta_stats(snap1, snap2)
    assert d["teletest"]["n"] == 2                 # both size bins merged
    assert d["teletest"]["sum_us"] == pytest.approx(6000.0)
    assert d["teletest"]["p99_us"] >= d["teletest"]["p50_us"] > 0
    # the LIVE population still holds all three records (no reset)
    key = ("teletest", int(4096).bit_length())
    assert trace.hist_snapshot()[key][0] == 2
    assert trace.hist_percentile("teletest", 0.5) > 0
    # an empty delta reports nothing (compact samples)
    assert trace.hist_delta_stats(snap2, trace.hist_snapshot()) == {}
    trace.hist_reset("teletest")


# --------------------------------------------------------- sampler unit

def _mk_world(monkeypatch, interval_ms=40):
    from ompi_tpu.base.var import registry
    from ompi_tpu.rte.coord import CoordServer
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import telemetry  # noqa: F401  (registers var)

    srv = CoordServer(1)
    monkeypatch.setenv("OTPU_COORD", f"{srv.addr[0]}:{srv.addr[1]}")
    monkeypatch.setenv("OTPU_RANK", "0")
    monkeypatch.setenv("OTPU_NPROCS", "1")
    # API-source set: the var registered long before this test ran, so
    # an env value could not be (re)applied now
    registry.lookup("otpu_telemetry_interval_ms").set(interval_ms)
    rt.reset_for_testing()
    import ompi_tpu

    w = ompi_tpu.init()
    return srv, w, rt


def test_sampler_publishes_schemad_deltas(monkeypatch):
    import numpy as np

    from ompi_tpu.runtime import telemetry

    srv, w, rt = _mk_world(monkeypatch)
    try:
        assert telemetry.enabled and telemetry._sampler is not None
        x = np.ones(256, np.float32)
        deadline = time.monotonic() + 5.0
        first = None
        while time.monotonic() < deadline:
            w.allreduce(x)
            raw = srv.collect("otpu_telemetry")
            if 0 in raw:
                s = json.loads(raw[0])
                if first is None:
                    first = s
                elif s["seq"] > first["seq"]:
                    break
            time.sleep(0.02)
        else:
            pytest.fail("no advancing telemetry samples within 5s")
        # every key is schema-declared; the builtins are all present
        assert set(s) <= set(telemetry.SCHEMA)
        for key in ("seq", "t", "rank", "interval_ms", "spc",
                    "spc_delta", "hist"):
            assert key in s, key
        assert s["rank"] == 0 and s["interval_ms"] == 40
        # component sources rode along (tcp registers at btl init,
        # progress at module import)
        assert "progress" in s and "callbacks" in s["progress"]
    finally:
        from ompi_tpu.base.var import registry

        registry.lookup("otpu_telemetry_interval_ms").set(0)
        rt.reset_for_testing()
        srv.close()
        from ompi_tpu.runtime import telemetry as t2

        assert t2.enabled is False and t2._sampler is None


def test_register_source_schema_enforced():
    from ompi_tpu.runtime import telemetry

    with pytest.raises(ValueError):
        telemetry.register_source("mystery", dict)
    with pytest.raises(ValueError):
        telemetry.register_source("seq", dict)     # builtin keys too
    telemetry.register_source("serving", lambda: {"queued": 1})
    telemetry.unregister_source("serving")


def test_bound_method_sources_drop_with_their_owner():
    """A torn-down component must neither be kept alive by the source
    registry nor keep publishing frozen stats: bound-method sources are
    WeakMethod-held and silently drop when the owner is collected."""
    import gc

    from ompi_tpu.runtime import telemetry

    class Owner:
        def stats(self):
            return {"queued": 1}

    o = Owner()
    telemetry.register_source("serving", o.stats)
    s = telemetry.Sampler(0, 100)
    assert s._sample_once().get("serving") == {"queued": 1}
    del o
    gc.collect()
    assert "serving" not in s._sample_once()
    assert "serving" not in telemetry._sources


# ------------------------------------------------------- otpu_top unit

def _sample(rank, seq, interval_ms=100, **extra):
    s = {"seq": seq, "t": time.time(), "rank": rank,
         "interval_ms": interval_ms,
         "spc": {"allreduce": 100.0, "bytes_sent": 1e6},
         "spc_delta": {"allreduce": 10.0, "bytes_sent": 4096.0},
         "hist": {"allreduce": {"n": 10, "sum_us": 1000.0,
                                "p50_us": 90.0, "p99_us": 200.0}}}
    s.update(extra)
    return s


def test_otpu_top_render_and_stale_flag():
    from ompi_tpu.tools import otpu_top

    session = otpu_top.TopSession.__new__(otpu_top.TopSession)
    session.nprocs = 3
    session._last_seq = {}
    session._last_advance = {}
    samples = {0: _sample(0, 5, tcp={"outq_frags": 2, "outq_bytes": 99,
                                     "conns": 1}),
               1: _sample(1, 7, chaos={"delay": 3}),
               2: None}
    now = time.monotonic()
    session._last_advance = {0: now, 1: now - 10.0}
    session._last_seq = {0: 5, 1: 7}
    table = otpu_top.render_table(session, samples, "allreduce")
    assert "90/200us" in table                     # hist cell rendered
    assert "STALE" in table                        # rank 2 has no sample
    lines = table.splitlines()                     # [hdr, r0, r1, r2]
    assert lines[1].strip().endswith("ok")         # rank 0 fresh
    assert "STALE" in lines[2]                     # rank 1 seq stalled
    assert "STALE" in lines[3]                     # rank 2 no sample
    # rates come from the sample's own interval: 10 msgs / 100ms
    assert otpu_top._rate(samples[0], ("allreduce",)) == \
        pytest.approx(100.0)
    parsable = otpu_top.render_table(session, samples, "allreduce",
                                     parsable=True)
    assert parsable.splitlines()[1].startswith("1:7:")
    # a long-dead rank's frozen KV sample is stale on the FIRST poll
    # too: the sample's own wall-clock age flags it even when seq
    # tracking has nothing to compare against
    frozen = _sample(0, 9)
    frozen["t"] = time.time() - 60.0
    session._last_advance[0] = now          # seq rule says "fresh"
    assert session.stale(0, frozen) is True


# --------------------------------------------------- flight recorder unit

def test_flight_dump_payload_and_once_guard(monkeypatch, tmp_path):
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import flight, trace

    srv, w, rt = _mk_world(monkeypatch, interval_ms=0)
    registry.lookup("otpu_flight_dir").set(str(tmp_path / "crash"))
    try:
        trace._set_enabled(True)
        trace.span("step", "coll", trace.now())
        flight.reset_for_testing()
        from ompi_tpu.runtime import init as rt_mod

        flight.arm(rt_mod.get_rte())
        path = flight.dump("sanitize", detail="unit")
        assert path and os.path.exists(path)
        d = json.loads(Path(path).read_text())
        for key in ("rank", "reason", "trace_tail", "coord_rpcs",
                    "chaos_events", "spc", "clock_offset_us",
                    "failed_ranks"):
            assert key in d, key
        assert d["reason"] == "sanitize" and d["rank"] == 0
        assert any(e.get("name") == "step" for e in d["trace_tail"])
        assert d["coord_rpcs"], "recent-RPC ring is empty"
        # published into the coord KV for the launcher-side gather
        assert 0 in srv.collect("otpu_flight")
        # a RECOVERABLE sanitize dump may be superseded by a fatal
        # trigger (the process's actual death must not go undumped)...
        path2 = flight.dump("abort")
        assert path2 and json.loads(
            Path(path2).read_text())["reason"] == "abort"
        # ...but after a fatal dump the once-guard is final
        assert flight.dump("uncaught") is None
        assert flight.dump("sanitize") is None
    finally:
        flight.reset_for_testing()
        rt.reset_for_testing()
        srv.close()


def test_flight_dump_bundles_profile_snapshot(monkeypatch, tmp_path):
    """otpu-prof satellite: an armed stage-clock/profiler plane rides
    in the crash dump — rank<r>.json shows where host time was going
    (stage histograms + phase-sample counts); an unarmed plane dumps
    ``profile: null`` rather than fabricating numbers."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import flight, profile

    srv, w, rt = _mk_world(monkeypatch, interval_ms=0)
    registry.lookup("otpu_flight_dir").set(str(tmp_path / "crash3"))
    try:
        flight.reset_for_testing()
        profile.reset_for_testing()
        from ompi_tpu.runtime import init as rt_mod

        flight.arm(rt_mod.get_rte())
        # unarmed: the dump records the absence honestly
        path = flight.dump("sanitize", detail="no profile")
        assert json.loads(Path(path).read_text())["profile"] is None
        # armed: stage histograms + profiler phase counts ride along
        profile._set_enabled(True)
        profile.stage_span("send.pack", profile.now() - 5000)
        p = profile.HostProfiler(rank=0, interval_ms=5)
        with profile._lock:
            profile._profiler = p
        p.samples = 3
        p.phase_counts = {"idle": 2, "other": 1}
        p.total_obs = 3
        p.blocked_obs = 2
        path = flight.dump("abort", detail="with profile")
        prof = json.loads(Path(path).read_text())["profile"]
        assert prof["stages"]["send.pack"]["n"] == 1
        assert prof["profiler"]["phases"] == {"idle": 2, "other": 1}
        assert prof["profiler"]["samples"] == 3
    finally:
        profile.reset_for_testing()
        flight.reset_for_testing()
        rt.reset_for_testing()
        srv.close()


def test_sanitizer_fail_triggers_flight_dump(monkeypatch, tmp_path):
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import flight, sanitizer

    srv, w, rt = _mk_world(monkeypatch, interval_ms=0)
    registry.lookup("otpu_flight_dir").set(str(tmp_path / "crash2"))
    try:
        flight.reset_for_testing()
        from ompi_tpu.runtime import init as rt_mod

        flight.arm(rt_mod.get_rte())
        with pytest.raises(sanitizer.SanitizeError):
            sanitizer.fail("ownership invariant broken")
        # the dump runs on its own short-lived thread (fail() may fire
        # under a declared lock; the dump dials the coord service)
        dump = tmp_path / "crash2" / "rank0.json"
        deadline = time.monotonic() + 10.0
        while not dump.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert dump.exists(), "async sanitize dump never landed"
        assert json.loads(dump.read_text())["reason"] == "sanitize"
    finally:
        flight.reset_for_testing()
        rt.reset_for_testing()
        srv.close()


# ------------------------------------------------------- analyzer unit

def _synthetic_events(rounds=10, ranks=3, slow_rank=2, skew_us=500.0):
    events = []
    t = 0.0
    for _k in range(rounds):
        for r in range(ranks):
            start = t + (skew_us if r == slow_rank else r * 10.0)
            events.append({"ph": "X", "cat": "coll", "name": "allreduce",
                           "ts": start, "dur": 600.0,
                           "pid": r, "args": {"cid": 0, "nbytes": 4096}})
        t += 5000.0
    return sorted(events, key=lambda e: e["ts"])


def test_analyze_last_arrival_and_skew():
    from ompi_tpu.tools import otpu_analyze

    rep = otpu_analyze.analyze(_synthetic_events())
    assert rep["straggler"]["rank"] == 2
    assert rep["straggler"]["fraction"] == 1.0
    cell = rep["collectives"]["allreduce/cid0"]
    assert cell["rounds"] == 10 and cell["straggler_rank"] == 2
    assert cell["skew_us"]["max"] == pytest.approx(500.0)
    assert rep["skew_us"]["p50"] == pytest.approx(500.0)
    assert set(rep["exposed_comm"]) == {"0", "1", "2"}
    # diff: straggler movement is flagged
    rep2 = otpu_analyze.analyze(_synthetic_events(slow_rank=1))
    d = otpu_analyze.diff_reports(rep, rep2)
    assert d["straggler_changed"] is True
    assert d["straggler"] == [2, 1]


def test_analyze_loads_payload_files(tmp_path):
    """Per-rank payload form: events are clock-corrected by each
    payload's offset before attribution."""
    from ompi_tpu.tools import otpu_analyze

    events = _synthetic_events(rounds=4)
    for r in range(3):
        mine = [dict(e, ts=e["ts"] + 1000.0 * r)  # skewed local clocks
                for e in events if e["pid"] == r]
        (tmp_path / f"trace_rank{r}.json").write_text(json.dumps(
            {"traceEvents": mine,
             "metadata": {"rank": r,
                          "clock_offset_us": 1000.0 * r}}))
    rep = otpu_analyze.analyze(
        otpu_analyze.load_events([str(tmp_path)]))
    assert rep["straggler"]["rank"] == 2
    assert rep["rounds_total"] == 4


# ------------------------------------------------- live jobs (tpurun)

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_tpurun(n, port, mca, cmd, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.pop("OTPU_COORD", None)
    argv = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
            "-n", str(n), "--coord-port", str(port), *extra]
    for k, v in mca:
        argv += ["--mca", k, v]
    argv += list(cmd)
    return subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait_coord(port, timeout=30.0):
    from ompi_tpu.rte.coord import CoordClient

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            c = CoordClient(addr=("127.0.0.1", port), timeout=2.0,
                            retries=0)
            c._rpc(op="ping")
            return c
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"coord service on :{port} never came up")


def test_otpu_top_attaches_to_live_job(tmp_path):
    """THE live-attach acceptance: otpu_top --json against a running
    3-rank job observes per-rank counter deltas advancing within two
    sampling intervals."""
    import contextlib
    import io

    from ompi_tpu.tools import otpu_top

    port = _free_port()
    env_extra = dict(os.environ)
    p = _launch_tpurun(
        3, port, [("otpu_telemetry_interval_ms", "150")],
        [sys.executable, str(WORKER)])
    try:
        c = _wait_coord(port)
        c.close()
        # poll every 0.15s: two sampler intervals = 300ms = 2 polls
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = otpu_top.main(["--coord", f"127.0.0.1:{port}",
                                "--json", "--interval", "0.15",
                                "--count", "20"])
        assert rc == 0
        polls = [json.loads(ln) for ln in
                 buf.getvalue().splitlines() if ln.strip()]
        assert polls and polls[0]["nprocs"] == 3
        # per-rank deltas advance within 2 sampling intervals: find,
        # for every rank, two polls <= 2 intervals apart whose seq
        # advanced and whose spc_delta shows traffic
        for rank in ("0", "1", "2"):
            seqs = [(poll["t"], poll["ranks"][rank]["seq"],
                     poll["ranks"][rank].get("spc_delta", {}))
                    for poll in polls
                    if poll["ranks"].get(rank)]
            assert seqs, f"rank {rank} never published"
            advanced = False
            for (t0, s0, _d0), (t1, s1, d1) in zip(seqs, seqs[1:]):
                if s1 > s0 and (t1 - t0) <= 0.45:
                    advanced = True
                    assert sum(d1.values()) > 0, (rank, d1)
                    break
            assert advanced, (rank, seqs)
    finally:
        out = p.communicate(timeout=120)[0]
    assert p.returncode == 0, out
    assert out.count("TELEMETRY WORKER DONE") == 3, out


_ELASTIC_FLIGHT_JOB = textwrap.dedent("""
    import sys
    import ompi_tpu
    from ompi_tpu.parallel.elastic import ElasticTrainer

    w = ompi_tpu.init()
    tr = ElasticTrainer(w, ckpt_dir=sys.argv[1], model_size=12,
                        global_batch=24, ckpt_every=5, respawn=False)
    tr.train(12)
    print("FLIGHTJOB DONE", w.rank, flush=True)
    ompi_tpu.finalize()
""")


def test_flight_bundle_on_chaos_kill(tmp_path):
    """THE flight-recorder acceptance: a chaos ``kill:rank=2,step=7``
    training run leaves a gathered bundle whose clock-aligned event
    order places the victim's last events before the survivors'
    revoke/shrink spans."""
    script = tmp_path / "job.py"
    script.write_text(_ELASTIC_FLIGHT_JOB)
    crash = tmp_path / "crash"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--enable-recovery",
           "--mca", "otpu_chaos_spec", "kill:rank=2,step=7",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", str(tmp_path / "trace"),
           "--mca", "otpu_flight_dir", str(crash),
           sys.executable, str(script), str(tmp_path / "ckpt")]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    bundle_path = crash / "bundle.json"
    assert bundle_path.exists(), out
    bundle = json.loads(bundle_path.read_text())
    dumps = bundle["dumps"]
    assert dumps["2"]["reason"] == "chaos-kill", out
    survivors = [r_ for r_ in ("0", "1") if r_ in dumps]
    assert survivors, f"no survivor dumps: {sorted(dumps)}\n{out}"
    for s in survivors:
        assert dumps[s]["reason"] == "proc-failed"
        assert 2 in dumps[s]["failed_ranks"]
    # the coord's own view saw the failure event
    assert 2 in bundle["coord"]["failed"]
    assert any(e["name"] == "proc_failed"
               for e in bundle["coord"]["events"])
    # clock-aligned ordering: the victim's last event precedes the
    # survivors' recovery (shrink) spans on the merged tail
    merged = bundle["merged_tail"]
    victim_ts = [e["ts"] for e in merged if e["pid"] == 2]
    shrink_ts = [e["ts"] for e in merged
                 if e["pid"] != 2 and str(e.get("name", ""))
                 .startswith("elastic_shrink")]
    assert victim_ts, "victim trace tail missing from the bundle"
    assert shrink_ts, "survivor shrink spans missing from the bundle"
    assert max(victim_ts) < min(shrink_ts), (
        f"victim events [{max(victim_ts)}] not ordered before "
        f"survivor shrink [{min(shrink_ts)}]")


def test_analyzer_names_designed_straggler(tmp_path):
    """THE analyzer acceptance: a chaos ``delay`` scoped to one rank
    (``delay:ms=8,rank=2,site=step`` — the per-step pacing point) of a
    3-rank collective loop — otpu_analyze names rank 2 as the
    straggler for >= 90% of collectives."""
    tdir = tmp_path / "trace"
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_ITERS="25")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--mca", "otpu_chaos_spec", "delay:ms=8,p=1,rank=2,site=step",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", str(tdir),
           sys.executable, str(WORKER)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    merged = tdir / "trace_merged.json"
    assert merged.exists(), out
    from ompi_tpu.tools import otpu_analyze

    rep = otpu_analyze.analyze(
        otpu_analyze.load_events([str(merged)]))
    assert rep["rounds_total"] >= 20, rep["rounds_total"]
    assert rep["straggler"]["rank"] == 2, rep["straggler"]
    assert rep["straggler"]["fraction"] >= 0.90, rep["straggler"]
    # the JSON report round-trips through the CLI --json/--diff path
    rep_path = tmp_path / "report.json"
    rc = otpu_analyze.main([str(merged), "--json", str(rep_path)])
    assert rc == 0
    again = json.loads(rep_path.read_text())
    assert again["straggler"]["rank"] == 2
    assert otpu_analyze.diff_reports(again, rep)[
        "straggler_changed"] is False
