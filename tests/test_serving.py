"""ompi_tpu/serving — the continuous-batching inference frontier.

Four layers of coverage:

* scheduler invariants (pure, no comm): strict-FIFO admission (no
  request starves), the batch never exceeds width/token/slot budgets,
  eviction without draining, requeue semantics;
* KV streaming (in-process loopback over mca/part): per-sequence
  ``Pready`` visibility, epoch exactness under MISMATCHED send/recv
  partition counts, epoch-desync loudness;
* the engine end to end in-process (router + worker threads over
  ``as_rank`` views): colocated and disaggregated stage modes, token
  bit-exactness, driver report sanity;
* multiprocess under tpurun: kill a worker mid-load and prove
  serve-through-failure (shrink to ``mpi://surviving``, re-shard, zero
  dropped requests), and (slow lane) autoscale via ``dpm.spawn`` +
  the ``mpi://job/<id>`` pset, plus the long Poisson soak.
"""
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                        RequestState, ServeRequest)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


# ---------------------------------------------------------------- scheduler

def test_scheduler_fifo_admission_no_starvation():
    """Admission is strictly arrival-ordered: with a stream of cheap
    requests behind one expensive head, nobody overtakes — and once
    capacity frees, the oldest queued request is always the next in."""
    s = ContinuousBatchScheduler(max_batch=2, max_batch_tokens=100)
    reqs = [s.submit(ServeRequest(10, 10)) for _ in range(8)]
    admitted_order = []
    for _ in range(40):
        admitted, _ = s.tick()
        admitted_order.extend(r.rid for r in admitted)
        s.check_invariants()
        for r in s.running():
            s.mark_done(r)
        if s.done_count() == len(reqs):
            break
    assert s.done_count() == len(reqs), "a request starved"
    assert admitted_order == [r.rid for r in reqs], \
        "admission broke arrival order"


def test_scheduler_budgets_hold_under_fuzz():
    rng = np.random.default_rng(7)
    s = ContinuousBatchScheduler(max_batch=4, max_batch_tokens=256,
                                 slots=6)
    live = []
    for step in range(300):
        if rng.random() < 0.5:
            s.submit(ServeRequest(int(rng.integers(1, 60)),
                                  int(rng.integers(1, 60))))
        admitted, evicted = s.tick()
        live.extend(admitted)
        s.check_invariants()
        assert len(s.running()) <= 4
        assert s.used_tokens() <= 256
        # finish a random running request now and then
        running = s.running()
        if running and rng.random() < 0.6:
            s.mark_done(running[int(rng.integers(len(running)))])
    # drain completely: every admitted request eventually evicts
    for _ in range(600):
        for r in s.running():
            s.mark_done(r)
        s.tick()
        s.check_invariants()
        if not s.running() and not s.depth():
            break
    assert not s.running() and not s.depth()


def test_scheduler_rejects_unadmittable_request():
    s = ContinuousBatchScheduler(max_batch=2, max_batch_tokens=64)
    with pytest.raises(MpiError) as ei:
        s.submit(ServeRequest(60, 10))      # cost 70 > 64: never fits
    assert ei.value.error_class is ErrorClass.ERR_ARG
    with pytest.raises(MpiError):
        ServeRequest(0, 4)                  # loud on degenerate lengths


def test_scheduler_eviction_without_drain():
    """Continuous batching: a short request admitted AFTER a long one
    completes and its freed capacity admits new work while the long
    request is still running — the batch never drains."""
    s = ContinuousBatchScheduler(max_batch=2, max_batch_tokens=1000)
    long_req = s.submit(ServeRequest(10, 100))
    short1 = s.submit(ServeRequest(10, 1))
    short2 = s.submit(ServeRequest(10, 1))
    s.tick()                          # admits long + short1 (width 2)
    assert short2.state is RequestState.QUEUED
    s.mark_done(short1)
    admitted, evicted = s.tick()      # short1 out, short2 in, long stays
    assert evicted == [short1] and admitted == [short2]
    assert long_req.state is RequestState.RUNNING
    assert long_req in s.running() and short2 in s.running()
    s.check_invariants()


def test_scheduler_requeue_skips_done_and_preserves_order():
    s = ContinuousBatchScheduler(max_batch=4, max_batch_tokens=1000)
    reqs = [s.submit(ServeRequest(5, 5)) for _ in range(4)]
    s.tick()
    s.mark_done(reqs[0])              # done-but-not-evicted at failure
    running = s.running()
    s.requeue(running)
    # the DONE request must NOT come back; the rest queue in arrival
    # order at the head with slots/token budget returned
    assert reqs[0].state is RequestState.DONE
    assert [r.rid for r in s._sq] == [r.rid for r in reqs[1:]]
    for r in reqs[1:]:
        assert r.state is RequestState.QUEUED and r.slot is None
        assert not r.prefilled
    s.tick()                          # evicts the done one, re-admits
    s.check_invariants()
    assert {r.rid for r in s.running()} == {r.rid for r in reqs[1:]}


# ------------------------------------------------------------ in-process env

@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    from ompi_tpu.mca.part import part_framework

    part_framework().open()
    yield w
    rt.reset_for_testing()


def _run_workers(workers):
    threads = [threading.Thread(target=wk.serve, daemon=True)
               for wk in workers]
    for t in threads:
        t.start()
    return threads


# ---------------------------------------------------------------- kv stream

def test_kv_stream_pready_per_sequence_and_mismatched_counts(world):
    """One stage pair on loopback: receiver partitions = 2x sender
    slots.  A slot's block is visible (Parrived exact at sub-slot
    granularity) as soon as ITS Pready lands, before the epoch's tail
    flush; values are bit-exact across restarted epochs."""
    from ompi_tpu.serving.kv_stream import KvSlabReceiver, KvSlabSender
    from ompi_tpu.serving.worker import toy_kv
    from ompi_tpu.runtime.progress import progress

    a, b = world.as_rank(0), world.as_rank(1)
    snd = KvSlabSender(a, peer=1, slots=4, elems_per_slot=32, tag=77)
    rcv = KvSlabReceiver(b, peer=0, slots=4, elems_per_slot=32, tag=77,
                         partitions=8)
    for epoch in range(3):
        snd.begin_epoch(epoch)
        rcv.begin_epoch(epoch)
        snd.write_slot(2, toy_kv(epoch * 10 + 2, 32))
        snd.slot_ready(2)
        for _ in range(200):
            if rcv.slot_arrived(2):
                break
            progress()
        assert rcv.slot_arrived(2), "readied slot never arrived"
        np.testing.assert_array_equal(rcv.read_slot(2),
                                      toy_kv(epoch * 10 + 2, 32))
        snd.write_slot(0, toy_kv(epoch * 10, 32))
        snd.slot_ready(0)
        snd.finish_epoch(wait=True)    # aggregated tail flush
        rcv.finish_epoch()
        np.testing.assert_array_equal(rcv.read_slot(0),
                                      toy_kv(epoch * 10, 32))
    snd.free()
    rcv.free()


def test_kv_stream_epoch_desync_is_loud(world):
    from ompi_tpu.serving.kv_stream import KvSlabReceiver, KvSlabSender

    a, b = world.as_rank(2), world.as_rank(3)
    snd = KvSlabSender(a, peer=3, slots=2, elems_per_slot=8, tag=78)
    rcv = KvSlabReceiver(b, peer=2, slots=2, elems_per_slot=8, tag=78)
    with pytest.raises(MpiError):
        snd.begin_epoch(1)             # epochs are consecutive from 0
    snd.begin_epoch(0)
    rcv.begin_epoch(0)
    with pytest.raises(MpiError):
        rcv.read_slot(0)               # read before arrival is an error
    with pytest.raises(MpiError):
        KvSlabReceiver(b, peer=2, slots=2, elems_per_slot=8, tag=79,
                       partitions=3)   # partitions must tile slots
    snd.finish_epoch(wait=True)
    rcv.finish_epoch()
    snd.free()
    rcv.free()


# ------------------------------------------------------------- end to end

def test_colocated_engine_end_to_end(world):
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker
    from ompi_tpu.serving.driver import PoissonDriver
    from ompi_tpu.serving.worker import toy_token

    workers = [ShardWorker(world.as_rank(r), router=0) for r in (1, 2)]
    threads = _run_workers(workers)
    r = Router(world.as_rank(0),
               scheduler=ContinuousBatchScheduler(max_batch=4,
                                                  max_batch_tokens=4096),
               workers=[1, 2], decode_chunk=4)
    rep = PoissonDriver(rate_rps=800, n_requests=24,
                        seed=3).run(r, max_wall_s=90)
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert rep["requests"] == 24
    assert rep["tokens"] > 0 and rep["tokens_per_s"] > 0
    # percentile report comes from the otpu-trace histogram; the exact
    # p99 over the driver's own samples must sit within the estimator's
    # one-log2-bin contract (factor-2 band) of it
    assert rep["p50_ms"] > 0 and rep["p99_ms"] > 0
    assert rep["p99_ms"] <= rep["p99_exact_ms"] * 2.0 + 1.0
    assert rep["p99_exact_ms"] <= rep["p99_ms"] * 2.0 + 1.0
    for req in r.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


def test_stages_engine_kv_streams_end_to_end(world):
    """Disaggregated prefill/decode pair with a mismatched receiver
    partition count: every KV block is verified bit-exact by the decode
    stage (ShardWorker raises on corruption), every token by the
    router."""
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker
    from ompi_tpu.serving.driver import PoissonDriver
    from ompi_tpu.serving.worker import toy_token

    pre = ShardWorker(world.as_rank(1), router=0, role="prefill",
                      peer=2, slots=8, kv_elems=64)
    dec = ShardWorker(world.as_rank(2), router=0, role="decode",
                      peer=1, slots=8, kv_elems=64, kv_partitions=16)
    threads = _run_workers([pre, dec])
    r = Router(world.as_rank(0),
               scheduler=ContinuousBatchScheduler(max_batch=8,
                                                  max_batch_tokens=8192,
                                                  slots=8),
               workers=[1, 2], stages=True, decode_chunk=3, kv_elems=64)
    rep = PoissonDriver(rate_rps=800, n_requests=16,
                        seed=4).run(r, max_wall_s=90)
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert rep["requests"] == 16
    from ompi_tpu.runtime import spc

    assert spc.read("serve_kv_epochs") > 0, "stages mode never streamed"
    for req in r.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


def test_stages_two_pairs_staggered_epochs(world):
    """Two prefill/decode pairs with admissions landing on DIFFERENT
    ticks per pair: KV epochs are counted per pair, so a pair that sat
    out a round must not desync (the global-epoch bug the review
    caught)."""
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker
    from ompi_tpu.serving.worker import toy_token

    pre1 = ShardWorker(world.as_rank(1), router=0, role="prefill",
                       peer=3, slots=4, kv_elems=32)
    pre2 = ShardWorker(world.as_rank(2), router=0, role="prefill",
                       peer=4, slots=4, kv_elems=32)
    dec1 = ShardWorker(world.as_rank(3), router=0, role="decode",
                       peer=1, slots=4, kv_elems=32)
    dec2 = ShardWorker(world.as_rank(4), router=0, role="decode",
                       peer=2, slots=4, kv_elems=32)
    threads = _run_workers([pre1, pre2, dec1, dec2])
    r = Router(world.as_rank(0),
               scheduler=ContinuousBatchScheduler(max_batch=2,
                                                  max_batch_tokens=4096,
                                                  slots=4),
               workers=[1, 2, 3, 4], stages=True, decode_chunk=2,
               kv_elems=32)
    # staggered: narrow batch means later admissions land on whichever
    # pair freed up — pairs see fresh batches on different ticks
    for i in range(8):
        r.submit(4 + i, 2 + (i % 5))
    done = r.serve_until_drained(max_ticks=5000)
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 8
    for req in done:
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


def test_stages_odd_worker_serves_colocated(world):
    """An odd worker count in stages mode must not strand the leftover
    rank: it serves colocated and takes admissions."""
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker

    pre = ShardWorker(world.as_rank(1), router=0, role="prefill",
                      peer=2, slots=4, kv_elems=32)
    dec = ShardWorker(world.as_rank(2), router=0, role="decode",
                      peer=1, slots=4, kv_elems=32)
    extra = ShardWorker(world.as_rank(3), router=0)   # colocated
    threads = _run_workers([pre, dec, extra])
    r = Router(world.as_rank(0),
               scheduler=ContinuousBatchScheduler(max_batch=4,
                                                  max_batch_tokens=4096,
                                                  slots=4),
               workers=[1, 2, 3], stages=True, decode_chunk=2,
               kv_elems=32)
    for i in range(10):
        r.submit(6, 4)
    done = r.serve_until_drained(max_ticks=5000)
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 10
    assert {q.worker for q in done} >= {2, 3}, \
        "the leftover rank never took work"


@pytest.mark.slow
def test_poisson_soak_invariants(world):
    """Long open-loop soak: heavy offered load, invariants checked on
    every tick, every request completes bit-exactly."""
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker
    from ompi_tpu.serving.driver import PoissonDriver
    from ompi_tpu.serving.worker import toy_token

    workers = [ShardWorker(world.as_rank(r), router=0) for r in (1, 2, 3)]
    threads = _run_workers(workers)
    sched = ContinuousBatchScheduler(max_batch=6, max_batch_tokens=4096)
    r = Router(world.as_rank(0), scheduler=sched, workers=[1, 2, 3],
               decode_chunk=2)
    drv = PoissonDriver(rate_rps=300, n_requests=200,
                        prompt_lens=(4, 96), decode_lens=(1, 48), seed=11)
    # drive manually so invariants run each tick
    import time as _time

    t0 = _time.perf_counter()
    while True:
        elapsed = _time.perf_counter() - t0
        assert elapsed < 300, "soak did not drain"
        for p, d in drv.due(elapsed):
            r.submit(p, d)
        r.tick()
        sched.check_invariants()
        if drv.exhausted and not sched.depth() and not sched.running():
            break
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(r.completed()) == 200
    for req in r.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


# ------------------------------------------------------------- multiprocess

def test_serve_through_failure_zero_dropped(tmp_path):
    """The acceptance scenario: kill a worker mid-load under
    ``--enable-recovery``; the router revokes, shrinks to
    ``mpi://surviving``, re-shards its worker table, requeues the dead
    worker's in-flight requests, and EVERY admitted request completes
    bit-exactly."""
    script = tmp_path / "serve_fail.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        import ompi_tpu
        from ompi_tpu.serving import (ContinuousBatchScheduler, Router,
                                      ShardWorker)
        from ompi_tpu.serving.worker import toy_token

        w = ompi_tpu.init()
        if w.rank == 0:
            r = Router(w, scheduler=ContinuousBatchScheduler(
                           max_batch=6, max_batch_tokens=1 << 14),
                       decode_chunk=2)
            subs = [r.submit(8 + (i % 5), 6 + (i % 7)) for i in range(24)]
            done = r.serve_until_drained(max_ticks=20000)
            assert len(done) == 24, (len(done), 24)
            assert len({q.rid for q in done}) == 24, "duplicate finishes"
            for q in subs:
                assert q.tokens == [toy_token(q.rid, i)
                                    for i in range(q.max_new_tokens)], q
            assert r.lost_and_requeued > 0, "victim died, nothing requeued"
            assert len(r.workers) == 2, r.workers
            # the surviving pset the recovery rode is now advertised
            s = ompi_tpu.Session.init()
            assert "mpi://surviving" in s.psets()
            surv = s.group_from_pset("mpi://surviving")
            assert 2 not in surv.world_ranks
            s.finalize()
            r.shutdown()
            print(f"ROUTER OK requeued={r.lost_and_requeued}", flush=True)
        elif w.rank == 2:
            # chaos kill schedule replaces the old hand-rolled Victim
            # subclass: permit 2 micro-batches, die on the 3rd —
            # mid-load, results unsent (ShardWorker._on_work hosts the
            # serve_work kill point)
            from ompi_tpu.ft import chaos
            chaos.install_spec("kill:rank=2,site=serve_work,count=2")
            ShardWorker(w, router=0).serve()
        else:
            ShardWorker(w, router=0).serve()
            print(f"WORKER {w.rank} OK", flush=True)
    """))
    r = _tpurun(4, script, extra=("--enable-recovery",), timeout=300)
    assert "ROUTER OK" in r.stdout, r.stdout + r.stderr
    assert r.stdout.count("WORKER") == 2, r.stdout + r.stderr


@pytest.mark.slow
def test_autoscale_spawns_workers_via_job_pset(tmp_path):
    """Queue depth above the watermark spawns a fresh worker process
    (``dpm.spawn``), whose membership is verified against the dynamic
    ``mpi://job/<id>`` pset before merging into the serving comm."""
    script = tmp_path / "serve_scale.py"
    script.write_text(textwrap.dedent("""
        import sys
        import ompi_tpu
        from ompi_tpu.serving import (ContinuousBatchScheduler, Router,
                                      ShardWorker)
        from ompi_tpu.serving.worker import toy_token
        from ompi_tpu.runtime import spc

        w = ompi_tpu.init()
        if w.rank == 0:
            r = Router(w, scheduler=ContinuousBatchScheduler(
                           max_batch=2, max_batch_tokens=1 << 13),
                       decode_chunk=2, scale_watermark=3, scale_step=1,
                       scale_patience=2,
                       scale_argv=[sys.executable, "-m",
                                   "ompi_tpu.serving.worker"])
            subs = [r.submit(8, 8) for _ in range(12)]
            done = r.serve_until_drained(max_ticks=20000)
            assert len(done) == 12, len(done)
            for q in subs:
                assert q.tokens == [toy_token(q.rid, i)
                                    for i in range(q.max_new_tokens)]
            assert spc.read("serve_scaleups") >= 1, "never scaled"
            assert len(r.workers) == 2 and r.comm.size == 3
            r.shutdown()
            print(f"SCALE OK workers={r.workers}", flush=True)
        else:
            ShardWorker(w, router=0).serve()
            print("BASE WORKER OK", flush=True)
    """))
    r = _tpurun(2, script, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SCALE OK" in r.stdout, r.stdout + r.stderr


def test_tpurun_serving_role_flags(tmp_path):
    """--router-ranks/--worker-ranks publish the serving psets and
    roles() resolves placement from them (router NOT rank 0 here)."""
    script = tmp_path / "roles.py"
    script.write_text(textwrap.dedent("""
        import ompi_tpu
        from ompi_tpu import serving

        w = ompi_tpu.init()
        router, workers = serving.roles(w)
        assert router == 1, (router, workers)
        assert workers == [0, 2], (router, workers)
        print(f"ROLES OK {w.rank}", flush=True)
    """))
    r = _tpurun(3, script,
                extra=("--router-ranks", "1", "--worker-ranks", "0,2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("ROLES OK") == 3
