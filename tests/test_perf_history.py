"""The perf-regression history plane: BENCH_HISTORY.jsonl schema,
otpu_perf's comparator, and THE chaos-slowdown acceptance.

* ``otpu_perf --check`` against the COMMITTED seed — the tier-1 gate
  the satellite demands: a schema or comparator regression fails CI
  loudly;
* comparator units: noise band, min-of-history baseline poisoning,
  malformed-file rejection, ladder-kind rows;
* THE acceptance — ``bench.py --history`` twice clean, then once with
  an injected chaos ``delay:ms=...`` wire fault: ``otpu_perf --diff``
  exits nonzero on the injected slowdown while the clean repeat passed
  inside the noise band.  (Load-sensitive ABSOLUTE pins stay in
  tests/bench_pins.json — this file pins only the comparator's
  relative behavior.)
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _mk(run, t, key, lat, kind="bench", **extra):
    row = {"v": 1, "kind": kind, "run": run, "t": t, "key": key,
           "lat_us": lat, "k": 3}
    row.update(extra)
    return row


# -------------------------------------------------- committed-seed gate

def test_history_check_committed_seed():
    """The tier-1 CI gate: the committed BENCH_HISTORY.jsonl seed must
    validate (schema v1, parseable rows, >= 1 bench run) and the
    comparator self-test must hold."""
    from ompi_tpu.tools import otpu_perf

    seed = REPO / "BENCH_HISTORY.jsonl"
    assert seed.exists(), "committed BENCH_HISTORY.jsonl seed missing"
    assert otpu_perf.main([str(seed), "--check"]) == 0
    rows, errors = otpu_perf.load_history(str(seed))
    assert not errors and rows
    # every committed row carries the topology label the ladder rules
    # derivation (ROADMAP item 3) will group by
    assert all("topology" in r for r in rows)


# ---------------------------------------------------- comparator units

def test_comparator_noise_band_and_baseline():
    from ompi_tpu.tools import otpu_perf

    rows = [_mk("r1", 1, "x", 100.0), _mk("r2", 2, "x", 130.0)]
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0)
    assert res["regressions"] == 0
    assert res["rows"][0]["status"] == "ok"
    # beyond the band: regression
    rows.append(_mk("r3", 3, "x", 100.0 * 1.5 + 11.0))
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0)
    assert res["regressions"] == 1
    assert res["rows"][0]["status"] == "REGRESSED"
    # a later clean run is compared against the rolling MIN — the slow
    # r3 does not poison the baseline
    rows.append(_mk("r4", 4, "x", 105.0))
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0)
    assert res["regressions"] == 0
    # keys with no prior history report as new, never regress
    rows.append(_mk("r5", 5, "y", 50.0))
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0)
    statuses = {r["key"]: r["status"] for r in res["rows"]}
    assert statuses["y"] == "new"
    assert res["regressions"] == 0


def test_comparator_window_limits_baseline():
    from ompi_tpu.tools import otpu_perf

    # an ancient fast run outside the window must NOT set the baseline
    rows = [_mk("r0", 0, "x", 10.0)]
    rows += [_mk(f"r{i}", i, "x", 200.0) for i in range(1, 5)]
    rows.append(_mk("r9", 9, "x", 210.0))
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0,
                            window=3)
    assert res["regressions"] == 0, res


def test_ladder_rows_compare_by_cell():
    from ompi_tpu.tools import otpu_perf

    def lad(run, t, alg, lat):
        return {"v": 1, "kind": "ladder", "run": run, "t": t,
                "topology": "host_sm_n2", "coll": "allreduce",
                "nbytes": 4096, "algorithm": alg, "lat_us": lat, "k": 2}

    rows = [lad("r1", 1, "ring", 200.0), lad("r1", 1, "rd", 100.0),
            lad("r2", 2, "ring", 205.0), lad("r2", 2, "rd", 400.0)]
    res = otpu_perf.compare(rows, band_rel=0.5, band_abs_us=10.0,
                            kind="ladder")
    by_key = {r["key"]: r["status"] for r in res["rows"]}
    assert by_key["ladder/host_sm_n2/allreduce/4096/rd"] == "REGRESSED"
    assert by_key["ladder/host_sm_n2/allreduce/4096/ring"] == "ok"


def test_check_rejects_malformed_history(tmp_path):
    from ompi_tpu.tools import otpu_perf

    bad = tmp_path / "hist.jsonl"
    bad.write_text(
        json.dumps(_mk("r1", 1, "x", 100.0)) + "\n"
        + "this is not json\n"
        + json.dumps({"v": 1, "kind": "bench", "run": "r2"}) + "\n"
        + json.dumps(_mk("r3", 3, "x", -5.0)) + "\n"
        + json.dumps(_mk("r4", 4, "x", 100.0, v=99)) + "\n"
        + json.dumps(_mk("r5", 5, "x", 100.0, kind="mystery")) + "\n")
    rows, errors = otpu_perf.load_history(str(bad))
    assert len(rows) == 1 and len(errors) == 5
    assert otpu_perf.main([str(bad), "--check"]) == 1
    # an empty file is a check failure too, not a silent pass
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert otpu_perf.main([str(empty), "--check"]) == 1


# ------------------------------------------------- THE acceptance run

def _run_history(history, env_extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               OTPU_BENCH_HISTORY_FILE=str(history),
               OTPU_BENCH_HISTORY_POINTS="allreduce:4096",
               OTPU_BENCH_HISTORY_REPS="4",
               OTPU_BENCH_HISTORY_BATCH="15")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.update(env_extra)
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--history"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.strip(), "history run produced no rows"


def test_history_diff_catches_injected_slowdown(tmp_path):
    """bench.py --history twice (clean) -> otpu_perf --diff passes
    inside the noise band; a third run with an injected chaos wire
    delay -> --diff flags it and exits nonzero (3)."""
    from ompi_tpu.tools import otpu_perf

    history = tmp_path / "hist.jsonl"
    _run_history(history, {})
    _run_history(history, {})
    # clean repeat: inside the noise band, exit 0
    assert otpu_perf.main([str(history), "--diff"]) == 0
    # injected slowdown: 5ms per wire send on a ~1ms baseline
    _run_history(history, {"OTPU_MCA_chaos_spec": "delay:ms=5,p=1"})
    assert otpu_perf.main([str(history), "--diff"]) == 3
    res = otpu_perf.compare(otpu_perf.load_history(str(history))[0])
    assert res["regressions"] == 1
    assert res["rows"][0]["ratio"] > 1.5, res
