"""Seeded fault-injection fuzz for the coordination-free ERA agreement
(``ompi_tpu/ft/agreement.py`` ``agree_p2p``).

Each seed drives one tpurun job (``tests/fuzz_agree_worker.py``) whose
rounds replay a deterministic adversarial scenario: randomized kill
subsets with precise protocol-phase triggers (root dying between
prepare-complete and commit, partial commit broadcasts, cascading
root+takeover deaths), false-suspicion injection on the real
propagation carriers, and concurrent agreement instances on two comms.
Every round asserts ERA's uniformity property: all survivors that
return a value return the SAME value — the property
``coll_ftagree_earlyreturning.c`` carries 3,371 lines of machinery for.

Seeds 0 and 1 are designed worst cases (0: root dies between
prepare-complete and commit AND the takeover root dies mid-prepare —
cascading takeover; 1: the root dies while two agreement instances are
concurrently in flight on different comms); the rest are randomized.
7 seeds x 2-4 rounds (+ a doubled concurrent round each) = 30
scenarios.
"""
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "fuzz_agree_worker.py"

N = 5
ROUNDS = 4
# the designed worst cases (0, 1) run in tier-1; the randomized seeds
# are the `slow` sweep — each is a 5-process kill-injection job whose
# recovery timeouts dominate suite wall-clock on oversubscribed hosts
SEEDS = [0, 1] + [pytest.param(s, marks=pytest.mark.slow)
                  for s in (11, 23, 37, 58, 71)]


def _plan_for(seed):
    """Re-derive the worker's plan (same code) for the asserts."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fuzz_agree_worker", WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build_plan(seed, N, ROUNDS)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_agreement_uniformity(seed):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.update(FUZZ_SEED=str(seed), FUZZ_N=str(N),
               FUZZ_ROUNDS=str(ROUNDS))
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(N),
           "--enable-recovery",
           "--mca", "ft_detector", "true",
           # generous detector envelope: on an oversubscribed 1-core
           # CI host a healthy rank can stall >1.5s (GC, compile,
           # sibling tests), and a false-positive death here makes its
           # agreement report legitimately vanish — that is the
           # detector working, not the property under test
           "--mca", "ft_detector_period", "0.3",
           "--mca", "ft_detector_timeout", "3.0",
           "--mca", "ft_detector_startup_grace", "4.0",
           sys.executable, str(WORKER)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                       cwd=REPO, env=env)
    out = r.stdout
    assert r.returncode == 0, out + r.stderr

    # collect FUZZ <key> <rank> <value> lines per scenario key (tpurun
    # prefixes child stdout with "[rank] ")
    values: dict[str, dict[int, int]] = {}
    for m in re.finditer(r"FUZZ (\S+) (\d+) (-?\d+)\s*$", out, re.M):
        values.setdefault(m.group(1), {})[int(m.group(2))] = \
            int(m.group(3))

    plan = _plan_for(seed)
    dead = set()
    for rd, spec in enumerate(plan):
        keys = [f"{rd}a", f"{rd}b"] if spec["concurrent"] else [str(rd)]
        # planned survivors of this round must all have reported
        must = set(range(N)) - dead - set(spec["victims"])
        if spec["suspect"]:
            must.discard(spec["suspect"][1])
        for key in keys:
            got = values.get(key, {})
            missing = must - set(got)
            assert not missing, (
                f"seed {seed} round {key}: ranks {sorted(missing)} never "
                f"reported\n{out}\n{r.stderr}")
            uniq = set(got.values())
            assert len(uniq) == 1, (
                f"seed {seed} round {key}: UNIFORMITY VIOLATED "
                f"{got}\n{out}\n{r.stderr}")
        dead |= set(spec["victims"])
        if spec["suspect"]:
            dead.add(spec["suspect"][1])

    # every planned survivor of the whole run finished cleanly
    finishers = {int(m.group(1))
                 for m in re.finditer(r"FUZZDONE (\d+)\s*$", out, re.M)}
    assert finishers >= (set(range(N)) - dead), (out, r.stderr)
