"""Test harness: run everything on the XLA CPU backend with 8 virtual devices.

This is the "fake multi-device backend" the reference never had (SURVEY.md §4):
single-host N-rank testing the way Open MPI uses ``mpirun -n 8
--oversubscribe`` over btl/self+sm.  Must run before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon boot hook (sitecustomize) forces jax_platforms=axon; override it
# before any backend initialization so tests always see 8 CPU devices.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def prewarm_native():
    """Build (or load) the otpu_native .so ONCE at session start.

    The first ``native.available()`` call may pay a ~2-minute g++
    compile into OTPU_NATIVE_CACHE; letting that land inside whichever
    test happens to call it first skews timing-sensitive tests (the
    bench-pin windows in test_perf_guard) and double-compiles under
    multi-process launches.  Warming here makes every later call a
    cheap cache hit — including the tpurun children, which inherit the
    populated cache directory."""
    if os.environ.get("OTPU_NATIVE_DISABLE"):
        yield
        return
    from ompi_tpu import native

    native.available()
    yield


@pytest.fixture
def fresh_registry():
    """Isolated var registry state for config-system tests."""
    from ompi_tpu.base import mca, output, var

    saved_vars = dict(var.registry._vars)
    saved_state = {
        name: (v._value, v._source, v._source_detail)
        for name, v in saved_vars.items()
    }
    saved_alias = dict(var.registry._alias)
    saved_pvars = dict(var.registry._pvars)
    saved_file = dict(var.registry._file)
    saved_loaded = var.registry._files_loaded
    yield var.registry
    var.registry._vars = saved_vars
    for name, (val, src, detail) in saved_state.items():
        v = saved_vars[name]
        v._value, v._source, v._source_detail = val, src, detail
    var.registry._alias = saved_alias
    var.registry._pvars = saved_pvars
    var.registry._file = saved_file
    var.registry._files_loaded = saved_loaded
    var.registry._cli.clear()
    var.registry._deprecation_warned.clear()
    output._help_seen.clear()
