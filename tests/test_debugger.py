"""Debugger handle introspection (runtime/debugger.py) — the MPIR /
``ompi/debuggers/ompi_common_dll.c`` analog: communicator handle table,
pml message queues (posted / unexpected / pending), proctable."""
import numpy as np
import pytest

import ompi_tpu


@pytest.fixture()
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


def test_comm_table_lists_world_and_dup(world):
    from ompi_tpu.runtime import debugger

    rows = debugger.comm_table()
    cids = {r["cid"] for r in rows}
    assert world.cid in cids
    me = next(r for r in rows if r["cid"] == world.cid)
    assert me["size"] == world.size and me["rank"] == world.rank
    assert me["peers"] == list(range(world.size))

    dup = world.dup()
    rows = debugger.comm_table()
    assert dup.cid in {r["cid"] for r in rows}
    dup.free()
    rows = debugger.comm_table()
    assert dup.cid not in {r["cid"] for r in rows}   # freed drop out


def test_message_queues_show_posted_and_unexpected(world):
    """Drive the host pml into a known queue state and read it back —
    the mqs_* iteration a debugger performs on a hung job."""
    from ompi_tpu.runtime import debugger

    if world.rte.is_device_world:
        # conductor model: rank views share one process's pml
        w = world
        # unexpected: send before any recv is posted
        w.as_rank(0).send(np.arange(4, dtype=np.int32), dest=1, tag=77)
        qs = debugger.message_queues(w)
        unexpected = [f for r in qs for f in r.get("unexpected", [])]
        assert any(f["tag"] == 77 for f in unexpected), qs
        # drain it so the fixture teardown isn't polluted
        buf = np.zeros(4, np.int32)
        w.as_rank(1).recv(buf, source=0, tag=77)
        qs = debugger.message_queues(w)
        unexpected = [f for r in qs for f in r.get("unexpected", [])]
        assert not any(f["tag"] == 77 for f in unexpected)
        # posted: irecv with no matching send yet
        req = w.as_rank(1).irecv(np.zeros(2, np.int32), source=0, tag=88)
        qs = debugger.message_queues(w)
        posted = [p for r in qs for p in r.get("posted_recvs", [])]
        assert any(p["tag"] == 88 for p in posted), qs
        w.as_rank(0).send(np.ones(2, np.int32), dest=1, tag=88)
        req.wait()
    else:
        pytest.skip("single-rank host world drives queues via conductor")


def test_proc_table_and_dump(world):
    from ompi_tpu.runtime import debugger

    procs = debugger.proc_table()
    assert len(procs) >= 1
    assert sum(1 for p in procs if p["is_me"]) == 1
    d = debugger.dump()
    assert {"comms", "message_queues", "procs"} <= set(d)
