"""pml/ob1 matching-engine tests: wildcards, ordering, unexpected queue,
out-of-order seqs, probe/mprobe, truncation, rendezvous protocol
(``pml_ob1_recvfrag.c`` semantics; SURVEY §3.2)."""
import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import waitall
from ompi_tpu.api.status import ANY_SOURCE, ANY_TAG


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


def test_basic_send_recv(world):
    a, b = world.as_rank(2), world.as_rank(5)
    a.send(np.array([1.5, 2.5]), dest=5, tag=9)
    buf = np.zeros(2)
    st = b.recv(buf, source=2, tag=9)
    assert buf.tolist() == [1.5, 2.5]
    assert st.source == 2 and st.tag == 9
    assert st.get_count(__import__("ompi_tpu.datatype", fromlist=["FLOAT64"]).FLOAT64) == 2


def test_wildcard_source_and_tag(world):
    world.as_rank(1).send(np.array([7]), dest=0, tag=42)
    buf = np.zeros(1, np.int64)
    st = world.as_rank(0).recv(buf, source=ANY_SOURCE, tag=ANY_TAG)
    assert st.source == 1 and st.tag == 42 and buf[0] == 7


def test_message_ordering_same_peer(world):
    """Messages from one sender with the same tag match in send order."""
    s, r = world.as_rank(3), world.as_rank(4)
    for i in range(5):
        s.send(np.array([i]), dest=4, tag=1)
    got = []
    for _ in range(5):
        buf = np.zeros(1, np.int64)
        r.recv(buf, source=3, tag=1)
        got.append(int(buf[0]))
    assert got == [0, 1, 2, 3, 4]


def test_tag_selective_matching(world):
    """A later-posted recv with the right tag matches an earlier message."""
    s, r = world.as_rank(6), world.as_rank(7)
    s.send(np.array([100]), dest=7, tag=5)
    s.send(np.array([200]), dest=7, tag=6)
    buf6 = np.zeros(1, np.int64)
    r.recv(buf6, source=6, tag=6)
    buf5 = np.zeros(1, np.int64)
    r.recv(buf5, source=6, tag=5)
    assert buf6[0] == 200 and buf5[0] == 100


def test_posted_recv_matches_later_send(world):
    r = world.as_rank(1)
    req = r.irecv(np.zeros(1, np.int64), source=0, tag=11)
    assert not req.complete_flag
    world.as_rank(0).send(np.array([33]), dest=1, tag=11)
    st = req.wait()
    assert st.source == 0


def test_out_of_order_seq_held(world):
    """Frag with a future seq is held until the gap fills (recvfrag.c:106)."""
    from ompi_tpu.mca.btl.base import MATCH, Frag

    pml = world.pml
    dst = 0
    cid = world.cid
    # deliver seq 1 before seq 0 from a fake peer stream on a fresh tag
    base_seq = 0
    # use a high source rank and fresh tag to avoid interference
    src = 5
    key = (cid, src, dst)
    import itertools

    ctr = pml._seq.setdefault(key, itertools.count())
    s0 = next(ctr)
    s1 = next(ctr)
    f0 = Frag(cid, src, dst, 77, s0, MATCH,
              np.array([10], np.int64).tobytes(), total_len=8)
    f1 = Frag(cid, src, dst, 77, s1, MATCH,
              np.array([20], np.int64).tobytes(), total_len=8)
    pml._recv_frag(f1)  # future seq → held
    b1 = np.zeros(1, np.int64)
    req = world.as_rank(0).irecv(b1, source=5, tag=77)
    assert not req.complete_flag
    pml._recv_frag(f0)  # gap fills, both deliver in order
    req.wait()
    assert b1[0] == 10
    b2 = np.zeros(1, np.int64)
    world.as_rank(0).recv(b2, source=5, tag=77)
    assert b2[0] == 20


def test_truncation_error(world):
    world.as_rank(0).send(np.arange(4, dtype=np.int64), dest=1, tag=13)
    small = np.zeros(2, np.int64)
    with pytest.raises(MpiError) as ei:
        world.as_rank(1).recv(small, source=0, tag=13)
    assert ei.value.error_class is ErrorClass.ERR_TRUNCATE
    assert small.tolist() == [0, 1]  # delivered what fit


def test_probe_iprobe(world):
    ok, st = world.as_rank(3).iprobe(source=2, tag=21)
    assert not ok
    world.as_rank(2).send(np.arange(3, dtype=np.float32), dest=3, tag=21)
    st = world.as_rank(3).probe(source=2, tag=21)
    assert st.source == 2 and st._nbytes == 12
    # probe does not consume
    buf = np.zeros(3, np.float32)
    world.as_rank(3).recv(buf, source=2, tag=21)
    assert buf.tolist() == [0.0, 1.0, 2.0]


def test_mprobe_mrecv(world):
    world.as_rank(4).send(np.array([5, 6]), dest=5, tag=31)
    msg = world.as_rank(5).mprobe(source=4, tag=31)
    # message removed from matching; a new recv on same tag won't see it
    ok, _ = world.as_rank(5).iprobe(source=4, tag=31)
    assert not ok
    buf = np.zeros(2, np.int64)
    st = msg.recv(buf)
    assert buf.tolist() == [5, 6]


def test_any_tag_ignores_internal_tags(world):
    from ompi_tpu.mca.btl.base import MATCH, Frag
    import itertools

    pml = world.pml
    ctr = pml._seq.setdefault((world.cid, 6, 7), itertools.count())
    pml._recv_frag(Frag(world.cid, 6, 7, -5, next(ctr), MATCH, b"\x01" * 8,
                        total_len=8))
    ok, _ = world.as_rank(7).iprobe(source=6, tag=ANY_TAG)
    assert not ok  # wildcard must not see internal (negative) tags
    buf = np.zeros(1, np.int64)
    world.as_rank(7).recv(buf, source=6, tag=-5)  # explicit internal tag does


def test_rendezvous_protocol(world):
    """Force RNDV/ACK/FRAG by shrinking btl/self's eager limits."""
    btl = world.pml.bml.endpoint(1).btl
    saved = (btl.eager_limit, btl.rndv_eager_limit, btl.max_send_size)
    btl.eager_limit, btl.rndv_eager_limit, btl.max_send_size = 64, 32, 48
    try:
        data = np.arange(100, dtype=np.float64)  # 800 bytes >> eager
        req = world.as_rank(0).isend(data, dest=1, tag=55)
        buf = np.zeros(100, np.float64)
        st = world.as_rank(1).recv(buf, source=0, tag=55)
        req.wait()
        np.testing.assert_array_equal(buf, data)
        assert st._nbytes == 800
    finally:
        btl.eager_limit, btl.rndv_eager_limit, btl.max_send_size = saved


def test_sendrecv_and_objects(world):
    st = world.as_rank(0).sendrecv(np.array([1.0]), dest=0,
                                   recvbuf=(out := np.zeros(1)), source=0,
                                   sendtag=61, recvtag=61)
    assert out[0] == 1.0
    world.as_rank(2).send_obj({"hello": [1, 2, 3]}, dest=3, tag=62)
    obj = world.as_rank(3).recv_obj(source=2, tag=62)
    assert obj == {"hello": [1, 2, 3]}


def test_spc_counters_advance(world):
    from ompi_tpu.runtime import spc

    before = spc.read("bytes_sent")
    world.as_rank(0).send(np.zeros(10, np.float64), dest=1, tag=70)
    world.as_rank(1).recv(np.zeros(10, np.float64), source=0, tag=70)
    assert spc.read("bytes_sent") >= before + 80


def test_sendrecv_replace(world):
    """MPI_Sendrecv_replace: received data overwrites the send buffer."""
    a, b = world.as_rank(0), world.as_rank(1)
    from ompi_tpu.api.request import waitall

    bufa = np.array([10.0, 11.0])
    bufb = np.array([20.0, 21.0])
    # eager-size exchange: the isend pairs with the replace sequentially
    ra = a.isend(bufa.copy(), dest=1, tag=5)
    st = b.sendrecv_replace(bufb, dest=0, source=0, sendtag=6, recvtag=5)
    assert bufb.tolist() == [10.0, 11.0]
    got = np.zeros(2)
    a.recv(got, source=1, tag=6)
    assert got.tolist() == [20.0, 21.0]
    waitall([ra])


def test_request_get_status_no_side_effects(world):
    """MPI_Request_get_status: completion visible without freeing."""
    s, r = world.as_rank(2), world.as_rank(3)
    buf = np.zeros(1)
    req = r.irecv(buf, source=2, tag=9)
    flag, _ = req.get_status()
    assert not flag
    s.send(np.array([4.0]), dest=3, tag=9)
    flag, st = req.get_status()
    assert flag and st.source == 2
    # request still waitable afterwards (get_status freed nothing)
    req.wait()
    assert buf[0] == 4.0
