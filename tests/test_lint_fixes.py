"""Regression tests for the real bugs otpu-lint's passes surfaced in
existing code, plus the OTPU_SANITIZE runtime-mode behavior.

The three fixes under pin:

1. **staging pool** (`mca/accelerator/jax_acc.py`): `_checkout` inserted
   into the checkout table `_out` OUTSIDE the pool lock.  Between
   acquire's unlock and the insert, a concurrent double release of the
   same adopted owner passed the under-lock guard — the owner looked
   neither free nor checked out — and repooled memory that was in use
   (the PR 4 aliasing family).  Now every `_out` mutation holds the
   (re-entrant) pool lock.

2. **btl/tcp** (`mca/btl/tcp.py`): `_by_rank` was mutated by the app
   thread (connect merge, flush-hard-error drop), the progress thread
   (EOF drop, handshake append), and close() with no common lock — a
   concurrent remove/extend on one peer's rail list could corrupt it.
   Now every mutation takes `_conns_lock` per the `_guarded_by`
   declaration.

3. **coord server** (`rte/coord.py`): the one-shot-fence late-arrival
   path called `_send_frame` (a blocking `sendall`) while `_fence_cond`
   was held — one slow-reading client would stall every fence/failure
   operation job-wide.  The reply now goes out after the condition is
   released.
"""
import pickle
import struct
import threading

import numpy as np
import pytest

from ompi_tpu.mca.accelerator.jax_acc import _StagingPool
from ompi_tpu.runtime import sanitizer
from ompi_tpu.runtime.sanitizer import SanitizeError


class _DepthLock:
    """RLock wrapper recording held depth, for lock-held assertions."""

    def __init__(self):
        self._inner = threading.RLock()
        self.depth = 0

    def __enter__(self):
        self._inner.acquire()
        self.depth += 1
        return self

    def __exit__(self, *exc):
        self.depth -= 1
        self._inner.release()
        return False

    acquire = __enter__

    def release(self):
        self.__exit__()


class _HookLock(_DepthLock):
    """Fires ``on_full_release`` the moment the lock is fully released —
    the first instant a concurrent thread could acquire it."""

    on_full_release = None

    def __exit__(self, *exc):
        super().__exit__(*exc)
        if self.depth == 0 and self.on_full_release is not None:
            cb, self.on_full_release = self.on_full_release, None
            cb()
        return False

    release = __exit__


class _AssertingDict(dict):
    """Dict that records any mutation made while the lock is not held."""

    def __init__(self, lock):
        super().__init__()
        self._lock = lock
        self.violations = []

    def _check(self, op):
        if self._lock.depth == 0:
            self.violations.append(op)

    def __setitem__(self, k, v):
        self._check("setitem")
        super().__setitem__(k, v)

    def pop(self, *a, **kw):
        self._check("pop")
        return super().pop(*a, **kw)


# -- fix 1: staging checkout table under the pool lock -----------------

def test_staging_checkout_table_mutates_only_under_pool_lock():
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    lock = _DepthLock()
    pool._lock = lock
    pool._out = _AssertingDict(lock)
    buf = pool.acquire(1024, np.uint8)          # insert into _out
    pool.release(buf)                           # pop from _out
    # adopted foreign owner: the release/re-acquire cycle walks every
    # checkout-table path, including the double-release guard scan
    foreign = np.empty(2048, np.uint8)
    pool.release(foreign)
    again = pool.acquire(2048, np.uint8)
    pool.release(again)
    dead = pool.acquire(512, np.uint8)
    del dead                                    # weakref purge path
    assert pool._out.violations == [], (
        f"checkout table mutated without the pool lock: "
        f"{pool._out.violations}")


def test_staging_double_release_guard_sees_live_checkout():
    """The interleaving the unlocked insert allowed: an adopted owner is
    re-acquired, and a stale second release of the SAME owner arrives
    while its bytes are checked out.  The guard must reject the repool
    (before the fix, a release racing the acquire->insert window could
    alias the checked-out bytes)."""
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    owner = np.empty(4096, np.uint8)
    pool.release(owner)                         # adopt
    view = pool.acquire(4096, np.uint8)         # pops the adopted owner
    view[:] = 7
    pool.release(owner)                         # stale double release
    other = pool.acquire(4096, np.uint8)        # must NOT alias `view`
    other[:] = 0
    assert view.sum() == 7 * 4096, "double release aliased a checkout"


def test_staging_acquire_checkout_atomic_with_pop():
    """The exact pre-fix interleaving: acquire pops an adopted owner
    from the free bin, and a STALE release of the same owner lands at
    the first instant the pool lock is free.  Before the fix the
    checkout registration happened in a later critical section, so at
    that instant the owner was neither free nor checked out — the guard
    passed and the repooled owner aliased the live checkout."""
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    owner = np.empty(4096, np.uint8)
    pool.release(owner)                         # adopt into the free bin
    lock = _HookLock()
    pool._lock = lock
    lock.on_full_release = lambda: pool.release(owner)   # the stale racer
    view = pool.acquire(4096, np.uint8)         # pops the adopted owner
    view[:] = 7
    other = pool.acquire(4096, np.uint8)
    other[:] = 0
    assert view.sum() == 7 * 4096, (
        "stale release in the pop->checkout window aliased the live "
        "checkout")


# -- fix 2: tcp _by_rank rail lists guarded by _conns_lock -------------

def _tcp_btl_and_conn():
    from ompi_tpu.mca.btl.tcp import TcpBtl, _Conn

    btl = TcpBtl.__new__(TcpBtl)
    TcpBtl.__init__(btl)
    conn = _Conn.__new__(_Conn)
    conn.sock = None
    conn.rank = 3
    conn.inbuf = bytearray()
    conn.outq = __import__("collections").deque()
    conn.out_bytes = 0
    conn.want_write = False
    conn.send_lock = threading.Lock()
    return btl, conn


class _AssertingRails(dict):
    def __init__(self, lock):
        super().__init__()
        self._lock = lock
        self.violations = []

    def _check(self, op):
        if self._lock.depth == 0:
            self.violations.append(op)

    def setdefault(self, *a):
        self._check("setdefault")
        return super().setdefault(*a)

    def pop(self, *a, **kw):
        self._check("pop")
        return super().pop(*a, **kw)

    def clear(self):
        self._check("clear")
        super().clear()


def test_tcp_by_rank_mutations_hold_conns_lock():
    btl, conn = _tcp_btl_and_conn()
    lock = _DepthLock()
    btl._conns_lock = lock
    btl._by_rank = _AssertingRails(lock)
    # handshake append (progress thread): a pickle-header hello frame
    hello = pickle.dumps({"rank": 3})
    frame = bytes((0,)) + struct.pack("!I", len(hello)) + hello
    fresh = type(conn).__new__(type(conn))
    fresh.rank = None
    assert btl._parse_frame(fresh, frame) is None
    assert fresh.rank == 3
    # EOF/hard-error drop (either thread)
    btl._drop_conn(fresh)
    assert 3 not in btl._by_rank
    btl._drop_conn(conn)                        # rank present, list gone
    assert btl._by_rank.violations == [], (
        f"_by_rank mutated without _conns_lock: {btl._by_rank.violations}")


def test_tcp_drop_conn_races_are_list_safe():
    """Two threads dropping rails for one peer while a third re-adds:
    with the lock this converges without ValueError/lost entries."""
    btl, conn = _tcp_btl_and_conn()
    conns = [conn]
    for _ in range(3):
        c = type(conn).__new__(type(conn))
        c.rank = 3
        conns.append(c)
    with btl._conns_lock:
        btl._by_rank.setdefault(3, []).extend(conns)
    errs = []

    def dropper(cs):
        try:
            for c in cs:
                btl._drop_conn(c)
        except Exception as exc:   # pragma: no cover - the regression
            errs.append(exc)

    ts = [threading.Thread(target=dropper, args=(conns[i::2],))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert 3 not in btl._by_rank


# -- fix 3: coord fence reply never rides under _fence_cond ------------

def test_coord_fence_replies_sent_outside_fence_cond(monkeypatch):
    from ompi_tpu.rte import coord as coord_mod

    srv = coord_mod.CoordServer(nprocs=1)
    held_during_send = []
    real_send = coord_mod._send_frame

    def checked_send(sock, obj):
        held_during_send.append(srv._fence_cond._is_owned())
        return real_send(sock, obj)

    monkeypatch.setattr(coord_mod, "_send_frame", checked_send)
    try:
        client = coord_mod.CoordClient(addr=srv.addr, timeout=10.0)
        try:
            # normal one-shot round, then the LATE-ARRIVAL path that
            # used to reply while _fence_cond was held
            client.fence_oneshot("f-done", rank=0, expect=[0])
            client.fence_oneshot("f-done", rank=0, expect=[0])
            client.put(0, "k", 1)
            assert client.get(0, "k") == 1
        finally:
            client.close()
    finally:
        srv.close()
    assert held_during_send, "instrumentation never fired"
    assert not any(held_during_send), (
        "a coord reply was sent while _fence_cond was held — one slow "
        "client would stall every fence job-wide")


# -- PR 11 (otpu-verify): template pml send dropped its request --------

def test_template_pml_send_waits_its_isend():
    """The mpi-typestate discarded-request finding in
    `mca/pml/template.py`: `send()` issued an isend and THREW AWAY the
    request — MPI_Send is isend + wait, and a pml grown from the
    skeleton would return before completion and silently drop any error
    the request carried.  Pinned both dynamically (the returned
    request's wait() must run, and its error must surface) and
    statically (no discarded-request finding anywhere in mca/pml)."""
    from ompi_tpu.api.errors import ErrorClass, MpiError
    from ompi_tpu.mca.pml.template import TemplatePml

    class _Probe:
        waited = 0

        def wait(self):
            _Probe.waited += 1

    class _ProbedPml(TemplatePml):
        def isend(self, comm, buf, dest, tag, mode="standard"):
            return _Probe()

    _ProbedPml.__new__(_ProbedPml).send(None, b"x", 0, 0)
    assert _Probe.waited == 1, "send() must wait its isend request"

    class _FailProbe:
        def wait(self):
            raise MpiError(ErrorClass.ERR_OTHER, "wire died")

    class _FailingPml(TemplatePml):
        def isend(self, comm, buf, dest, tag, mode="standard"):
            return _FailProbe()

    with pytest.raises(MpiError):
        _FailingPml.__new__(_FailingPml).send(None, b"x", 0, 0)

    from ompi_tpu import analysis
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    res = analysis.lint([str(repo / "ompi_tpu" / "mca" / "pml")],
                        select=["mpi-typestate"])
    discarded = [f for f in res.findings if "discarded" in f.message]
    assert not discarded, [f.format() for f in discarded]


# -- OTPU_SANITIZE runtime mode ----------------------------------------

@pytest.fixture
def sanitize_on(monkeypatch):
    monkeypatch.setattr(sanitizer, "enabled", True)
    yield


def test_sanitizer_double_release_raises(sanitize_on):
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    owner = np.empty(4096, np.uint8)
    pool.release(owner)
    _checked_out = pool.acquire(4096, np.uint8)
    with pytest.raises(SanitizeError, match="double release"):
        pool.release(owner)


def test_sanitizer_noncontiguous_release_raises(sanitize_on):
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    arr = np.empty((64, 64), np.float32)
    with pytest.raises(SanitizeError, match="non-C-contiguous"):
        pool.release(arr.T)


def test_sanitizer_tcp_framing_desync_raises(sanitize_on):
    btl, conn = _tcp_btl_and_conn()
    conn.inbuf = bytearray(struct.pack("!I", 0)) + b"junk"
    with pytest.raises(SanitizeError, match="framing desync"):
        btl._drain(conn)


def test_sanitizer_forces_memchecker(sanitize_on):
    from ompi_tpu.runtime import memchecker

    assert memchecker.enabled()


def test_sanitizer_off_by_default_and_tolerant():
    assert sanitizer.enabled is False
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    owner = np.empty(4096, np.uint8)
    pool.release(owner)
    _checked_out = pool.acquire(4096, np.uint8)
    pool.release(owner)        # tolerated silently (guarded, no raise)
    arr = np.empty((64, 64), np.float32)
    pool.release(arr.T)        # warn-once path, no raise
