"""The multi-process device world (VERDICT round-5 item 1): tpurun
``--device-world`` boots ``jax.distributed`` in every rank through the
instance layer — coordinator address from the coord service, process_id
from the rank map, gloo CPU collectives — so one compiled XLA program
spans processes.  The acceptance shape: a ``coll/xla`` allreduce AND one
flagship ``train_step`` execute across a REAL process boundary
(2 processes × 4 virtual CPU devices), with the communicator built via
``Group_from_session_pset`` + ``Comm_create_from_group`` and NO
MPI_Init anywhere in the rank program.
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun_dw(script, n=2, local=4, timeout=540):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("OTPU_RANK", "OTPU_NPROCS", "OTPU_COORD", "XLA_FLAGS"):
        env.pop(k, None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           "--device-world", "--local-devices", str(local),
           sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


def test_session_device_allreduce_and_train_step_cross_process(tmp_path):
    """The done-criterion test: sessions-model construction end to end,
    device collective + train step crossing the process boundary."""
    script = tmp_path / "dw.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu
        from ompi_tpu.api.errhandler import ERRORS_RETURN

        # sessions model only — MPI_Init must never run in this program
        s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
        assert not ompi_tpu.initialized()

        import jax
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 8, len(jax.devices())
        assert len(jax.local_devices()) == 4

        g = ompi_tpu.Group.from_session_pset(s, "mpi://WORLD")
        comm = ompi_tpu.Comm.create_from_group(g, "ci-device-world")
        assert comm.size == 2

        # the comm's device slots must be the coll/xla cross-process
        # module, not a host fallback
        from ompi_tpu.mca.coll.xla import XlaMpCollModule
        slot = comm.c_coll["allreduce_array"]
        while hasattr(slot, "__wrapped__"):
            slot = slot.__wrapped__
        assert isinstance(slot.__self__, XlaMpCollModule), slot

        # allreduce across the process boundary: each process
        # contributes rank+1, the sum needs BOTH processes' rows
        x = np.full((3,), float(comm.rank + 1), np.float32)
        y = comm.allreduce_array(x)
        got = np.asarray(y).ravel()
        assert got.tolist() == [3.0] * 3, got
        # bcast from the OTHER process + allgather of both rows
        b = comm.bcast_array(
            np.array([41.0 + comm.rank], np.float32), root=1)
        assert float(np.asarray(b)[0]) == 42.0
        ag = comm.allgather_array(np.array([comm.rank], np.int32))
        assert np.asarray(ag).ravel().tolist() == [0, 1]
        print(f"DWCOLL OK {comm.rank}", flush=True)

        # one flagship train step over the GLOBAL mesh: dp/sp/tp psums
        # ride gloo across the boundary inside one jitted program
        from ompi_tpu.parallel.dryrun import make_step_and_args
        step, (params, xd), mspec = make_step_and_args(jax.devices())
        new_params, loss = step(params, xd)
        jax.block_until_ready(new_params)
        loss = float(loss)
        _, loss2 = step(new_params, xd)
        assert float(loss2) < loss, (loss, float(loss2))
        print(f"DWTRAIN OK {comm.rank} mesh {mspec.sizes()} "
              f"loss {loss:.4f}->{float(loss2):.4f}", flush=True)
        comm.free()
        s.finalize()
    """))
    r = _tpurun_dw(script)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("DWCOLL OK") == 2, r.stdout + r.stderr
    assert r.stdout.count("DWTRAIN OK") == 2, r.stdout + r.stderr


def test_dryrun_multichip_two_process_mode():
    """``dryrun_multichip(8, nprocs=2)``: the driver's dry run in its
    multi-process shape — 2 ranks × 4 virtual devices, full descending
    train step over the global mesh."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("OTPU_RANK", "OTPU_NPROCS", "OTPU_COORD", "XLA_FLAGS"):
        env.pop(k, None)
    r = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {REPO!r}); "
         "import __graft_entry__ as g; g.dryrun_multichip(8, nprocs=2)"],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("dryrun ok") == 2, r.stdout + r.stderr


def test_device_world_reinit_same_process(tmp_path):
    """World-model re-init must survive an already-initialized
    jax.distributed client: init → finalize → init in a device-world
    rank reuses the live distributed runtime instead of re-dialing."""
    script = tmp_path / "dwreinit.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu

        w = ompi_tpu.init()
        assert getattr(w.rte, "device_world_booted", False)
        y = w.allreduce_array(np.ones(1, np.float32))
        assert float(np.asarray(y)[0]) == 2.0
        ompi_tpu.finalize()
        w = ompi_tpu.init()          # second boot, same jax client
        assert getattr(w.rte, "device_world_booted", False)
        y = w.allreduce_array(np.full(1, 2.0, np.float32))
        assert float(np.asarray(y)[0]) == 4.0
        print(f"DWREINIT OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun_dw(script)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("DWREINIT OK") == 2, r.stdout + r.stderr
