"""Datatype engine tests — the deepest unit suite, mirroring the reference's
``test/datatype/`` (ddt_pack.c, unpack_ooo.c, position.c, external32.c,
large_data.c; SURVEY.md §4)."""
import numpy as np
import pytest

import ompi_tpu.datatype as dtmod
from ompi_tpu.datatype import (
    BFLOAT16,
    BYTE,
    FLOAT32,
    FLOAT64,
    FLOAT_INT,
    INT32,
    Convertor,
    ConvertorFlags,
    contiguous,
    create_struct,
    darray,
    from_numpy_dtype,
    hindexed,
    hindexed_block,
    indexed,
    indexed_block,
    resized,
    subarray,
    vector,
    ORDER_C,
    ORDER_FORTRAN,
    DISTRIBUTE_BLOCK,
    DISTRIBUTE_CYCLIC,
    DISTRIBUTE_DFLT_DARG,
)


def _roundtrip(dt, count, buf_elems=None, chunk=None):
    """Pack from a random source, unpack into a zero target, compare."""
    rng = np.random.default_rng(0)
    extent_total = dt.lb + count * dt.extent + (dt.true_ub - dt.ub
                                                if dt.true_ub > dt.ub else 0)
    nbytes = max(extent_total, dt.true_lb + dt.true_ub + count * dt.extent, 1)
    src = rng.integers(0, 255, size=nbytes, dtype=np.uint8)
    dst = np.zeros_like(src)
    cp = Convertor(dt, count, src)
    packed = b""
    if chunk is None:
        packed = cp.pack().tobytes()
    else:
        while not cp.finished:
            packed += cp.pack(chunk).tobytes()
    assert len(packed) == count * dt.size
    cu = Convertor(dt, count, dst)
    if chunk is None:
        cu.unpack(packed)
    else:
        mv = memoryview(packed)
        while not cu.finished:
            n = cu.unpack(mv[:chunk])
            mv = mv[n:]
    # every byte belonging to the type map must match; others stay zero
    mask = np.zeros(nbytes, dtype=bool)
    for e in range(count):
        for s in dt.segments:
            lo = e * dt.extent + s.offset
            mask[lo:lo + s.nbytes] = True
    np.testing.assert_array_equal(dst[mask], src[mask])
    assert not dst[~mask].any()
    return packed


def test_named_type_sizes():
    assert FLOAT32.size == 4 and FLOAT32.extent == 4
    assert BFLOAT16.size == 2
    assert FLOAT_INT.size == 8  # f4 + i4 payload
    assert FLOAT_INT.extent == 8


def test_contiguous_roundtrip():
    _roundtrip(contiguous(16, FLOAT32), 4)


def test_vector_roundtrip():
    # 3 blocks of 2 floats every 5 floats
    dt = vector(3, 2, 5, FLOAT32)
    assert dt.size == 3 * 2 * 4
    assert dt.extent == (2 * 5 + 2) * 4
    _roundtrip(dt, 3)


def test_vector_chunked_partial_resume():
    dt = vector(4, 3, 7, FLOAT64)
    for chunk in (1, 3, 5, 8, 13, 64):
        _roundtrip(dt, 2, chunk=chunk)


def test_indexed_and_block():
    dt = indexed([2, 1, 3], [0, 4, 9], INT32)
    assert dt.size == 6 * 4
    _roundtrip(dt, 2, chunk=7)
    dtb = indexed_block(2, [0, 5, 11], INT32)
    _roundtrip(dtb, 3, chunk=5)


def test_hindexed_overlapping_order():
    # typemap order is pack order even when displacements are descending
    dt = hindexed([1, 1], [8, 0], INT32)
    src = np.arange(4, dtype=np.int32).view(np.uint8)
    packed = Convertor(dt, 1, src.copy()).pack()
    vals = np.frombuffer(packed, np.int32)
    assert list(vals) == [2, 0]  # entry at byte 8 first


def test_struct_mixed_types():
    dt = create_struct([2, 1, 4], [0, 8, 16], [INT32, FLOAT64, BYTE])
    assert dt.size == 2 * 4 + 8 + 4
    _roundtrip(dt, 3, chunk=9)


def test_struct_with_gaps_coalescing():
    # adjacent same-type blocks coalesce into one segment
    dt = create_struct([2, 2], [0, 8], [INT32, INT32])
    assert len(dt.segments) == 1
    assert dt.segments[0].count == 4


def test_resized_extent():
    dt = resized(FLOAT32, lb=-4, extent=16)
    assert dt.lb == -4 and dt.extent == 16
    con = contiguous(3, dt)
    assert con.extent == 3 * 16
    assert con.size == 12


def test_subarray_c_order():
    full = np.arange(6 * 8, dtype=np.float32).reshape(6, 8)
    dt = subarray([6, 8], [2, 3], [1, 2], ORDER_C, FLOAT32)
    assert dt.size == 2 * 3 * 4
    assert dt.extent == 6 * 8 * 4
    packed = Convertor(dt, 1, full.copy()).pack()
    got = np.frombuffer(packed, np.float32).reshape(2, 3)
    np.testing.assert_array_equal(got, full[1:3, 2:5])


def test_subarray_fortran_order():
    full = np.arange(4 * 5, dtype=np.int32).reshape(4, 5, order="F")
    dt = subarray([4, 5], [2, 2], [1, 3], ORDER_FORTRAN, INT32)
    buf = np.asfortranarray(full).T.copy()  # memory in F layout
    packed = Convertor(dt, 1, buf.reshape(-1)).pack()
    got = np.frombuffer(packed, np.int32)
    # F order: fastest-varying is first dim
    expect = full[1:3, 3:5].flatten(order="F")
    np.testing.assert_array_equal(got, expect)


def test_darray_block_cyclic():
    # 4 ranks on a 2x2 grid over an 8x8 array; block rows, cyclic cols
    g = np.arange(64, dtype=np.int32).reshape(8, 8)
    views = []
    for rank in range(4):
        dt = darray(4, rank, [8, 8],
                    [DISTRIBUTE_BLOCK, DISTRIBUTE_CYCLIC],
                    [DISTRIBUTE_DFLT_DARG, 1], [2, 2], ORDER_C, INT32)
        packed = Convertor(dt, 1, g.copy()).pack()
        views.append(set(np.frombuffer(packed, np.int32)))
    # disjoint cover of all 64 elements
    assert set().union(*views) == set(range(64))
    assert sum(len(v) for v in views) == 64


def test_set_position_out_of_order_unpack():
    # unpack_ooo.c equivalent: feed chunks out of order via set_position
    dt = vector(5, 2, 4, INT32)
    rng = np.random.default_rng(1)
    nbytes = dt.extent * 3 + dt.true_ub
    src = rng.integers(0, 255, nbytes, dtype=np.uint8)
    packed = Convertor(dt, 3, src.copy()).pack()
    dst = np.zeros(nbytes, dtype=np.uint8)
    cu = Convertor(dt, 3, dst)
    total = len(packed)
    pieces = [(total // 2, total), (0, total // 2)]  # reversed order
    for lo, hi in pieces:
        cu.set_position(lo)
        cu.unpack(packed[lo:hi])
    dst2 = np.zeros_like(dst)
    cu2 = Convertor(dt, 3, dst2)
    cu2.unpack(packed)
    np.testing.assert_array_equal(dst, dst2)


def test_external32_byteswap():
    data = np.array([1, 2, 3, 4], dtype=np.int32)
    c = Convertor(INT32, 4, data.copy(), flags=ConvertorFlags.EXTERNAL32)
    packed = c.pack()
    assert np.frombuffer(packed, ">i4").tolist() == [1, 2, 3, 4]
    out = np.zeros(4, dtype=np.int32)
    cu = Convertor(INT32, 4, out, flags=ConvertorFlags.EXTERNAL32)
    cu.unpack(packed)
    np.testing.assert_array_equal(out, data)


def test_external32_chunks_stay_item_aligned():
    data = np.arange(10, dtype=np.float64)
    c = Convertor(FLOAT64, 10, data.copy(), flags=ConvertorFlags.EXTERNAL32)
    chunks = []
    while not c.finished:
        chunks.append(c.pack(13).tobytes())  # 13 rounds down to 8
    assert all(len(ch) % 8 == 0 for ch in chunks[:-1])
    joined = b"".join(chunks)
    assert np.frombuffer(joined, ">f8").tolist() == data.tolist()


def test_checksum_consistency():
    data = np.arange(100, dtype=np.float32)
    c1 = Convertor(FLOAT32, 100, data.copy(), flags=ConvertorFlags.CHECKSUM)
    c1.pack()
    c2 = Convertor(FLOAT32, 100, np.zeros(100, np.float32),
                   flags=ConvertorFlags.CHECKSUM)
    c2.unpack(np.ascontiguousarray(data).tobytes())
    assert c1.checksum == c2.checksum != 0


def test_large_datatype():
    # large_data.c analog, scaled: >16MB through chunked pack
    n = 1 << 22  # 4M floats = 16MB
    dt = contiguous(n, FLOAT32)
    src = np.arange(n, dtype=np.float32)
    c = Convertor(dt, 1, src)
    out = bytearray()
    while not c.finished:
        out += memoryview(c.pack(1 << 20))
    np.testing.assert_array_equal(np.frombuffer(out, np.float32), src)


def test_from_numpy_structured_dtype():
    nd = np.dtype([("a", np.int32), ("b", np.float64), ("c", np.int8, (3,))],
                  align=True)
    dt = from_numpy_dtype(nd)
    assert dt.extent == nd.itemsize
    assert dt.size == 4 + 8 + 3
    arr = np.zeros(4, dtype=nd)
    arr["a"] = [1, 2, 3, 4]
    arr["b"] = [0.5, 1.5, 2.5, 3.5]
    arr["c"] = np.arange(12).reshape(4, 3)
    packed = Convertor(dt, 4, arr.view(np.uint8)).pack()
    assert len(packed) == 4 * dt.size


def test_element_count():
    dt = create_struct([2, 1], [0, 8], [INT32, FLOAT64])
    assert dt.element_count(dt.size) == 3
    assert dt.element_count(4) == 1
    assert dt.element_count(dt.size * 2 + 8) == 8  # 2 full elems + both int32s
    assert dt.element_count(dt.size * 2 + 12) == 8  # half a float64 counts 0
    assert dt.element_count(dt.size * 3) == 9


def test_device_flag_rejects_host_prepare():
    with pytest.raises(RuntimeError):
        Convertor(FLOAT32, 4, np.zeros(4, np.float32),
                  flags=ConvertorFlags.DEVICE)


def test_pack_unpack_api():
    """MPI_Pack / Unpack / Pack_size / Reduce_local (``ompi/mpi/c/pack.c``,
    ``reduce_local.c``)."""
    from ompi_tpu.api import op as op_mod
    from ompi_tpu.datatype import (FLOAT32, FLOAT64, pack, pack_size,
                                   reduce_local, unpack, vector)

    dt = vector(3, 2, 4, FLOAT64)   # 3 blocks of 2, stride 4
    src = np.arange(12.0)
    data = pack(src, 1, dt)
    assert len(data) == 6 * 8
    assert pack_size(1, dt) >= len(data)
    dst = np.zeros(12)
    assert unpack(data, dst, 1, dt) == 48
    assert dst[4] == 4.0 and dst[2] == 0.0   # gaps untouched

    # external32: canonical big-endian stream
    d32 = pack(np.arange(4, dtype=np.float32), 4, FLOAT32, external32=True)
    assert np.frombuffer(d32, ">f4").tolist() == [0.0, 1.0, 2.0, 3.0]

    a, b = np.arange(4.0), np.ones(4)
    reduce_local(a, b, op_mod.MAX)
    assert b.tolist() == [1.0, 1.0, 2.0, 3.0]


def test_type_attributes():
    """MPI_Type_create_keyval / set_attr / get_attr / delete_attr."""
    from ompi_tpu.api.attributes import DUP_FN, keyval_create, keyval_free
    from ompi_tpu.datatype import FLOAT32, vector

    dt = vector(2, 1, 3, FLOAT32)
    kv_null = keyval_create()        # default = MPI_NULL_COPY_FN
    kv_dup = keyval_create(copy_fn=DUP_FN)
    assert not dt.attr_get(kv_null)[0]
    dt.attr_put(kv_null, {"unit": "rows"})
    dt.attr_put(kv_dup, "shared")
    found, val = dt.attr_get(kv_null)
    assert found and val["unit"] == "rows"
    d2 = dt.dup()
    # MPI semantics: NULL_COPY keyvals do NOT propagate, DUP_FN ones do
    assert not d2.attr_get(kv_null)[0]
    assert d2.attr_get(kv_dup) == (True, "shared")
    dt.attr_delete(kv_null)
    assert not dt.attr_get(kv_null)[0]
    assert d2.attr_get(kv_dup)[0]    # the dup's copy survives
    keyval_free(kv_null)
    keyval_free(kv_dup)


def test_hindexed_block_matches_hindexed():
    """MPI_Type_create_hindexed_block == hindexed with equal lengths
    (``ompi/mpi/c/type_create_hindexed_block.c``)."""
    import numpy as np

    a = hindexed_block(2, [0, 16], INT32)
    b = hindexed([2, 2], [0, 16], INT32)
    assert a.size == b.size and a.extent == b.extent
    buf = np.arange(8, dtype=np.int32)
    from ompi_tpu.datatype import pack

    assert pack(buf, 1, a) == pack(buf, 1, b)
    assert a.combiner == "hindexed_block"
