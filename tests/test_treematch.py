"""treematch-style rank reordering: cart_create(reorder=True) places
row-major grid neighbors on the same node (topo/treematch's objective)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_cart_reorder_groups_nodes(tmp_path):
    """Ranks interleaved across two nodes (0,2 on n0; 1,3 on n1): with
    reorder=True each 2x2 cart ROW must be node-pure; without it the
    identity mapping leaves rows split across nodes."""
    script = tmp_path / "tm.py"
    script.write_text(textwrap.dedent("""
        import os
        # node interleave BEFORE the runtime reads it
        os.environ['OTPU_NODE_ID'] = f"n{int(os.environ['OTPU_RANK']) % 2}"
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        me_node = os.environ['OTPU_NODE_ID']

        cart = w.cart_create([2, 2], reorder=True)
        i, j = cart.cart_coords()
        # all row members agree on a node; columns cross nodes
        rows = cart.allgather(np.array(
            [i, 1 if me_node == 'n1' else 0], np.int64))
        rows = np.asarray(rows).reshape(4, 2)
        for row in (0, 1):
            vals = {int(n) for r, n in rows if r == row}
            assert len(vals) == 1, (row, rows)
        # and the two rows are on DIFFERENT nodes
        n0 = {int(n) for r, n in rows if r == 0}
        n1 = {int(n) for r, n in rows if r == 1}
        assert n0 != n1, rows

        # without reorder the identity mapping splits every row
        plain = w.cart_create([2, 2], reorder=False)
        pi, pj = plain.cart_coords()
        prows = np.asarray(plain.allgather(np.array(
            [pi, 1 if me_node == 'n1' else 0], np.int64))).reshape(4, 2)
        mixed = any(len({int(n) for r, n in prows if r == row}) == 2
                    for row in (0, 1))
        assert mixed, prows
        print(f"treematch OK rank {w.rank}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("treematch OK") == 4
