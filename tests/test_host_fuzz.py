"""Randomized host-path shakes, CI-pinned seeds.

Two tpurun-driven workers replay seed-deterministic plans on every
rank and check against replicated numpy models:

- ``fuzz_hostcoll_worker.py``: random collectives (allreduce/bcast/
  reduce/gather/allgatherv/alltoallv) + wildcard p2p + strided-vector
  datatype sends — the sweep that found the untyped-alltoallv
  inconsistency.
- ``fuzz_osc_worker.py``: fence-epoch RMA schedules (put/accumulate/
  fetch_and_op/get, disjoint per-origin regions) + a passive-target
  lock token ring.  Epochs separate with a barrier AFTER each rank
  checks its exposure epoch (mapped-window puts may land early — MPI
  makes epoch separation the program's job).
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(worker, n, env_extra, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         sys.executable, str(REPO / "tests" / worker)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)


@pytest.mark.parametrize("seed", [11, 47])
def test_fuzz_host_collectives(seed):
    # OTPU_SANITIZE arms the hard-assertion mode for the designed
    # worst-case seeds: staging double-release/aliasing, tcp framing
    # desync, and memchecker's frozen in-flight send buffers all fail
    # loudly at the faulty operation instead of corrupting downstream
    r = _run("fuzz_hostcoll_worker.py", 4,
             {"HF_SEED": str(seed), "HF_ITERS": "15", "OTPU_SANITIZE": "1"})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert r.stdout.count("randomized iterations OK") == 4


@pytest.mark.parametrize("seed", [5, 31])
def test_fuzz_osc_epochs(seed):
    r = _run("fuzz_osc_worker.py", 4,
             {"OF_SEED": str(seed), "OF_EPOCHS": "8"})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert "osc fuzz ok" in r.stdout


@pytest.mark.parametrize("seed", [9, 21])
def test_fuzz_shmem_epochs(seed):
    r = _run("fuzz_shmem_worker.py", 4,
             {"SF_SEED": str(seed), "SF_EPOCHS": "8"})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert "shmem fuzz ok" in r.stdout


@pytest.mark.parametrize("seed", [3, 27])
def test_fuzz_io_views(seed, tmp_path):
    r = _run("fuzz_io_worker.py", 4,
             {"IOF_SEED": str(seed), "IOF_ITERS": "6",
              "IOF_PATH": str(tmp_path / "fuzz.bin")})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert "io fuzz ok" in r.stdout


@pytest.mark.parametrize("seed", [7, 19])
def test_fuzz_algorithm_menus(seed):
    """Every tuned-menu algorithm for every collective must agree with
    numpy on random payloads — the decision ladder may pick any entry."""
    r = _run("fuzz_algs_worker.py", 4,
             {"AF_SEED": str(seed), "OTPU_SANITIZE": "1"}, timeout=520)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert r.stdout.count("menus agree") == 4
