"""Randomized MPI-IO shake: random (disp, etype, filetype) views and
interleaved individual/collective/shared writes, verified against a
replicated byte model of the final file."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu.api import file as fmod
from ompi_tpu.datatype import core

seed = int(os.environ["IOF_SEED"])
iters = int(os.environ.get("IOF_ITERS", "8"))
path = os.environ["IOF_PATH"]
ompi_tpu.init()
w = ompi_tpu.COMM_WORLD
me, n = w.rank, w.size
rng = np.random.default_rng(seed)          # same stream on every rank

FSIZE = 1 << 14
model = np.zeros(FSIZE, np.uint8)          # replicated file model
f = fmod.File.open(w, path, fmod.MODE_CREATE | fmod.MODE_RDWR)
f.set_size(FSIZE)
w.barrier()

def view_extents(disp, ft, start, nbytes):
    from ompi_tpu.mca.io.ompio import view_extents as ve
    return ve(disp, ft, start, nbytes)

for it in range(iters):
    # random view: etype f32; filetype vector or contiguous over f32
    disp = int(rng.integers(0, 64)) * 4
    kind = rng.choice(["contig", "vector", "indexed"])
    if kind == "contig":
        ft = core.contiguous(int(rng.integers(1, 5)), core.FLOAT32)
    elif kind == "vector":
        ft = core.vector(int(rng.integers(1, 4)),
                         int(rng.integers(1, 3)),
                         int(rng.integers(2, 5)), core.FLOAT32)
    else:
        nb = int(rng.integers(1, 3))
        disps = sorted(rng.choice(range(0, 8), nb, replace=False))
        ft = core.indexed([1] * nb, [int(d) for d in disps],
                          core.FLOAT32)
    f.set_view(disp, core.FLOAT32, ft)
    # each rank writes its own block at a rank-disjoint view offset
    cnt = int(rng.integers(1, 40))
    vals = rng.standard_normal((n, cnt)).astype(np.float32)
    off_et = me * 64                      # view-relative etype offset
    mode = rng.choice(["at_all", "at", "iat"])
    if mode == "at_all":
        f.write_at_all(off_et, vals[me])
    elif mode == "at":
        f.write_at(off_et, vals[me])
    else:
        f.iwrite_at(off_et, vals[me]).wait()
    # model: every rank applies ALL ranks' writes
    for r in range(n):
        data = vals[r].tobytes()
        pos = 0
        for foff, ln in view_extents(disp, ft, r * 64 * 4, len(data)):
            model[foff:foff + ln] = np.frombuffer(
                data[pos:pos + ln], np.uint8)
            pos += ln
    w.barrier()
    # interleave a readback check from a random rank's region
    src = int(rng.integers(0, n))
    out = np.zeros(cnt, np.float32)
    f.read_at(src * 64, out)
    assert np.allclose(out, vals[src]), (it, src)
    w.barrier()

f.sync() if hasattr(f, "sync") else None
w.barrier()
f.close()
if me == 0:
    real = np.fromfile(path, np.uint8)
    real = np.pad(real, (0, FSIZE - real.size))
    assert np.array_equal(real, model), \
        f"file diverges at {np.nonzero(real != model)[0][:8]}"
    print("io fuzz ok", flush=True)
ompi_tpu.finalize()
