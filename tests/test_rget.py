"""ob1 RGET protocol + btl one-sided put/get.

The reference's large-message ladder has eager / RNDV / RGET / RPUT
(``ompi/mca/pml/ob1/pml_ob1_sendreq.h:375-401``) over the btl RMA triple
(``opal/mca/btl/btl.h:949,987``).  These tests drive the new RGET branch
end-to-end over both transports: true one-sided segment pull on btl/sm,
request/stream emulation on btl/tcp (forced via --fake-nodes), plus the
raw btl put/get surface.
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


_LARGE_MSG = """
import numpy as np, ompi_tpu
from ompi_tpu.runtime import spc

w = ompi_tpu.init()
n = (3 << 20) // 8                       # 3MB float64 > rget_limit (1m)
if w.rank == 0:
    x = np.arange(n, dtype=np.float64)
    w.send(x, dest=1, tag=3)
    assert spc.read("rget_msgs") >= 1, "sender never took the RGET branch"
    # derived (vector) datatype: pack_borrow cannot hand out a view, so
    # RGET exposes the PACKED temporary — the non-borrowed branch
    from ompi_tpu.datatype import core
    nblk = n // 4
    dt = core.vector(nblk, 2, 4, core.FLOAT64)   # 2-of-4 stride pattern
    y = np.arange(4 * nblk, dtype=np.float64)
    w.send((y, 1, dt), dest=1, tag=4)
    print("SENDER OK", flush=True)
else:
    r = np.empty(n, np.float64)
    w.recv(r, source=0, tag=3)
    assert r[0] == 0 and r[-1] == n - 1 and r[n // 2] == n // 2, r
    nblk = n // 4
    r2 = np.empty(2 * nblk, np.float64)
    w.recv(r2, source=0, tag=4)
    # packed stream = elements 0,1, 4,5, 8,9, ... of the source
    assert r2[0] == 0 and r2[1] == 1 and r2[2] == 4 and r2[3] == 5, r2[:4]
    assert r2[-1] == 4 * (nblk - 1) + 1, r2[-1]
    print("RECEIVER OK", flush=True)
ompi_tpu.finalize()
"""


def test_rget_large_message_sm(tmp_path):
    script = tmp_path / "rget_sm.py"
    script.write_text(textwrap.dedent(_LARGE_MSG))
    r = _tpurun(2, script)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SENDER OK" in r.stdout and "RECEIVER OK" in r.stdout


def test_rget_large_message_tcp_emulated(tmp_path):
    # two fake nodes: sm declines cross-node, tcp carries the message and
    # RGET runs in pull-emulation mode (opt-in since round 4: emulation
    # measured slower than the FRAG stream, so it is gated by default)
    script = tmp_path / "rget_tcp.py"
    script.write_text(textwrap.dedent(_LARGE_MSG))
    r = _tpurun(2, script, extra=("--fake-nodes", "2",
                                  "--mca", "pml_ob1_rget_emulate", "1"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SENDER OK" in r.stdout and "RECEIVER OK" in r.stdout


def test_rget_not_engaged_on_non_rdma_btl_by_default(tmp_path):
    """Like the reference (RGET requires btl_get), the pull emulation on
    non-rdma btls is opt-in: a large tcp message with default vars must
    ride the FRAG stream (measured faster there), not RGET."""
    script = tmp_path / "norget.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.runtime import spc

        w = ompi_tpu.init()
        n = (3 << 20) // 8
        if w.rank == 0:
            w.send(np.arange(n, dtype=np.float64), dest=1, tag=3)
            assert spc.read("rget_msgs") == 0, \\
                "RGET emulation engaged on a non-rdma btl by default"
            print("GATED OK", flush=True)
        else:
            r = np.empty(n, np.float64)
            w.recv(r, source=0, tag=3)
            assert r[-1] == n - 1
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script, extra=("--fake-nodes", "2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "GATED OK" in r.stdout


def test_rget_disabled_falls_back_to_rndv(tmp_path):
    script = tmp_path / "rndv.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.runtime import spc

        w = ompi_tpu.init()
        n = (3 << 20) // 8
        if w.rank == 0:
            w.send(np.arange(n, dtype=np.float64), dest=1, tag=3)
            assert spc.read("rget_msgs") == 0, "RGET engaged while disabled"
            print("RNDV OK", flush=True)
        else:
            r = np.empty(n, np.float64)
            w.recv(r, source=0, tag=3)
            assert r[-1] == n - 1
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script, extra=("--mca", "pml_ob1_rget_limit", "0"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RNDV OK" in r.stdout


def test_btl_sm_put_get_surface(tmp_path):
    """Raw btl RMA triple: prepare_src / get / put between two ranks."""
    script = tmp_path / "rma.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.mca.bml import resolve_bml
        from ompi_tpu.runtime import init as rt

        w = ompi_tpu.init()
        bml = resolve_bml(rt.get_world_if_initialized().pml)
        peer = 1 - w.rank
        ep = bml.endpoint(peer)
        assert ep.btl.name == "sm" and ep.btl.rdma
        src = np.arange(1024, dtype=np.uint8)
        key = ep.btl.prepare_src(ep, src)
        # exchange keys over p2p, then pull the peer's region
        import pickle
        kb = np.frombuffer(pickle.dumps(key), np.uint8)
        w.send(np.array([kb.size], np.int64), dest=peer, tag=8)
        w.send(kb, dest=peer, tag=9)
        ln = np.empty(1, np.int64)
        w.recv(ln, source=peer, tag=8)
        kbuf = np.empty(int(ln[0]), np.uint8)
        w.recv(kbuf, source=peer, tag=9)
        peer_key = pickle.loads(kbuf.tobytes())
        dst = np.zeros(1024, np.uint8)
        ep.btl.get(ep, dst, peer_key)
        assert np.array_equal(dst, src), "one-sided get corrupted data"
        # put: overwrite the peer's exposed region, then verify via get
        ep.btl.put(ep, dst[::-1].copy(), peer_key)
        w.barrier()
        chk = np.zeros(1024, np.uint8)
        ep.btl.get(ep, chk, peer_key)
        assert chk[0] == 255 and chk[-1] == 0, chk
        w.barrier()
        ep.btl.release_src(key)
        print(f"RMA OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.stdout.count("RMA OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_multirail_striping_sm_plus_tcp(tmp_path):
    """Large RNDV streams stripe bandwidth-weighted across every rail
    that reaches the peer (bml_r2 multi-BTL striping): same-host ranks
    have sm AND tcp, and the FRAG stream must use them in proportion."""
    script = tmp_path / "stripe.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.mca.bml import resolve_bml
        from ompi_tpu.runtime import init as rt, spc

        w = ompi_tpu.init()
        bml = resolve_bml(rt.get_world_if_initialized().pml)
        eps = bml.endpoints(1 - w.rank)
        assert [e.btl.name for e in eps] == ["sm", "tcp"], eps
        # comparable rails: with sm's default 100x bandwidth edge the
        # finish-time-greedy schedule CORRECTLY starves tcp; equalize so
        # proportionality itself is what gets tested
        sm, tcp = eps[0].btl, eps[1].btl
        sm.bandwidth = tcp.bandwidth = 100
        carried = {"sm": 0, "tcp": 0}
        for name, btl in (("sm", sm), ("tcp", tcp)):
            orig = btl.send
            def wrapped(ep, frag, _o=orig, _n=name):
                if frag.kind == "frag":
                    carried[_n] += 1
                return _o(ep, frag)
            btl.send = wrapped
        n = (4 << 20) // 8
        if w.rank == 0:
            w.send(np.arange(n, dtype=np.float64), dest=1, tag=5)
            assert spc.read("striped_msgs") >= 1, "stream never striped"
            assert carried["sm"] >= 1 and carried["tcp"] >= 1, carried
            print(f"STRIPE SEND OK {carried}", flush=True)
        else:
            r = np.empty(n, np.float64)
            w.recv(r, source=0, tag=5)
            assert r[0] == 0 and r[-1] == n - 1 and r[n // 3] == n // 3
            print("STRIPE RECV OK", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script, extra=("--mca", "pml_ob1_rget_limit", "0",
                                  "--mca", "pml_ob1_stripe_min", "1m"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "STRIPE SEND OK" in r.stdout and "STRIPE RECV OK" in r.stdout


def test_tcp_multilink(tmp_path):
    """btl_tcp_links > 1: several connections per peer, frames striped
    round-robin; pml seq reordering reassembles across links."""
    script = tmp_path / "links.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.mca.bml import resolve_bml
        from ompi_tpu.runtime import init as rt

        w = ompi_tpu.init()
        peer = 1 - w.rank
        n = (2 << 20) // 8
        if w.rank == 0:
            for it in range(3):
                w.send(np.arange(n, dtype=np.float64) + it, dest=1, tag=it)
        else:
            for it in range(3):
                r = np.empty(n, np.float64)
                w.recv(r, source=0, tag=it)
                assert r[0] == it and r[-1] == n - 1 + it, (it, r)
        bml = resolve_bml(rt.get_world_if_initialized().pml)
        tcp = next(b for b in bml.btls if b.name == "tcp")
        links = tcp._by_rank.get(peer, [])
        assert len(links) >= 3, f"expected >=3 links, got {len(links)}"
        print(f"LINKS OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script, extra=("--fake-nodes", "2",
                                  "--mca", "btl_tcp_links", "3",
                                  "--mca", "pml_ob1_rget_limit", "0"))
    assert r.stdout.count("LINKS OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr
