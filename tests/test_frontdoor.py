"""ompi_tpu/serving/frontdoor — SLO-tiered admission, shedding,
preemption, and speculative decoding.

Coverage layers:

* token-bucket units: deterministic refill math against an injected
  clock, exact retry-after hints from the bucket deficit;
* door admission units (no comm): bounded-queue shed paths with the
  fd_retry_s hint, per-tenant rate-limit sheds, the one-class-per-
  tenant binding, forwarding order (interactive first, scheduler kept
  below the backlog watermark);
* preemption invariants over a REAL scheduler: an interactive-p99
  breach requeues RUNNING batch work (never dropped — same rids drain
  later), withdraws QUEUED batch work back behind the door, holds
  batch forwarding for fd_hold_ticks pumps, and bumps serve_preempt;
* speculative decoding: the draft/target toy pair's deterministic
  disagreement pattern, bit-exact output vs plain decode with PINNED
  accept/reject counters, then end-to-end through the colocated and
  prefill/decode staged modes (router re-verifies every token);
* THE overload soak (multiprocess, chaos-armed): MixedPoissonDriver
  above fleet capacity across both SLO classes through an armed front
  door — interactive p99 held within otpu_serving_slo_p99_ms, batch
  degrading predictably, every shed counted with its retry-after
  honored by the driver, zero crashes, zero dropped requests.
"""
import os
import subprocess
import sys
import threading
import weakref

import pytest

import ompi_tpu
from ompi_tpu.api.errors import MpiError
from ompi_tpu.base.var import registry
from ompi_tpu.runtime import spc
from ompi_tpu.serving.frontdoor import (SLO_BATCH, SLO_INTERACTIVE,
                                        FrontDoor, TokenBucket)
from ompi_tpu.serving.scheduler import ContinuousBatchScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), script_args=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script), *script_args]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


# ------------------------------------------------------- token bucket units

def test_token_bucket_deterministic_refill_math():
    b = TokenBucket(rate=10.0, burst=2.0, now=0.0)
    assert b.try_take(0.0) == 0.0          # burst tokens available
    assert b.try_take(0.0) == 0.0
    # empty: the hint is the EXACT deficit wait, (1 - tokens) / rate
    assert b.try_take(0.0) == pytest.approx(0.1, abs=1e-12)
    # half a token refilled after 0.05s: wait is the remaining half
    assert b.try_take(0.05) == pytest.approx(0.05, abs=1e-12)
    # after the full hint elapses the take succeeds
    assert b.try_take(0.05 + 0.1) == 0.0
    # refill caps at burst: a long idle gap does not bank extra tokens
    b2 = TokenBucket(rate=10.0, burst=2.0, now=0.0)
    for _ in range(2):
        assert b2.try_take(1000.0) == 0.0
    assert b2.try_take(1000.0) > 0.0

    with pytest.raises(MpiError):
        TokenBucket(rate=0.0, burst=1.0)


class _Pool:
    """Minimal router stand-in: the door only touches ``.sched``."""

    def __init__(self, **kw):
        kw.setdefault("max_batch", 8)
        kw.setdefault("max_batch_tokens", 65536)
        self.sched = ContinuousBatchScheduler(**kw)


def _door(pools=("m",), **kw):
    routers = {p: _Pool() for p in pools}
    kw.setdefault("queue_cap", 4)
    kw.setdefault("rate_rps", 0.0)
    kw.setdefault("backlog", 64)
    clock = kw.pop("clock", None) or (lambda: 0.0)
    fd = FrontDoor(routers, clock=clock, **kw)
    return fd, routers


# --------------------------------------------------------- admission units

def test_door_queue_full_sheds_with_retry_hint():
    spc.init()
    import ompi_tpu.serving.frontdoor as fd_mod

    fd, routers = _door(queue_cap=2, retry_s=0.25)
    try:
        shed0 = spc.read("serve_shed")
        assert fd.submit("t", "m", 8, 4).admitted
        assert fd.submit("t", "m", 8, 4).admitted
        dec = fd.submit("t", "m", 8, 4)
        assert not dec.admitted and dec.reason == "queue"
        assert dec.retry_after_s == pytest.approx(0.25)
        assert spc.read("serve_shed") == shed0 + 1
        st = fd.stats()
        assert st["shed"] == 1 and st["shed_by"] == {"t/interactive": 1}
        assert st["last_retry_ms"] == pytest.approx(250.0)
        # forwarding drains the door; capacity admits again
        fd.pump()
        assert fd.depth() == 0
        assert routers["m"].sched.depth() == 2
        assert fd.submit("t", "m", 8, 4).admitted
        fd.check_invariants()
        assert fd_mod.enabled is True and fd_mod._active is fd
    finally:
        fd.close()
    assert fd_mod.enabled is False and fd_mod._active is None


def test_door_rate_limit_sheds_with_exact_deficit():
    spc.init()
    now = [0.0]
    fd, _ = _door(rates={"t": (2.0, 1.0)}, queue_cap=16,
                  clock=lambda: now[0])
    try:
        assert fd.submit("t", "m", 8, 4).admitted      # the burst token
        dec = fd.submit("t", "m", 8, 4)
        assert not dec.admitted and dec.reason == "rate"
        assert dec.retry_after_s == pytest.approx(0.5)  # (1-0)/2 rps
        # honoring the hint admits deterministically
        now[0] = 0.5
        assert fd.submit("t", "m", 8, 4).admitted
        # an unlisted tenant uses the defaults (rate 0 = unlimited)
        for _ in range(3):
            assert fd.submit("free", "m", 8, 4).admitted
    finally:
        fd.close()


def test_door_binds_one_slo_class_per_tenant():
    fd, _ = _door()
    try:
        assert fd.submit("t", "m", 8, 4, slo=SLO_BATCH).admitted
        with pytest.raises(MpiError):
            fd.submit("t", "m", 8, 4, slo=SLO_INTERACTIVE)
        with pytest.raises(MpiError):
            fd.submit("u", "m", 8, 4, slo="gold")       # unknown class
        with pytest.raises(MpiError):
            fd.submit("u", "nope", 8, 4)                # unknown pool
    finally:
        fd.close()


def test_door_forwards_interactive_first_below_backlog():
    fd, routers = _door(queue_cap=16, backlog=3)
    sched = routers["m"].sched
    try:
        for _ in range(4):
            assert fd.submit("bat", "m", 8, 4, slo=SLO_BATCH).admitted
        for _ in range(4):
            assert fd.submit("int", "m", 8, 4,
                             slo=SLO_INTERACTIVE).admitted
        fd.pump()
        # the scheduler stays below the watermark and every forwarded
        # request is interactive — batch waits behind the door
        assert sched.depth() == 3
        assert all(r.slo == SLO_INTERACTIVE
                   for q in sched._tq.values() for r in q)
        assert fd.depth() == 5
        fd.check_invariants()
        # draining the scheduler lets the door top it back up (the
        # last interactive, then batch in arrival order)
        a, _ = sched.tick()
        for r in list(sched.running()):
            sched.mark_done(r)
        sched.tick()
        fd.pump()
        assert sched.depth() + len(sched.running()) >= 1
    finally:
        fd.close()


# ------------------------------------------------- preemption invariants

def test_preemption_requeues_batch_never_drops(monkeypatch):
    """An interactive-p99 breach must (a) requeue RUNNING batch work,
    (b) withdraw QUEUED batch work behind the door, (c) hold batch
    forwarding for fd_hold_ticks pumps, (d) count serve_preempt — and
    every preempted rid must drain later (never dropped)."""
    spc.init()
    registry.set("otpu_serving_slo_p99_ms", 10.0)
    try:
        fd, routers = _door(queue_cap=64, backlog=64, hold_ticks=3,
                            window=16)
        sched = routers["m"].sched
        try:
            bat = [fd.submit("bat", "m", 4, 2, slo=SLO_BATCH).request
                   for _ in range(6)]
            inter = [fd.submit("int", "m", 4, 2,
                               slo=SLO_INTERACTIVE).request
                     for _ in range(2)]
            fd.pump()                    # all 8 forwarded (backlog 64)
            assert sched.depth() == 8
            sched.tick()                 # admit up to max_batch (8)
            running = sched.running()
            assert len(running) == 8
            # breach: 16 interactive completions far over the target
            for _ in range(16):
                fd.observe("m", SLO_INTERACTIVE, 50.0)
            pre0 = spc.read("serve_preempt")
            fd.pump()
            # every RUNNING batch request went back to QUEUED and was
            # withdrawn behind the door with the queued batch work
            assert spc.read("serve_preempt") == pre0 + 6
            assert {r.rid for r in sched.running()} == \
                {r.rid for r in inter}
            assert sched.withdraw(SLO_BATCH) == []    # none left inside
            with fd._lock:
                door_bat = [r.rid for r in fd._q[("m", SLO_BATCH)]]
            assert door_bat == [r.rid for r in bat], \
                "preempted batch rids lost or reordered"
            fd.check_invariants()
            st = fd.stats()
            assert st["preempts"] == 6 and st["breaches"] == 1
            assert st["holds"] == {"m": 3}
            # the hold keeps batch out for hold_ticks pumps
            fd.pump()
            assert not [r for r in sched.running()
                        if r.slo == SLO_BATCH]
            fd.pump()
            fd.pump()                    # hold expired: batch returns
            assert [r.rid for q in sched._tq.values() for r in q] or \
                [r for r in sched.running() if r.slo == SLO_BATCH] or \
                fd.depth() == 0
            # drain everything: every admitted rid completes
            done = set()
            for _ in range(200):
                fd.pump()
                sched.tick()
                for r in list(sched.running()):
                    sched.mark_done(r)
                    done.add(r.rid)
                sched.tick()
                if not fd.depth() and not sched.depth() \
                        and not sched.running():
                    break
            assert done >= {r.rid for r in bat + inter}, \
                "a preempted request never drained"
            sched.check_invariants()
        finally:
            fd.close()
    finally:
        registry.set("otpu_serving_slo_p99_ms", 0.0)


def test_preemption_needs_a_real_window(monkeypatch):
    """No breach verdict from a thin window or without a target."""
    fd, routers = _door(window=16)
    try:
        # no target set: observe/pump never preempt
        for _ in range(32):
            fd.observe("m", SLO_INTERACTIVE, 1e6)
        fd.pump()
        assert fd.stats()["breaches"] == 0
    finally:
        fd.close()
    registry.set("otpu_serving_slo_p99_ms", 10.0)
    try:
        fd, routers = _door(window=16)
        try:
            for _ in range(8):           # under _MIN_WINDOW samples
                fd.observe("m", SLO_INTERACTIVE, 1e6)
            fd.pump()
            assert fd.stats()["breaches"] == 0
            # batch completions never feed the interactive window
            for _ in range(32):
                fd.observe("m", SLO_BATCH, 1e6)
            fd.pump()
            assert fd.stats()["breaches"] == 0
        finally:
            fd.close()
    finally:
        registry.set("otpu_serving_slo_p99_ms", 0.0)


# ------------------------------------------------- speculative decode units

def test_toy_draft_disagreement_pattern():
    from ompi_tpu.serving.worker import _VOCAB, toy_draft_token, toy_token

    for rid in (0, 7, 123):
        mismatches = [t for t in range(64)
                      if toy_draft_token(rid, t) != toy_token(rid, t)]
        assert mismatches == [t for t in range(64)
                              if (rid + t) % 8 == 5]
        for t in mismatches:
            assert toy_draft_token(rid, t) == \
                (toy_token(rid, t) + 1) % _VOCAB


def _bare_worker(spec_k, rid=7, elems=64):
    import numpy as np

    from ompi_tpu.serving.worker import ShardWorker

    w = ShardWorker.__new__(ShardWorker)
    w._kv = {rid: np.ones(elems, np.float32)}
    w.spec_k = spec_k
    return w


def test_speculative_decode_bit_exact_with_pinned_counters():
    from ompi_tpu.serving.worker import toy_token

    spc.init()
    plain = _bare_worker(0)._decode(7, 0, 16)
    assert plain == [toy_token(7, t) for t in range(16)]
    a0, r0 = spc.read("serve_spec_accepts"), spc.read("serve_spec_rejects")
    spec = _bare_worker(4)._decode(7, 0, 16)
    assert spec == plain, "speculative output must be bit-exact"
    # PINNED accept/reject ledger for (rid=7, 16 tokens, k=4): windows
    # [0..3]+bonus4, [5..8] rejected at 6, [7..10]+bonus11,
    # [12..15] rejected at 14, [15] — 12 accepted, 5 rejected
    assert spc.read("serve_spec_accepts") == a0 + 12
    assert spc.read("serve_spec_rejects") == r0 + 5
    # chunked exactly like the router's decode_chunk=4 stream
    w = _bare_worker(4)
    chunked = []
    for t0 in (0, 4, 8, 12):
        chunked.extend(w._decode(7, t0, 4))
    assert chunked == plain
    # the plain path never touches the draft counters
    a1, r1 = spc.read("serve_spec_accepts"), spc.read("serve_spec_rejects")
    _bare_worker(0)._decode(7, 0, 16)
    assert spc.read("serve_spec_accepts") == a1
    assert spc.read("serve_spec_rejects") == r1


# ----------------------------------------------------- in-process end-to-end

@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    from ompi_tpu.mca.part import part_framework

    part_framework().open()
    yield w
    rt.reset_for_testing()


def _run_workers(workers):
    threads = [threading.Thread(target=wk.serve, daemon=True)
               for wk in workers]
    for t in threads:
        t.start()
    return threads


def test_speculative_colocated_end_to_end(world):
    """spec_k=4 through the real router/worker wire: the router
    re-verifies every token, so completing at all IS the bit-exactness
    proof — asserted explicitly anyway, plus live spec counters."""
    from ompi_tpu.serving import Router, ShardWorker
    from ompi_tpu.serving.worker import toy_token

    wk = ShardWorker(world.as_rank(1), router=0, spec_k=4)
    threads = _run_workers([wk])
    router = Router(world.as_rank(0), workers=[1], decode_chunk=4)
    a0 = spc.read("serve_spec_accepts")
    for i in range(4):
        router.submit(4 + i, 8, tenant="t")
    done = router.serve_until_drained(max_ticks=5000)
    router.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 4
    for req in done:
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]
    assert spc.read("serve_spec_accepts") > a0, \
        "speculative path never engaged"


def test_speculative_staged_end_to_end(world):
    """spec_k through the prefill/decode split: drafts ride the decode
    stage against streamed KV slabs, outputs stay the target stream."""
    from ompi_tpu.serving import Router, ShardWorker
    from ompi_tpu.serving.worker import toy_token

    pre = ShardWorker(world.as_rank(1), router=0, role="prefill",
                      peer=2, slots=4, kv_elems=32)
    dec = ShardWorker(world.as_rank(2), router=0, role="decode",
                      peer=1, slots=4, kv_elems=32, spec_k=4)
    threads = _run_workers([pre, dec])
    router = Router(world.as_rank(0), workers=[1, 2],
                    prefill_ranks=[1], decode_ranks=[2],
                    decode_chunk=4, kv_elems=32)
    a0 = spc.read("serve_spec_accepts")
    for i in range(4):
        router.submit(4 + i, 6, tenant="t")
    done = router.serve_until_drained(max_ticks=5000)
    router.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 4
    for req in done:
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]
    assert spc.read("serve_spec_accepts") > a0


def test_fleet_frontdoor_escalation_in_process(world):
    """Fleet + armed door end to end: overload sheds with retry-after
    (driver re-arrives them), every request still completes bit-exact,
    the report splits shed/retried/completed per tenant AND per SLO
    class, and the frontdoor telemetry source publishes."""
    from ompi_tpu.runtime import telemetry
    from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                                  PoolSpec, ShardWorker)
    from ompi_tpu.serving.worker import toy_token

    workers = [ShardWorker(world.as_rank(r), router=0) for r in (1, 2)]
    threads = _run_workers(workers)
    fleet = FleetController(
        world.as_rank(0),
        pools=[PoolSpec("m_a", [1, 2], max_batch=4,
                        max_batch_tokens=4096)],
        tenants={"int": 2, "bat": 1},
        frontdoor=dict(queue_cap=4, backlog=2, retry_s=0.02))
    assert fleet.frontdoor is not None
    drv = MixedPoissonDriver({
        "int": dict(model="m_a", rate_rps=800, n_requests=12,
                    prompt_lens=(4, 8), decode_lens=(2, 4),
                    slo="interactive"),
        "bat": dict(model="m_a", rate_rps=800, n_requests=10,
                    prompt_lens=(4, 8), decode_lens=(2, 4),
                    slo="batch"),
    }, seed=11)
    rep = drv.run(fleet, max_wall_s=90, check_invariants=True)
    door_stats = fleet.frontdoor.stats()
    fleet.shutdown()
    for t in threads:
        t.join(timeout=10)
    # zero dropped: every arrival completed (sheds re-arrived)
    assert rep["requests"] == 22
    for req in fleet.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]
    # the flood over a cap-4 door queue MUST have shed something, and
    # every shed re-arrived (retried) before completing
    assert rep["shed"] > 0 and rep["retried"] >= rep["shed"]
    for name in ("int", "bat"):
        tr = rep["tenants"][name]
        assert tr["retried"] >= tr["shed"]
    cls = rep["slo_classes"]
    assert cls["interactive"]["requests"] == 12
    assert cls["batch"]["requests"] == 10
    assert cls["interactive"]["shed"] + cls["batch"]["shed"] == \
        rep["shed"]
    # the door's telemetry source is registered and publishes
    assert door_stats["shed"] == rep["shed"]
    entry = telemetry._sources.get("frontdoor")
    assert entry is not None, "frontdoor never registered its source"
    fn = entry() if isinstance(entry, weakref.WeakMethod) else entry
    assert isinstance(fn(), dict)


# ------------------------------------------------------------- multiprocess

_OVERLOAD_SOAK = """
import sys

import ompi_tpu
from ompi_tpu.base.var import registry
from ompi_tpu.runtime import spc
from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                              ShardWorker)
from ompi_tpu.serving.worker import toy_token

w = ompi_tpu.init()
if w.rank == 0:
    registry.set("otpu_serving_slo_p99_ms", 800.0)
    fleet = FleetController(
        w, tenants={"int": 2, "bat": 1},
        autoscale=dict(poll_ticks=10**9, idle_patience=10**9),
        frontdoor=dict(queue_cap=6, backlog=3, retry_s=0.01,
                       hold_ticks=20, window=16))
    drv = MixedPoissonDriver({
        "int": dict(model="m_a", rate_rps=150, n_requests=28,
                    prompt_lens=(4, 8), decode_lens=(2, 4),
                    slo="interactive"),
        "bat": dict(model="m_a", rate_rps=400, n_requests=36,
                    prompt_lens=(4, 8), decode_lens=(6, 12),
                    slo="batch"),
    }, seed=13)
    rep = drv.run(fleet, max_wall_s=180, check_invariants=True)
    total = 28 + 36
    # zero crashes, zero dropped: every arrival (including every shed,
    # re-arrived after its retry-after) completed bit-exactly
    assert rep["requests"] == total, (rep["requests"], total)
    assert len({q.rid for q in fleet.completed()}) == total
    for q in fleet.completed():
        assert q.tokens == [toy_token(q.rid, i)
                            for i in range(q.max_new_tokens)], q
    # the chaos kill was absorbed by serve-through-failure
    assert rep["requeued"] > 0, "victim died, nothing requeued"
    # overload policy: the batch flood shed at the door (counted, with
    # retry-after honored — retried >= shed proves the driver honored
    # every hint), while unclassified nothing was shed
    assert rep["shed"] > 0, rep
    assert rep["retried"] >= rep["shed"], rep
    assert spc.read("serve_shed") == rep["shed"], \\
        (spc.read("serve_shed"), rep["shed"])
    cls = rep["slo_classes"]
    # interactive p99 held within the SLO target under overload;
    # batch degrades predictably (no better than interactive)
    assert cls["interactive"]["p99_exact_ms"] <= 800.0, cls
    assert cls["batch"]["p99_exact_ms"] >= \\
        cls["interactive"]["p99_exact_ms"], cls
    assert cls["batch"]["shed"] > 0, cls
    st = fleet.frontdoor.stats()
    assert st["shed"] == rep["shed"]
    fleet.shutdown()
    import json
    print("OVERLOAD OK " + json.dumps(
        {"shed": rep["shed"], "retried": rep["retried"],
         "preempts": st["preempts"],
         "int_p99": cls["interactive"]["p99_exact_ms"],
         "bat_p99": cls["batch"]["p99_exact_ms"],
         "requeued": rep["requeued"]}), flush=True)
else:
    if w.rank == 2:
        from ompi_tpu.ft import chaos
        chaos.install_spec("kill:rank=2,site=serve_work,count=1")
    ShardWorker(w, router=0).serve()
    print(f"WORKER {w.rank} DONE", flush=True)
"""


def test_frontdoor_overload_soak_chaos_armed(tmp_path):
    """THE acceptance scenario: sustained overload (arrivals above the
    pool's decode capacity) across both SLO classes through the armed
    front door, a worker chaos-killed mid-load — interactive p99 held,
    batch degraded predictably, sheds counted with honored retry-after,
    zero crashes, zero dropped requests."""
    script = tmp_path / "overload_soak.py"
    script.write_text(_OVERLOAD_SOAK)
    r = _tpurun(3, script,
                extra=("--enable-recovery", "--pool", "m_a:1,2"),
                timeout=300)
    assert "OVERLOAD OK" in r.stdout, r.stdout + r.stderr
