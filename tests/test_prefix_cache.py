"""serving/prefix_cache — pure units, no MPI boot.

The prefix cache is the correctness-sensitive half of prefix-aware
routing, so it gets exhaustive unit coverage in isolation: hash
stability ACROSS PROCESSES (the router, every worker, and a respawned
replacement must all name a prefix identically), block-granularity
boundary cases, the generation-mismatch fallback (a stale hint must be
a perf miss, never wrong KV), and registry invalidation along the
eviction-notice and shrink/re-shard paths.
"""
import subprocess
import sys

from ompi_tpu.serving.prefix_cache import (PrefixRegistry, PrefixStore,
                                           block_hashes)

B = 4   # explicit block size: the tests must not depend on the MCA var


# ------------------------------------------------------------- hashing

def test_block_hashes_boundaries():
    toks = list(range(10))
    # only FULL blocks hash: 10 tokens at block 4 -> 2 digests
    assert len(block_hashes(toks, B)) == 2
    assert block_hashes(toks[:3], B) == ()          # under one block
    assert len(block_hashes(toks[:4], B)) == 1      # exactly one block
    assert len(block_hashes(toks[:7], B)) == 1      # partial tail drops
    assert len(block_hashes(toks[:8], B)) == 2
    assert block_hashes((), B) == ()


def test_block_hashes_chain_is_prefix_sensitive():
    a = block_hashes([1, 2, 3, 4, 5, 6, 7, 8], B)
    b = block_hashes([1, 2, 3, 4, 9, 9, 9, 9], B)
    c = block_hashes([0, 2, 3, 4, 5, 6, 7, 8], B)
    assert a[0] == b[0], "shared first block must share its digest"
    assert a[1] != b[1], "diverging second block must diverge"
    assert a[0] != c[0], "first-token change must change block 0"
    # the chain makes digest i cover the WHOLE prefix, not block i
    # alone: same second block after different first blocks differs
    d = block_hashes([9, 9, 9, 9, 5, 6, 7, 8], B)
    assert a[1] != d[1]


def test_block_hashes_stable_across_processes():
    """The digests must be process-stable (blake2b over packed tokens,
    never Python's salted hash()) — a respawned worker and the router
    must agree on every prefix name."""
    toks = [17, 4093, 0, 88, 17, 17, 2, 9]
    here = block_hashes(toks, B)
    out = subprocess.run(
        [sys.executable, "-c",
         "from ompi_tpu.serving.prefix_cache import block_hashes\n"
         f"print(','.join(block_hashes({toks!r}, {B})))"],
        capture_output=True, text=True, timeout=120,
        env={"PYTHONPATH": ":".join(sys.path), "JAX_PLATFORMS": "cpu",
             "PYTHONHASHSEED": "random"})
    assert out.returncode == 0, out.stderr
    assert tuple(out.stdout.strip().split(",")) == here


# ------------------------------------------------------------ registry

def test_registry_longest_prefix_lookup():
    reg = PrefixRegistry(capacity=32)
    h8 = block_hashes(list(range(8)), B)      # 2 blocks
    h12 = block_hashes(list(range(12)), B)    # 3 blocks, extends h8
    reg.insert(h8, worker=3, generation=1)
    hit = reg.lookup(h12)
    assert hit is not None
    assert (hit.worker, hit.generation, hit.blocks) == (3, 1, 2)
    assert hit.hash == h8[1], "deepest registered block wins"
    reg.insert(h12, worker=4, generation=2)
    hit = reg.lookup(h12)
    assert (hit.worker, hit.blocks) == (4, 3)
    # an unrelated prompt misses (and the miss is counted)
    assert reg.lookup(block_hashes([99] * 8, B)) is None
    st = reg.stats()
    assert st["hits"] == 2 and st["misses"] == 1
    assert 0 < st["hit_rate"] < 1
    assert reg.lookup(()) is None             # promptless: no count
    assert reg.stats()["misses"] == 1


def test_registry_lru_capacity():
    reg = PrefixRegistry(capacity=3)
    chains = [block_hashes([i] * 4, B) for i in range(5)]
    for i, ch in enumerate(chains):
        reg.insert(ch, worker=i, generation=0)
    assert len(reg) == 3
    assert reg.lookup(chains[0]) is None      # oldest evicted
    assert reg.lookup(chains[4]) is not None


def test_registry_forget_respects_owner():
    """A late eviction notice from worker A must not kill worker B's
    fresh entry under the same hash (the idempotent piggyback
    channel can replay old notices)."""
    reg = PrefixRegistry(capacity=8)
    ch = block_hashes([5] * 4, B)
    reg.insert(ch, worker=1, generation=0)
    reg.forget(ch, worker=2)                  # wrong owner: ignored
    assert reg.lookup(ch).worker == 1
    reg.insert(ch, worker=2, generation=3)    # B took the block over
    reg.forget(ch, worker=1)                  # stale notice from A
    assert reg.lookup(ch).worker == 2
    reg.forget(ch, worker=2)
    assert reg.lookup(ch) is None
    reg.forget(ch, worker=2)                  # idempotent


def test_registry_invalidation_paths():
    """The shrink/re-shard and retire paths: per-worker and wholesale
    invalidation drop exactly the right entries."""
    reg = PrefixRegistry(capacity=32)
    ch1 = block_hashes([1] * 8, B)
    ch2 = block_hashes([2] * 8, B)
    reg.insert(ch1, worker=1, generation=0)
    reg.insert(ch2, worker=2, generation=0)
    assert reg.invalidate_worker(1) == 2      # both of ch1's blocks
    assert reg.lookup(ch1) is None
    assert reg.lookup(ch2) is not None
    reg.invalidate_all()
    assert reg.lookup(ch2) is None and len(reg) == 0
    assert reg.stats()["invalidated"] == 4


# -------------------------------------------------------------- store

def test_store_generation_mismatch_falls_back():
    """THE correctness property: a hint minted against an older store
    lifetime (worker recovered / re-sharded) must MISS — stale routing
    state degrades to a full prefill, never to wrong KV."""
    store = PrefixStore(capacity=8)
    ch = block_hashes([7] * 8, B)
    store.add_all(ch)
    gen = store.generation
    assert store.has(ch[1], gen)
    store.clear()                             # recovery path
    assert store.generation == gen + 1
    assert not store.has(ch[1], gen), "old-generation hint matched"
    store.add_all(ch)                         # re-prefilled post-shrink
    assert not store.has(ch[1], gen), \
        "pre-shrink generation must never match again"
    assert store.has(ch[1], store.generation)


def test_store_lru_eviction_reports_evicted():
    """Evicted hashes must surface to the caller — they become the
    eviction notices that keep the router's registry honest."""
    store = PrefixStore(capacity=2)
    h = [block_hashes([i] * 4, B)[0] for i in range(4)]
    assert store.add_all(h[:2]) == []
    assert store.add_all([h[2]]) == [h[0]]
    assert not store.has(h[0], store.generation)
    # touching an entry refreshes it: h[1] survives, h[2] goes
    assert store.has(h[1], store.generation)
    assert store.add_all([h[3]]) == [h[2]]
    assert store.has(h[1], store.generation)


def test_worker_prefill_skip_and_stale_hint_fallback():
    """ShardWorker._prefill_or_skip against a bare store (no comm):
    verified hint skips the full pass, stale hint does the full pass,
    both install the prompt's blocks and queue the report."""
    from ompi_tpu.runtime import spc
    from ompi_tpu.serving.prefix_cache import PrefixStore
    from ompi_tpu.serving.worker import ShardWorker, toy_kv
    import numpy as np

    wk = ShardWorker.__new__(ShardWorker)
    wk.kv_elems = 16
    wk._prefix = PrefixStore(capacity=8)
    wk._prefix_hits = 0
    wk._preport_installed, wk._preport_evicted = [], []
    wk._preport_prefills = 0
    ch = block_hashes(list(range(8)), B)
    spc.init()
    prefills0 = spc.read("serve_prefills")
    # cold: full prefill, blocks installed
    kv = wk._prefill_or_skip(11, 8, ch, None)
    np.testing.assert_array_equal(kv, toy_kv(11, 16))
    assert spc.read("serve_prefills") == prefills0 + 1
    rep = wk._take_preport()
    assert rep["prefills"] == 1 and rep["hits"] == 0
    assert list(ch) == list(rep["installed"])
    # warm with a VERIFIED hint: skip (kv still bit-exact)
    kv = wk._prefill_or_skip(12, 8, ch, (ch[1], wk._prefix.generation,
                                         2))
    np.testing.assert_array_equal(kv, toy_kv(12, 16))
    assert spc.read("serve_prefills") == prefills0 + 1, "hit prefilled"
    assert wk._take_preport()["hits"] == 1
    # stale hint (generation bumped): full prefill fallback
    wk._prefix.clear()
    kv = wk._prefill_or_skip(13, 8, ch, (ch[1], 0, 2))
    np.testing.assert_array_equal(kv, toy_kv(13, 16))
    assert spc.read("serve_prefills") == prefills0 + 2
    rep = wk._take_preport()
    assert rep["hits"] == 0 and rep["prefills"] == 1


def test_degenerate_capacities_clamp():
    # degenerate capacities clamp to >= 1 rather than thrash-evict
    assert PrefixRegistry(capacity=0).capacity == 1
    assert PrefixStore(capacity=-3).capacity == 1
