"""hwloc-analog topology/binding + PERUSE matching-event tests.

Reference models: ``opal/mca/hwloc`` (topology + binding policy) and
``ompi/peruse/peruse.h`` events fired from ``pml_ob1_recvfrag.c``.
"""
import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.base import hwloc
from ompi_tpu.runtime import peruse


class TestHwloc:
    def test_host_topology(self):
        t = hwloc.host_topology(refresh=True)
        assert t.ncpus_online >= 1
        assert len(t.cpus_allowed) >= 1
        assert t.hostname

    def test_device_topology(self):
        devs = hwloc.device_topology()
        assert len(devs) >= 1
        assert devs[0].index == 0
        # CPU test mesh has no ICI coords; shape must be None not garbage
        if all(d.coords is None for d in devs):
            assert hwloc.ici_mesh_shape() is None

    def test_binding_partition(self):
        topo = hwloc.HostTopology("h", 8, tuple(range(8)),
                                  ((0, tuple(range(4))),
                                   (1, tuple(range(4, 8)))))
        b0 = hwloc.compute_binding(0, 2, topo)
        b1 = hwloc.compute_binding(1, 2, topo)
        assert b0 == (0, 1, 2, 3) and b1 == (4, 5, 6, 7)
        # oversubscribed: more ranks than cores → unbound (all cpus)
        over = hwloc.compute_binding(3, 16, topo)
        assert over == tuple(range(8))

    def test_locality_tiers(self):
        numa = ((0, (0, 1)), (1, (2, 3)))
        assert hwloc.locality("a", "b") == hwloc.LOC_DIFFERENT_NODE
        assert hwloc.locality("a", "a") == hwloc.LOC_SAME_NODE
        assert hwloc.locality("a", "a", (0,), (1,), numa, ncpus=4) == \
            hwloc.LOC_SAME_NUMA
        assert hwloc.locality("a", "a", (0, 1), (1,), numa, ncpus=4) == \
            hwloc.LOC_SAME_CORE
        assert hwloc.locality("a", "a", (0,), (2,), numa, ncpus=4) == \
            hwloc.LOC_SAME_NODE
        # unbound ranks (full mask) must NOT look core-local
        assert hwloc.locality("a", "a", (0, 1, 2, 3), (0, 1, 2, 3), numa,
                              ncpus=4) == hwloc.LOC_SAME_NODE

    def test_summary_runs(self):
        s = hwloc.summary()
        assert "host:" in s and "device[0]" in s


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


class TestPeruse:
    def test_posted_then_matched(self, world):
        events = []
        h = peruse.subscribe(peruse.REQ_INSERT_IN_POSTED_Q,
                             lambda e, cid, **i: events.append((e, i)))
        h2 = peruse.subscribe(peruse.MSG_MATCH_POSTED_REQ,
                              lambda e, cid, **i: events.append((e, i)))
        try:
            r = world.as_rank(0)
            buf = np.zeros(1)
            req = r.irecv(buf, source=1, tag=77)
            assert any(e == peruse.REQ_INSERT_IN_POSTED_Q and
                       i["tag"] == 77 for e, i in events)
            world.as_rank(1).send(np.array([3.0]), dest=0, tag=77)
            req.wait()
            assert any(e == peruse.MSG_MATCH_POSTED_REQ for e, _ in events)
        finally:
            h.release()
            h2.release()
        assert not peruse.active()

    def test_unexpected_queue_events(self, world):
        events = []
        hs = [peruse.subscribe(ev,
                               lambda e, cid, **i: events.append((e, i)))
              for ev in (peruse.MSG_INSERT_IN_UNEX_Q, peruse.REQ_MATCH_UNEX,
                         peruse.REQ_COMPLETE)]
        try:
            world.as_rank(2).send(np.array([9.0]), dest=3, tag=5)
            # no recv posted yet: the message must hit the unexpected queue
            assert any(e == peruse.MSG_INSERT_IN_UNEX_Q for e, _ in events)
            buf = np.zeros(1)
            world.as_rank(3).recv(buf, source=2, tag=5)
            assert buf[0] == 9.0
            assert any(e == peruse.REQ_MATCH_UNEX for e, _ in events)
        finally:
            for h in hs:
                h.release()

    def test_comm_scoped_subscription(self, world):
        """A subscription scoped to one comm ignores other comms."""
        events = []
        h = peruse.subscribe(peruse.REQ_ACTIVATE,
                             lambda e, cid, **i: events.append(cid),
                             comm=world)
        try:
            world.as_rank(4).send(np.array([1.0]), dest=5, tag=1)
            buf = np.zeros(1)
            world.as_rank(5).recv(buf, source=4, tag=1)
            assert events and all(c == world.cid for c in events)
        finally:
            h.release()

    def test_callback_errors_are_swallowed(self, world):
        def bad(e, cid, **i):
            raise RuntimeError("introspection bug")

        h = peruse.subscribe(peruse.REQ_ACTIVATE, bad)
        try:
            world.as_rank(6).send(np.array([1.0]), dest=7, tag=2)
            buf = np.zeros(1)
            world.as_rank(7).recv(buf, source=6, tag=2)  # must not raise
            assert buf[0] == 1.0
        finally:
            h.release()
