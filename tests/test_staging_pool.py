"""Staging-buffer reuse pool (the rcache/grdma analog in
``mca/accelerator/jax_acc.py``): unit semantics + reuse across repeated
host-path ring allreduces."""
import threading
import traceback

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.mca.accelerator.jax_acc import _StagingPool, staging


class TestPoolUnit:
    def test_hit_miss_and_reuse(self):
        p = _StagingPool(max_bytes=1 << 20)
        a = p.acquire(100, np.float32)
        assert p.misses == 1 and p.hits == 0
        base = a.base
        ptr = a.__array_interface__["data"][0]
        p.release(a)
        b = p.acquire(100, np.float32)
        # warmed MEMORY reused (size-class binning hands out a fresh
        # shaped view of the same raw class buffer)
        assert b.base is base
        assert b.__array_interface__["data"][0] == ptr
        assert b.shape == (100,) and b.dtype == np.float32
        assert p.hits == 1
        # same size class, different shape: still a hit once the class
        # bin is warm — that is the binning win over exact-key pooling
        p.release(b)
        c = p.acquire(101, np.float32)      # 404 bytes, same 512b class
        assert c.shape == (101,) and p.hits == 2
        # a different size class is a miss
        d = p.acquire(100, np.float64)      # 800 bytes -> 1k class
        assert p.misses == 2

    def test_noncontiguous_release_warns_loudly(self, capsys):
        p = _StagingPool(max_bytes=1 << 20)
        arr = np.empty((8, 8), np.float32)
        p.release(arr.T)                    # non-C-contiguous
        err = capsys.readouterr().err
        assert "non-C-contiguous" in err or "staging" in err
        # warned ONCE per pool, not per call
        p.release(arr.T)
        assert capsys.readouterr().err == ""
        # nothing was pooled from those releases
        assert p.acquire(64, np.float32) is not None
        assert p.hits == 0

    def test_views_never_pooled(self):
        p = _StagingPool()
        a = p.acquire(10, np.float32)
        p.release(a[:5])                # view: base owns the memory
        assert p.acquire(5, np.float32) is not None
        assert p.hits == 0

    def test_foreign_double_release_never_aliases(self):
        p = _StagingPool(max_bytes=1 << 20)
        owner = np.empty(512, np.uint8)     # foreign owner, adopted
        p.release(owner)
        p.release(owner)                    # double release: dropped
        a = p.acquire(512, np.uint8)
        b = p.acquire(512, np.uint8)
        assert a.__array_interface__["data"][0] != \
            b.__array_interface__["data"][0]

    def test_eviction_skips_bins_emptied_by_acquire(self):
        # acquire drains a class bin; a later eviction walking the LRU
        # order from the cold end must not trip over the empty bin
        p = _StagingPool(max_bytes=1024)
        a = p.acquire(256, np.uint8)        # 256b class
        p.release(a)
        p.acquire(256, np.uint8)            # empties the 256b bin
        big = [p.acquire(512, np.uint8) for _ in range(4)]
        for b in big:                       # forces eviction passes
            p.release(b)
        assert p._bytes <= 1024

    def test_lru_eviction_bound(self):
        p = _StagingPool(max_bytes=1000)
        bufs = [p.acquire(100, np.uint8) for _ in range(20)]
        for b in bufs:
            p.release(b)
        assert p._bytes <= 1000

    def test_disabled_passthrough(self):
        p = _StagingPool()
        p.enabled = False
        a = p.acquire(7, np.int32)
        p.release(a)
        b = p.acquire(7, np.int32)
        assert b is not a and p.hits == 0


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


def _spmd(comm, fn, timeout=60):
    results = [None] * comm.size
    errors = []

    def run(i):
        try:
            results[i] = fn(comm.as_rank(i), i)
        except Exception:
            errors.append((i, traceback.format_exc()))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(comm.size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not errors, errors[0][1]
    return results


def test_ring_allreduce_reuses_staging(world):
    from ompi_tpu.mca.coll import algorithms as algs

    staging.clear()
    x = np.arange(64 * world.size, dtype=np.float64)

    def body(me, i):
        return algs.allreduce_ring(me, x + i)

    want0 = sum(x + i for i in range(world.size))
    for _ in range(3):
        results = _spmd(world, body)
        for r in results:
            np.testing.assert_allclose(r, want0)
    # after the first sweep warmed the pool, later sweeps must hit
    assert staging.hits > 0, (staging.hits, staging.misses)
    assert staging.misses <= world.size, (staging.hits, staging.misses)
