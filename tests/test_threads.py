"""threads framework: native worker pool + python fallback substrate.

Covers the ``opal/mca/threads``-analog contract: component selection,
typed parallel jobs (memcpy / reduce / pack / unpack) matching their
serial twins, request-style completion handles, and the convertor's
wide-pack integration.
"""
import os

import numpy as np
import pytest

from ompi_tpu.mca.threads import base as tbase
from ompi_tpu.mca.threads.native import COMPONENT as native_comp
from ompi_tpu.mca.threads.python import COMPONENT as python_comp


def _pools():
    out = [("python", python_comp.make_pool(3))]
    if native_comp.open():
        out.append(("native", native_comp.make_pool(3)))
    return out


@pytest.fixture(scope="module")
def pools():
    ps = _pools()
    yield dict(ps)
    for _, p in ps:
        p.close()


def test_selection_prefers_native():
    fw = tbase.framework()
    fw.open()
    comp = fw.select()
    assert comp is not None
    if native_comp.opened:
        assert comp.name == "native"
    else:
        assert comp.name == "python"


def test_native_available_in_ci():
    # the image bakes g++; CI must exercise the real substrate, not
    # silently fall back — but a dev box without a toolchain still
    # runs the rest of the suite on the python substrate
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain on this host")
    if os.environ.get("OTPU_NATIVE_DISABLE"):
        pytest.skip("explicit fallback-lane run (OTPU_NATIVE_DISABLE)")
    assert native_comp.open()


@pytest.mark.parametrize("name", ["python", "native"])
def test_memcpy_matches(pools, name):
    if name not in pools:
        pytest.skip("native lib unavailable")
    pool = pools[name]
    rng = np.random.default_rng(7)
    src = rng.integers(0, 256, size=(1 << 20) + 13, dtype=np.uint8)
    dst = np.zeros_like(src)
    w = pool.memcpy(dst, src)
    w.wait()
    assert w.test()
    np.testing.assert_array_equal(dst, src)


@pytest.mark.parametrize("name", ["python", "native"])
@pytest.mark.parametrize("op", ["sum", "prod", "max", "min"])
@pytest.mark.parametrize("dtype", ["float32", "float64", "int32",
                                   "int64"])
def test_reduce_matches_numpy(pools, name, op, dtype):
    if name not in pools:
        pytest.skip("native lib unavailable")
    pool = pools[name]
    fn = {"sum": np.add, "prod": np.multiply,
          "max": np.maximum, "min": np.minimum}[op]
    rng = np.random.default_rng(11)
    a = (rng.random(100003) * 3 + 1).astype(dtype)
    b = (rng.random(100003) * 3 + 1).astype(dtype)
    if dtype.startswith("float"):
        # NaN propagation must match numpy from EITHER operand
        a[100], b[200] = np.nan, np.nan
        want = fn(a, b)
        pool.reduce(op, a, b).wait()
        np.testing.assert_allclose(a, want, rtol=1e-6, equal_nan=True)
        assert np.isnan(a[100]) and np.isnan(a[200])
    else:
        want = fn(a, b)
        pool.reduce(op, a, b).wait()
        np.testing.assert_allclose(a, want, rtol=1e-6)


@pytest.mark.parametrize("name", ["python", "native"])
def test_pack_unpack_match_serial(pools, name):
    if name not in pools:
        pytest.skip("native lib unavailable")
    pool = pools[name]
    # a {4B used, 4B gap, 4B used, 4B gap} element, many elements —
    # the vector-datatype shape the pack engine exists for
    seg_off = np.array([0, 8], np.int64)
    seg_len = np.array([4, 4], np.int64)
    extent, nelem = 16, 4001
    rng = np.random.default_rng(3)
    mem = rng.integers(0, 256, size=extent * nelem, dtype=np.uint8)
    want = np.zeros(8 * nelem, np.uint8)
    from ompi_tpu import native as nat

    if nat.available():
        nat.pack_elems(mem, want, seg_off, seg_len, extent, 0, 0, nelem)
    else:  # serial reference built by numpy gather
        idx = (np.arange(nelem)[:, None] * extent
               + np.array([0, 1, 2, 3, 8, 9, 10, 11])).reshape(-1)
        want[:] = mem[idx]
    got = np.zeros_like(want)
    pool.pack(mem, got, seg_off, seg_len, extent, 0, 0, nelem).wait()
    np.testing.assert_array_equal(got, want)
    # unpack the stream back into a fresh buffer: used bytes roundtrip
    mem2 = np.zeros_like(mem)
    pool.unpack(mem2, got, seg_off, seg_len, extent, 0, 0, nelem).wait()
    back = np.zeros_like(want)
    pool.pack(mem2, back, seg_off, seg_len, extent, 0, 0, nelem).wait()
    np.testing.assert_array_equal(back, want)


def test_reduce_rejects_dtype_mismatch(pools):
    for pool in pools.values():
        a = np.zeros(64, np.float64)
        b = np.zeros(64, np.float32)
        if getattr(pool, "parallel_pack", False):  # native substrate
            with pytest.raises(ValueError):
                pool.reduce("sum", a, b)


def test_memcpy_rejects_noncontiguous(pools):
    for pool in pools.values():
        src = np.zeros((8, 8), np.uint8)
        dst = np.zeros((8, 8), np.uint8).T
        with pytest.raises(ValueError):
            pool.memcpy(dst, src)


def test_pack_pins_converted_segment_tables(pools):
    """Segment tables passed as Python lists are converted to temp int64
    arrays whose pointers the queued chunks hold — the handle must keep
    them alive until completion (regression: use-after-free)."""
    import gc

    pool = pools.get("native")
    if pool is None:
        pytest.skip("native lib unavailable")
    extent, nelem = 16, 50000
    mem = np.arange(extent * nelem, dtype=np.int64).view(np.uint8)[
        : extent * nelem].copy()
    want = np.zeros(8 * nelem, np.uint8)
    from ompi_tpu import native as nat

    nat.pack_elems(mem, want, np.array([0, 8], np.int64),
                   np.array([4, 4], np.int64), extent, 0, 0, nelem)
    got = np.zeros_like(want)
    w = pool.pack(mem, got, [0, 8], [4, 4], extent, 0, 0, nelem)
    gc.collect()          # would collect unpinned temporaries
    w.wait()
    np.testing.assert_array_equal(got, want)


def test_abandoned_handle_does_not_leak(pools):
    """Dropping a Work without wait() must still free its ticket (via
    __del__) — smoke: abandon many and let gc drive completion."""
    import gc

    pool = pools.get("native")
    if pool is None:
        pytest.skip("native lib unavailable")
    src = np.zeros(1 << 16, np.uint8)
    dst = np.zeros_like(src)
    for _ in range(64):
        pool.memcpy(dst, src)   # handle dropped immediately
    gc.collect()


def test_concurrent_test_and_wait_single_free(pools):
    """test() polling from one thread while another wait()s — the
    ticket must be freed exactly once (regression: double free)."""
    import threading

    pool = pools.get("native") or pools["python"]
    src = np.random.default_rng(0).integers(
        0, 256, size=1 << 22, dtype=np.uint8)
    dst = np.zeros_like(src)
    for _ in range(10):
        w = pool.memcpy(dst, src)
        done = threading.Event()

        def poll():
            while not done.is_set():
                if w.test():
                    break

        t = threading.Thread(target=poll)
        t.start()
        w.wait()
        done.set()
        t.join()
        assert w.test()


def test_work_handles_complete_out_of_order(pools):
    pool = pools.get("native") or pools["python"]
    rng = np.random.default_rng(5)
    jobs = []
    for _ in range(8):
        src = rng.integers(0, 256, size=300017, dtype=np.uint8)
        dst = np.zeros_like(src)
        jobs.append((pool.memcpy(dst, src), dst, src))
    for w, dst, src in reversed(jobs):
        w.wait()
        np.testing.assert_array_equal(dst, src)


def test_concurrent_submitters(pools):
    """Many Python threads submitting at once — the pool's queue is the
    shared structure the mutex protects."""
    import threading

    pool = pools.get("native") or pools["python"]
    errs = []

    def hammer(seed):
        try:
            rng = np.random.default_rng(seed)
            for _ in range(5):
                a = (rng.random(50021) + 1).astype(np.float64)
                b = (rng.random(50021) + 1).astype(np.float64)
                want = a + b
                pool.reduce("sum", a, b).wait()
                np.testing.assert_allclose(a, want)
        except Exception as exc:  # pragma: no cover - failure path
            errs.append(exc)

    ts = [threading.Thread(target=hammer, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs


def test_global_pool_and_shutdown():
    pool = tbase.get_pool()
    src = np.arange(1000, dtype=np.uint8)
    dst = np.zeros_like(src)
    pool.memcpy(dst, src).wait()
    np.testing.assert_array_equal(dst, src)
    tbase.shutdown_pool()
    # lazily rebuilt after shutdown
    pool2 = tbase.get_pool()
    assert pool2 is not pool
    tbase.shutdown_pool()


def test_workers_var_controls_size():
    from ompi_tpu.base.mca import registry

    var = registry.lookup("otpu_threads_pool_workers")
    assert var is not None
    old = var.value
    try:
        var.set(2)
        assert tbase.default_workers() == 2
        var.set(0)
        import os as _os

        want = max(1, min(4, _os.cpu_count() or 1))
        assert tbase.default_workers() == want
    finally:
        var.set(old)
        tbase.shutdown_pool()


def test_op_host_reduce_pool_path_matches():
    """Op.reduce_arrays above the fan-out threshold (pool path) must be
    bit-identical to the plain ufunc path below it (workers forced to 2
    so a 1-core host still exercises the pool path)."""
    from ompi_tpu.api import op
    from ompi_tpu.base.mca import registry

    var = registry.lookup("otpu_threads_pool_workers")
    old_w = var.value
    tbase.shutdown_pool()
    var.set(2)
    try:
        n = op._POOL_REDUCE_MIN // 4 + 31
        rng = np.random.default_rng(17)
        a = (rng.random(n) + 1).astype(np.float32)
        b = (rng.random(n) + 1).astype(np.float32)
        for o, uf in ((op.SUM, np.add), (op.PROD, np.multiply),
                      (op.MAX, np.maximum), (op.MIN, np.minimum)):
            got = o.reduce_arrays(a, b)
            np.testing.assert_array_equal(got, uf(a, b))
        # below-threshold small path still exact
        np.testing.assert_array_equal(
            op.SUM.reduce_arrays(a[:100], b[:100]),
            np.add(a[:100], b[:100]))
        # non-contiguous operands must take the plain path, not corrupt
        s = a[::2]
        np.testing.assert_array_equal(
            op.SUM.reduce_arrays(s, b[: s.size].copy()),
            np.add(s, b[: s.size]))
    finally:
        var.set(old_w)
        tbase.shutdown_pool()


def test_pool_survives_fork():
    """A forked child (tpurun's worker model) must not inherit dead
    native workers — the handle resets and rebuilds lazily."""
    import os

    if not hasattr(os, "fork"):
        pytest.skip("no fork on this platform")
    tbase.get_pool()          # parent pool exists before fork
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:              # child
        try:
            src = np.arange(300000, dtype=np.uint8)
            dst = np.zeros_like(src)
            tbase.get_pool().memcpy(dst, src).wait()
            ok = b"1" if np.array_equal(dst, src) else b"0"
        except Exception:
            ok = b"0"
        os.write(w, ok)
        os._exit(0)
    os.close(w)
    got = os.read(r, 1)
    os.close(r)
    os.waitpid(pid, 0)
    assert got == b"1"
    tbase.shutdown_pool()


def test_convertor_wide_pack_matches_narrow():
    """Above the fan-out threshold the convertor's pack must be
    byte-identical to the single-thread path (workers forced to 2 so a
    1-core host still exercises the pool path)."""
    from ompi_tpu.base.mca import registry

    var = registry.lookup("otpu_threads_pool_workers")
    old_w = var.value
    tbase.shutdown_pool()
    var.set(2)
    from ompi_tpu.datatype import convertor as conv_mod
    from ompi_tpu.datatype import core
    from ompi_tpu.datatype.convertor import Convertor

    try:
        vec = core.vector(2, 1, 2, core.FLOAT32)  # 4B used, gap, 4B used
        n = (conv_mod._POOL_PACK_MIN // vec.size) + 77
        rng = np.random.default_rng(9)
        buf = rng.random(n * (vec.extent // 4)).astype(np.float32)

        def pack_all():
            c = Convertor(vec, n, buf)
            return c.pack().tobytes()

        wide = pack_all()
        old = conv_mod._POOL_PACK_MIN
        conv_mod._POOL_PACK_MIN = 1 << 62  # force the narrow path
        try:
            narrow = pack_all()
        finally:
            conv_mod._POOL_PACK_MIN = old
    finally:
        var.set(old_w)
        tbase.shutdown_pool()
    assert wide == narrow
