"""btl/tcp fastpath wire format: framing under adversarial segmentation.

The fastpath PR split the tcp frame into a per-fragment header-type
byte negotiating between the fixed struct fast header (eager MATCH /
FRAG continuations — all the payload bytes) and the pickle fallback
(exotic metas).  TCP delivers a byte STREAM: both header kinds must
reassemble exactly when frames arrive split at every awkward boundary
and interleaved on one connection — that is what these tests fuzz,
plus the u32 length prefix's 4GB guard.
"""
import pickle
import random

import numpy as np
import pytest

from ompi_tpu.mca.btl import tcp as tcp_mod
from ompi_tpu.mca.btl.base import ACK, CTL, FRAG, MATCH, RGET, RNDV, Frag


def encode(frag: Frag) -> bytes:
    """Wire-encode one fragment exactly the way TcpBtl.send frames it."""
    payload = frag.data
    if not isinstance(payload, (bytes, bytearray, memoryview)):
        payload = memoryview(payload)
    if isinstance(payload, memoryview) and (
            payload.ndim != 1 or payload.itemsize != 1):
        payload = payload.cast("B")
    hdr = tcp_mod._fast_header(frag)
    if hdr is not None:
        fl = 1 + len(hdr) + len(payload)
        return (tcp_mod._LEN.pack(fl) + bytes((tcp_mod._H_FAST,)) + hdr
                + bytes(payload))
    hdr = pickle.dumps(
        (frag.cid, frag.src, frag.dst, frag.tag, frag.seq, frag.kind,
         frag.total_len, frag.offset, frag.meta),
        protocol=pickle.HIGHEST_PROTOCOL)
    fl = 1 + tcp_mod._LEN.size + len(hdr) + len(payload)
    return (tcp_mod._LEN.pack(fl) + bytes((tcp_mod._H_PICKLE,))
            + tcp_mod._LEN.pack(len(hdr)) + hdr + bytes(payload))


class _FakeConn:
    """The slice of _Conn that _drain/_parse_frame touch."""

    def __init__(self, rank=7):
        self.rank = rank
        self.inbuf = bytearray()


def _collect(btl):
    got = []
    btl.set_recv_callback(got.append)
    return got


def _assert_same(orig: Frag, back: Frag):
    assert (orig.cid, orig.src, orig.dst, orig.tag, orig.seq, orig.kind,
            orig.total_len, orig.offset) == \
           (back.cid, back.src, back.dst, back.tag, back.seq, back.kind,
            back.total_len, back.offset)
    assert dict(orig.meta) == dict(back.meta)
    assert bytes(memoryview(np.ascontiguousarray(orig.data))) \
        == bytes(memoryview(np.ascontiguousarray(back.data)))


def _mixed_frags(rng: random.Random, n=24) -> list:
    """Fragments that alternate fast- and pickle-header eligibility."""
    frags = []
    for i in range(n):
        payload = np.frombuffer(
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200))),
            np.uint8)
        pick = i % 4
        if pick == 0:       # eager MATCH, empty meta -> fast header
            f = Frag(3, 0, 1, rng.randrange(1000), i, MATCH, payload,
                     total_len=len(payload))
        elif pick == 1:     # FRAG continuation -> fast header (req_id)
            f = Frag(3, 1, 0, -1, 0, FRAG, payload,
                     total_len=1 << 20, offset=rng.randrange(1 << 20),
                     meta={"req_id": rng.randrange(1 << 40)})
        elif pick == 2:     # RNDV with rich meta -> pickle
            f = Frag(3, 0, 1, rng.randrange(1000), i, RNDV, payload,
                     total_len=len(payload) + 512,
                     meta={"req_id": i, "window": [1, 2]})
        else:               # CTL proto -> pickle
            f = Frag(3, 1, 0, -1, 0, CTL, payload,
                     meta={"proto": "ob1_rget_done", "req_id": i})
        frags.append(f)
    return frags


def test_header_type_selection():
    data = np.arange(8, dtype=np.uint8)
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 5, 9, MATCH, data, total_len=8)) is not None
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, -1, 0, FRAG, data, total_len=64, offset=8,
             meta={"req_id": 3})) is not None
    # anything beyond {req_id} falls back to pickle
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 5, 9, ACK, data,
             meta={"req_id": 3, "peer_req": 4})) is None
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 5, 9, RGET, data, meta={"key": (1, 2)})) is None
    # out-of-struct-range fields must not silently truncate on the wire
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 1 << 40, 9, MATCH, data)) is None
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 5, 9, MATCH, data,
             meta={"req_id": -5})) is None
    assert tcp_mod._fast_header(
        Frag(1, 0, 1, 5, 9, "weird_kind", data)) is None


@pytest.mark.parametrize("seed", range(6))
def test_fuzzed_split_boundaries_mixed_headers(seed):
    """Mixed fast/pickle frames, delivered in random chunk sizes that
    split frames at every kind of boundary (inside the length prefix,
    inside the htype byte, inside headers, inside payloads)."""
    rng = random.Random(seed)
    frags = _mixed_frags(rng)
    stream = b"".join(encode(f) for f in frags)
    btl = tcp_mod.TcpBtl()
    got = _collect(btl)
    conn = _FakeConn()
    pos = 0
    while pos < len(stream):
        step = rng.choice((1, 2, 3, 5, 7, 13, 64, 1024))
        conn.inbuf += stream[pos:pos + step]
        pos += step
        btl._drain(conn)
    assert len(got) == len(frags)
    for orig, back in zip(frags, got):
        _assert_same(orig, back)
    assert not conn.inbuf, "stream fully consumed"


def test_byte_at_a_time_delivery():
    """The pathological segmentation: one byte per recv."""
    frags = _mixed_frags(random.Random(99), n=6)
    stream = b"".join(encode(f) for f in frags)
    btl = tcp_mod.TcpBtl()
    got = _collect(btl)
    conn = _FakeConn()
    for i in range(len(stream)):
        conn.inbuf += stream[i:i + 1]
        btl._drain(conn)
    assert len(got) == len(frags)
    for orig, back in zip(frags, got):
        _assert_same(orig, back)


def test_handshake_interleaved_with_data_frames():
    """A fresh inbound connection identifies itself with a pickle-header
    handshake frame; data frames (fast and pickle) follow on the same
    connection and must parse with the now-known rank."""
    hello = pickle.dumps({"rank": 5})
    hs = (tcp_mod._LEN.pack(1 + tcp_mod._LEN.size + len(hello))
          + bytes((tcp_mod._H_PICKLE,)) + tcp_mod._LEN.pack(len(hello))
          + hello)
    f_fast = Frag(2, 5, 0, 11, 0, MATCH, np.arange(16, dtype=np.uint8),
                  total_len=16)
    f_pickle = Frag(2, 5, 0, 11, 1, RNDV, np.arange(4, dtype=np.uint8),
                    total_len=1024, meta={"req_id": 1, "x": "y"})
    stream = hs + encode(f_fast) + encode(f_pickle)
    btl = tcp_mod.TcpBtl()
    got = _collect(btl)
    conn = _FakeConn(rank=None)
    conn.inbuf += stream
    btl._drain(conn)
    assert conn.rank == 5               # handshake consumed, rank learned
    assert btl._by_rank[5] == [conn]    # conn became the reply rail
    assert len(got) == 2
    _assert_same(f_fast, got[0])
    _assert_same(f_pickle, got[1])


def test_frame_too_large_guard(capsys):
    """A frame that cannot fit the u32 length prefix must fail loudly at
    the sender, never truncate on the wire.  A zero-stride broadcast
    array gives a >4GB payload without allocating one, and the guard
    fires on ``nbytes`` BEFORE any connect/memoryview work."""
    btl = tcp_mod.TcpBtl()
    huge = np.broadcast_to(np.zeros(1, np.uint8), ((1 << 32) + 10,))
    frag = Frag(1, 0, 1, 5, 0, MATCH, huge, total_len=huge.nbytes)

    class _Ep:
        world_rank = 1

    with pytest.raises(ValueError, match="length-prefix"):
        btl.send(_Ep(), frag)
    assert "frame" in capsys.readouterr().err.lower()


@pytest.mark.parametrize("seed", range(4))
def test_on_bytes_streaming_path_fuzzed(seed):
    """The zero-copy streaming receive path (_on_bytes): frames parsed
    straight from recv-scratch views arrive ``borrowed``; frames split
    across recv boundaries reassemble through inbuf and arrive owned.
    Payload bytes must be identical either way."""
    rng = random.Random(1000 + seed)
    frags = _mixed_frags(rng, n=18)
    stream = b"".join(encode(f) for f in frags)
    btl = tcp_mod.TcpBtl()
    got = []
    # snapshot payload bytes AT DELIVERY: borrowed views die when the
    # next chunk overwrites the scratch, exactly like a real recv loop
    btl.set_recv_callback(
        lambda f: got.append((f, bytes(memoryview(
            np.ascontiguousarray(f.data))), f.borrowed)))
    conn = _FakeConn()
    saw_borrowed = saw_owned = False
    # force both paths deterministically: split the first frame's length
    # prefix (reassembly -> owned), deliver the tail as one big chunk
    # (complete frames from one view -> borrowed), fuzz in between
    btl._on_bytes(conn, memoryview(bytearray(stream[:2])))
    pos = 2
    while pos < len(stream) - 8192:
        step = rng.choice((5, 37, 256, 4096))
        chunk = stream[pos:pos + step]
        pos += step
        btl._on_bytes(conn, memoryview(bytearray(chunk)))
    btl._on_bytes(conn, memoryview(bytearray(stream[pos:])))
    assert len(got) == len(frags)
    for orig, (back, payload, borrowed) in zip(frags, got):
        assert (orig.cid, orig.src, orig.dst, orig.tag, orig.seq,
                orig.kind, orig.total_len, orig.offset) == \
               (back.cid, back.src, back.dst, back.tag, back.seq,
                back.kind, back.total_len, back.offset)
        assert dict(orig.meta) == dict(back.meta)
        assert bytes(memoryview(np.ascontiguousarray(orig.data))) \
            == payload
        saw_borrowed |= borrowed
        saw_owned |= not borrowed
    # the fuzz must exercise BOTH delivery paths
    assert saw_borrowed and saw_owned
    assert not conn.inbuf


def test_own_queued_copies_only_the_tail():
    """Backpressure ownership is O(remainder): ``_own_queued_locked``
    owns only the entries the current send queued (the queue's tail).  A
    standing backlog of frames owned at their own send time must ride
    untouched — re-copying it per borrowed send would be the O(n²)
    pathology the deque out-queue replaced."""
    import socket

    a, b = socket.socketpair()
    btl = tcp_mod.TcpBtl()
    conn = tcp_mod._Conn(a, rank=1)
    backlog = [memoryview(bytes([i]) * 64) for i in range(6)]
    conn.outq.extend(backlog)
    user = bytearray(b"x" * 128)          # the caller's borrowed buffer
    conn.outq.append(memoryview(b"H" * 16))          # this send's header
    conn.outq.append(memoryview(user))
    with conn.send_lock:                  # the *_locked contract
        btl._own_queued_locked(conn, 2)
    q = list(conn.outq)
    assert len(q) == 8
    for orig, now in zip(backlog, q[:6]):
        assert now is orig               # backlog entries not re-copied
    user[:] = b"y" * 128                 # tail owned: caller's mutation
    assert bytes(q[7]) == b"x" * 128     # must not reach the queue
    assert bytes(q[6]) == b"H" * 16
    a.close()
    b.close()


def test_sendmsg_flush_trace_histogram():
    """With tracing enabled, every sendmsg flush lands a ``btl_sendmsg``
    span + log2-size histogram bin (the fastpath observability
    satellite; surfaces as ``otpu_trace_hist_btl_sendmsg_*`` pvars)."""
    import socket

    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import trace

    a, b = socket.socketpair()
    a.setblocking(False)
    btl = tcp_mod.TcpBtl()
    conn = tcp_mod._Conn(a, rank=1)
    registry.set("otpu_trace_enable", True)
    try:
        before = len([k for k in trace.histograms()
                      if k[0] == "btl_sendmsg"])
        payload = memoryview(bytes(range(256)) * 16)
        with conn.send_lock:
            conn.outq.append(payload)
            conn.out_bytes = len(payload)
            btl._flush_locked(conn)
        hist = trace.histograms()
        assert any(k[0] == "btl_sendmsg" for k in hist), hist
    finally:
        registry.set("otpu_trace_enable", False)
        a.close()
        b.close()


def test_fast_header_roundtrip_extremes():
    """Field extremes survive the struct: max u32 ranks/cid, negative
    tag, 63-bit seq/offset/req_id."""
    payload = np.arange(3, dtype=np.uint8)
    f = Frag((1 << 32) - 1, (1 << 32) - 1, 0, -(1 << 31), (1 << 62),
             FRAG, payload, total_len=(1 << 62), offset=(1 << 61),
             meta={"req_id": (1 << 62)})
    btl = tcp_mod.TcpBtl()
    got = _collect(btl)
    conn = _FakeConn()
    conn.inbuf += encode(f)
    btl._drain(conn)
    assert len(got) == 1
    _assert_same(f, got[0])
