"""otpu-crit: causal flow keys and cross-rank critical-path attribution.

Four layers of coverage:

* flow layer units: flow events' Chrome schema (ids, binding point),
  the flow-disabled identity, per-comm collective sequence agreement,
  and pml span flow-key stamping on a loopback send/recv;
* critical-path units on synthetic timelines: barrier edges blame the
  last-arriving rank, message edges jump send-complete -> recv, the
  critical exposed-comm fraction counts only on-path comm, and the
  report diffs;
* ``--suggest-ladder``: the draft rules file is schema-valid for
  ``coll/tuned._load_rules``, versioned, and skips colls with no
  ladder;
* THE acceptance run — a chaos ``delay:ms=8,rank=2,site=step`` 3-rank
  job: ``--critical-path`` attributes >= 90% of steps to rank 2 with a
  per-stage blame breakdown, flow events link >= 95% of pml sends to
  their recvs in the merged Chrome export, and ``--suggest-ladder``
  emits a loadable draft rules file.
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from ompi_tpu.base.var import registry
from ompi_tpu.runtime import trace

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "crit_worker.py"


@pytest.fixture
def tracer():
    registry.set("otpu_trace_enable", True)
    registry.set("otpu_trace_flow", True)
    trace.reset_for_testing()
    yield trace
    registry.set("otpu_trace_enable", False)
    registry.set("otpu_trace_flow", True)
    trace.reset_for_testing()


# ------------------------------------------------------ flow layer units

def test_flow_events_chrome_schema(tracer):
    t0 = trace.now()
    trace.span("send", "pml", t0, args={"fid": (7, 0, 1, 3)})
    trace.flow_start("pml_msg", (7, 0, 1, 3))
    trace.flow_finish("pml_msg", "7.0.1.3")
    evs = trace.chrome_events()
    s = next(e for e in evs if e["ph"] == "s")
    f = next(e for e in evs if e["ph"] == "f")
    # tuple and string keys render the same documented id format
    assert s["id"] == f["id"] == "7.0.1.3"
    assert s["cat"] == f["cat"] == "flow"
    assert s["name"] == f["name"] == "pml_msg"
    assert f["bp"] == "e"           # binds to the enclosing recv slice
    assert "dur" not in s and "dur" not in f
    # the whole payload JSON round-trips
    json.loads(json.dumps(trace.chrome_payload(0)))


def test_flow_disabled_is_identity(tracer):
    registry.set("otpu_trace_flow", False)
    assert trace.enabled is True and trace.flow_enabled is False
    before = trace.recorded_count()
    trace.flow_start("pml_msg", (1, 0, 1, 0))
    trace.flow_finish("pml_msg", (1, 0, 1, 0))
    assert trace.recorded_count() == before
    # tracing off forces flow off regardless of the var
    registry.set("otpu_trace_flow", True)
    registry.set("otpu_trace_enable", False)
    assert trace.flow_enabled is False
    registry.set("otpu_trace_enable", True)
    assert trace.flow_enabled is True


def test_coll_seq_counts_per_comm(tracer):
    assert trace.next_coll_seq(4) == 0
    assert trace.next_coll_seq(4) == 1
    assert trace.next_coll_seq(9) == 0
    assert trace.next_coll_seq(4) == 2
    trace.reset_for_testing()
    assert trace.next_coll_seq(4) == 0      # counters reset with state


def test_coll_wrapper_stamps_cseq(tracer):
    class _FakeComm:
        cid = 11

        def __init__(self):
            self.c_coll = {}

    import numpy as np

    comm = _FakeComm()
    comm.c_coll["allreduce"] = lambda c, x: x
    trace.wrap_coll_table(comm)
    x = np.ones(16, np.float32)
    for _ in range(3):
        comm.c_coll["allreduce"](comm, x)
    spans = [e for e in trace.chrome_events()
             if e["name"] == "allreduce"]
    assert [e["args"]["cseq"] for e in spans] == [0, 1, 2]
    # flow off: no cseq stamped, span otherwise identical
    registry.set("otpu_trace_flow", False)
    comm.c_coll["allreduce"](comm, x)
    last = [e for e in trace.chrome_events()
            if e["name"] == "allreduce"][-1]
    assert "cseq" not in last["args"] and last["args"]["cid"] == 11


def test_pml_spans_carry_flow_key_on_loopback():
    """A self send/recv crosses the full pml datapath: the send and
    recv spans must share the stamped flow key and the s/f flow events
    must link on the same id."""
    import numpy as np

    import ompi_tpu
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    registry.set("otpu_trace_enable", True)
    registry.set("otpu_trace_flow", True)
    trace.reset_for_testing()
    try:
        w = ompi_tpu.init()
        x = np.ones(64, np.float32)
        buf = np.empty_like(x)
        a, b = w.as_rank(0), w.as_rank(1)
        a.send(x, dest=1, tag=3)
        b.recv(buf, source=0, tag=3)
        evs = trace.chrome_events()
        sends = [e for e in evs if e.get("name") == "send"
                 and e.get("cat") == "pml"]
        recvs = [e for e in evs if e.get("name") == "recv"
                 and e.get("cat") == "pml"]
        assert sends and recvs
        sfid = tuple(sends[-1]["args"]["fid"])
        rfid = tuple(recvs[-1]["args"]["fid"])
        assert sfid == rfid
        flow_s = {e["id"] for e in evs if e["ph"] == "s"}
        flow_f = {e["id"] for e in evs if e["ph"] == "f"}
        assert flow_s & flow_f
    finally:
        registry.set("otpu_trace_enable", False)
        trace.reset_for_testing()
        rt.reset_for_testing()


# --------------------------------------------- critical path (synthetic)

def _span(pid, name, cat, ts, dur, args=None):
    e = {"ph": "X", "pid": pid, "tid": 1, "name": name, "cat": cat,
         "ts": float(ts), "dur": float(dur)}
    if args:
        e["args"] = args
    return e


def _slow_rank_timeline(steps=5, slow=2, nranks=3):
    """Back-to-back steps: fast ranks enter the allreduce at +10, the
    slow rank computes until +100 and everyone releases at +120."""
    events = []
    for k in range(steps):
        t0 = k * 125.0
        for r in range(nranks):
            late = r == slow
            events.append(_span(r, "step", "step", t0,
                                121.0 if late else 122.0, {"step": k}))
            events.append(_span(
                r, "allreduce", "coll",
                t0 + (100 if late else 10),
                20.0 if late else 110.0,
                {"cid": 0, "cseq": k, "nbytes": 4096}))
    events.sort(key=lambda e: e["ts"])
    return events


def test_critical_path_blames_last_arrival():
    from ompi_tpu.tools import otpu_analyze as oa

    rep = oa.analyze(_slow_rank_timeline(), critical_path=True)
    cp = rep["critical_path"]
    assert len(cp["steps"]) == 5
    assert cp["bound_by"]["rank"] == 2
    assert cp["bound_by"]["fraction"] == 1.0
    # only ON-path comm counts: the fast ranks sit ~90% of the step
    # inside the collective, but the path runs through rank 2's compute
    assert cp["critical_exposed_comm"] < 0.3
    naive = rep["exposed_comm"]
    assert float(naive["0"]) > 0.8        # the naive number the crit
    #                                       fraction exists to correct
    assert cp["top_blockers"][0]["rank"] == 2
    assert "allreduce/4k" in cp["coll_critical_us"]
    step = cp["steps"][0]
    assert step["bound_by"] == 2
    assert step["buckets"]["compute"] > step["buckets"]["coll"]
    assert "2" in step["on_path_us"]


def test_critical_path_follows_message_edges():
    """P2P-only workload: rank 1's recv waits on rank 0's late send —
    the walk must jump the message edge and land the blame on rank 0's
    compute, with the recv wait counted as on-path comm."""
    from ompi_tpu.tools import otpu_analyze as oa

    events = []
    for k in range(4):
        t0 = k * 1000.0
        # rank 0: long compute, send completes at +200
        events.append(_span(0, "step", "step", t0, 205.0, {"step": k}))
        events.append(_span(0, "send", "pml", t0 + 190, 10.0,
                            {"cid": 0, "fid": [0, 0, 1, k],
                             "nbytes": 4096}))
        # rank 1: posts the recv immediately, waits until +202
        events.append(_span(1, "step", "step", t0, 206.0, {"step": k}))
        events.append(_span(1, "recv", "pml", t0 + 2, 200.0,
                            {"cid": 0, "fid": [0, 0, 1, k],
                             "nbytes": 4096}))
    events.sort(key=lambda e: e["ts"])
    rep = oa.analyze(events, critical_path=True)
    cp = rep["critical_path"]
    assert cp["bound_by"]["rank"] == 0, cp
    # rank 0 owns most of the path (its compute); rank 1 only the
    # post-send delivery tail
    top = {row["rank"]: row["on_path_us"] for row in cp["top_blockers"]}
    assert top[0] > 3 * top.get(1, 0.1)


def test_critical_path_without_steps_notes_it():
    from ompi_tpu.tools import otpu_analyze as oa

    events = [_span(0, "allreduce", "coll", 0.0, 5.0,
                    {"cid": 0, "cseq": 0, "nbytes": 64}),
              _span(1, "allreduce", "coll", 1.0, 4.0,
                    {"cid": 0, "cseq": 0, "nbytes": 64})]
    rep = oa.analyze(events, critical_path=True)
    assert rep["critical_path"]["steps"] == []
    assert "step" in rep["critical_path"]["note"]


def test_diff_reports_tracks_critical_path():
    from ompi_tpu.tools import otpu_analyze as oa

    old = oa.analyze(_slow_rank_timeline(slow=2), critical_path=True)
    new = oa.analyze(_slow_rank_timeline(slow=1), critical_path=True)
    d = oa.diff_reports(old, new)
    assert d["critical_bound_by_changed"] is True
    assert d["critical_bound_by"] == [2, 1]
    assert "critical_exposed_comm_delta" in d
    assert "allreduce/4k" in d["coll_critical_us_delta"]
    same = oa.diff_reports(old, old)
    assert same["critical_bound_by_changed"] is False


# ------------------------------------------------------- suggest-ladder

def _apply_rules(rules, coll, nbytes):
    """First-match-wins evaluation, exactly tuned._pick's rule scan."""
    for rcoll, _max_size, max_bytes, alg, _seg in rules:
        if rcoll != coll:
            continue
        if max_bytes and nbytes > max_bytes:
            continue
        return alg
    return None


def test_suggest_ladder_is_schema_valid_and_behavior_identical(tmp_path):
    from ompi_tpu.mca.coll.tuned import (_MENUS, _load_rules,
                                         default_algorithm)
    from ompi_tpu.tools import otpu_analyze as oa

    rep = oa.analyze(_slow_rank_timeline(), critical_path=True)
    text = oa.suggest_ladder(rep, comm_size=3)
    assert text.startswith("# otpu-crit suggested tuning ladder v1")
    out = tmp_path / "draft.rules"
    out.write_text(text)
    rules = _load_rules(str(out))       # tuned's own loader accepts it
    assert rules
    coll, max_size, max_bytes, alg, seg = rules[0]
    assert coll == "allreduce" and max_size == 3
    assert alg in _MENUS["allreduce"]
    assert "critical_us=" in text       # annotated with measurements
    # loading the draft must change NO pick: every covered size gets
    # exactly the fixed ladder's incumbent, and uncovered sizes fall
    # through to the fixed ladder itself
    for nb in (0, 1, 64, 2048, 4096, 4097, 8191, 65536, 1 << 19,
               1 << 21, 8 << 20):
        got = _apply_rules(rules, "allreduce", nb)
        if got is not None:
            assert got == default_algorithm("allreduce", 3, nb), nb


def test_dynamic_rules_skipped_for_noncommutative_ops():
    """A machine-generated (or hand-written) rules file cannot express
    commutativity; tuned must never let it route a non-commutative
    reduction onto an operand-reordering algorithm (the fixed ladder's
    :77-80 exclusions stay authoritative)."""
    from ompi_tpu.mca.coll.tuned import COMPONENT, TunedModule

    if not hasattr(COMPONENT, "_force"):
        COMPONENT._force = {}
        COMPONENT._seg = {}
    saved = COMPONENT.rules
    COMPONENT.rules = [("allreduce", 0, 0, "ring", 0)]
    try:
        m = TunedModule(COMPONENT)
        # commutative traffic takes the rule
        assert m._pick("allreduce", 4, 1024, "recursive_doubling",
                       commute=True) == ("ring", 0)
        # non-commutative traffic ignores it (ring reorders operands)
        assert m._pick("allreduce", 4, 1024, "recursive_doubling",
                       commute=False) == ("recursive_doubling", 0)
    finally:
        COMPONENT.rules = saved


def test_suggest_ladder_skips_unladdered_colls():
    from ompi_tpu.tools import otpu_analyze as oa

    report = {"critical_path": {
        "steps": [{}],
        "coll_critical_us": {"allreduce_array/4k": 100.0},
        "_coll_critical_nbytes": {"allreduce_array/4k": 4096},
    }}
    text = oa.suggest_ladder(report, comm_size=3)
    assert "allreduce_array" not in text.replace(
        "# (no collective time on the critical path)", "")
    assert "no collective time" in text


def test_ladder_rules_reproduce_fixed_ladder():
    """``tuned.ladder_rules`` (what --suggest-ladder emits per coll)
    is breakpoint-exact: first-match-wins over its rows equals
    ``default_algorithm`` for every covered size, fall-through above —
    including alltoall's per-block (non-pow2) threshold."""
    from ompi_tpu.mca.coll.tuned import default_algorithm, ladder_rules

    probes = (0, 1, 255, 256, 767, 768, 769, 1023, 4096, 4097, 8191,
              65535, 65536, (1 << 19) - 1, 1 << 19, (4 << 20) - 1,
              4 << 20, 1 << 25)
    for coll in ("allreduce", "bcast", "alltoall", "barrier",
                 "reduce_scatter"):
        for size in (2, 3, 8):
            for commute in (True, False):
                rows = ladder_rules(coll, size, 1 << 23, commute)
                for nb in probes:
                    want = default_algorithm(coll, size, nb, commute)
                    got = next((alg for mx, alg in rows
                                if not (mx and nb > mx)), None)
                    assert got in (None, want), (coll, size, commute,
                                                 nb, got, want)


def test_default_algorithm_matches_ladder_shape():
    """The extracted pure ladder keeps the dispatch methods' exact
    boundaries (the suggest-ladder draft must name the incumbent the
    running system would actually pick)."""
    from ompi_tpu.mca.coll.tuned import _MENUS, default_algorithm

    assert default_algorithm("allreduce", 4, 4096) == \
        "recursive_doubling"            # boundary inclusive
    assert default_algorithm("allreduce", 4, 4097) == "rabenseifner"
    assert default_algorithm("allreduce", 4, 1 << 20) == "ring"
    assert default_algorithm("allreduce", 4, 8 << 20) == "ring_segmented"
    assert default_algorithm("allreduce", 2, 64, commute=False) == \
        "nonoverlapping"
    assert default_algorithm("bcast", 8, 1024) == "binomial"
    assert default_algorithm("bcast", 8, 4096) == "scatter_allgather"
    assert default_algorithm("barrier", 4, 0) == "recursive_doubling"
    assert default_algorithm("barrier", 5, 0) == "bruck"
    assert default_algorithm("alltoall", 4, 512) == "bruck"
    assert default_algorithm("alltoall", 4, 4096) == "pairwise"
    with pytest.raises(KeyError):
        default_algorithm("nope", 4, 0)
    # every pick is a real menu entry for its collective
    for coll in _MENUS:
        for size in (2, 3, 8):
            for nb in (0, 512, 4096, 1 << 17, 1 << 21, 8 << 20):
                assert default_algorithm(coll, size, nb) in _MENUS[coll]
                assert default_algorithm(coll, size, nb,
                                         commute=False) in _MENUS[coll]


# ----------------------------------------------- ring overflow honesty

def test_analyzer_report_pins_ring_overflow(tmp_path, tracer):
    """The ring-wrap counter travels: ring -> payload metadata ->
    load_run meta -> report header (text and parsable) — a silent wrap
    would make critical paths lie."""
    from ompi_tpu.tools import otpu_analyze as oa

    n = trace._ring_n
    extra = 137
    for i in range(n + extra):
        trace.span("s", "coll", trace.now(),
                   args={"cid": 0, "nbytes": 0})
    payload = trace.chrome_payload(0)
    assert payload["metadata"]["events_overwritten"] == extra
    p = tmp_path / "trace_rank0.json"
    p.write_text(json.dumps(payload))
    events, profiles, meta = oa.load_run([str(p)])
    assert meta["events_overwritten"] == {0: extra}
    rep = oa.analyze(events, profiles=profiles, meta=meta)
    assert rep["events_overwritten"]["total"] == extra
    assert rep["events_overwritten"]["per_rank"] == {"0": extra}
    text = oa.render_text(rep)
    assert "WARNING" in text and str(extra) in text
    parsable = oa.render_text(rep, parsable=True)
    assert f"events_overwritten:{extra}:" in parsable


def test_analyze_includes_zero_span_payload_ranks(tmp_path):
    """A rank whose payload carries zero spans (crash bundle) still
    appears in the report's rank list instead of silently vanishing."""
    from ompi_tpu.tools import otpu_analyze as oa

    (tmp_path / "trace_rank0.json").write_text(json.dumps({
        "traceEvents": [_span(0, "allreduce", "coll", 10.0, 5.0,
                              {"cid": 0, "nbytes": 64})],
        "metadata": {"rank": 0, "clock_offset_us": 0.0}}))
    (tmp_path / "trace_rank1.json").write_text(json.dumps({
        "traceEvents": [],
        "metadata": {"rank": 1, "clock_offset_us": -250.0}}))
    events, profiles, meta = oa.load_run([str(tmp_path)])
    assert meta["payload_ranks"] == [0, 1]
    rep = oa.analyze(events, profiles=profiles, meta=meta)
    assert rep["ranks"] == [0, 1]


# ------------------------------------------------- THE acceptance run

def test_critical_path_acceptance_designed_slow_rank(tmp_path):
    """THE otpu-crit acceptance (ISSUE 14): chaos
    ``delay:ms=8,rank=2,site=step`` on a 3-rank job — the critical
    path attributes >= 90% of steps to rank 2 with a per-stage blame
    breakdown, flow events link >= 95% of pml sends to their recvs in
    the merged Chrome export, and --suggest-ladder emits a draft rules
    file coll/tuned can load."""
    tdir = tmp_path / "trace"
    env = dict(os.environ, JAX_PLATFORMS="cpu", CW_ITERS="20")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.pop("OTPU_COORD", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--mca", "otpu_chaos_spec", "delay:ms=8,p=1,rank=2,site=step",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", str(tdir),
           # collectives through the pml datapath so sends are spanned
           "--mca", "otpu_coll_sm_coll_priority", "0",
           sys.executable, str(WORKER)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert out.count("CRIT WORKER DONE") == 3, out
    merged = json.load(open(tdir / "trace_merged.json"))
    evs = merged["traceEvents"]
    # flow arrows present and >= 95% of pml sends link to a recv
    s_ids = {e["id"] for e in evs if e.get("ph") == "s"}
    f_ids = {e["id"] for e in evs if e.get("ph") == "f"}
    sends = [e for e in evs
             if e.get("cat") == "pml" and e.get("name") == "send"]
    assert sends and s_ids, "no pml flow starts in the merged export"
    assert len(s_ids & f_ids) / len(s_ids) >= 0.95, (
        len(s_ids), len(s_ids & f_ids))
    from ompi_tpu.tools import otpu_analyze as oa

    events, profiles, meta = oa.load_run([str(tdir)])
    rep = oa.analyze(events, profiles=profiles, meta=meta,
                     critical_path=True)
    cp = rep["critical_path"]
    assert len(cp["steps"]) >= 18, len(cp["steps"])
    assert cp["bound_by"]["rank"] == 2, cp["bound_by"]
    assert cp["bound_by"]["fraction"] >= 0.90, cp["bound_by"]
    # per-stage blame breakdown: every step row carries the buckets
    for step in cp["steps"]:
        assert set(step["buckets"]) == {"compute", "send", "recv",
                                        "coll"}
    assert cp["top_blockers"][0]["rank"] == 2
    # the slow rank's time is its own compute (the pace delay), NOT
    # comm: critical exposed-comm sits well under the fast ranks'
    # naive exposed-comm fraction
    naive_fast = max(float(rep["exposed_comm"].get("0", 0)),
                     float(rep["exposed_comm"].get("1", 0)))
    assert cp["critical_exposed_comm"] < naive_fast
    # --suggest-ladder end to end through the CLI
    ladder = tmp_path / "draft.rules"
    rep_path = tmp_path / "report.json"
    rc = oa.main([str(tdir), "--critical-path",
                  "--suggest-ladder", str(ladder),
                  "--json", str(rep_path)])
    assert rc == 0
    from ompi_tpu.mca.coll.tuned import _load_rules

    rules = _load_rules(str(ladder))
    assert rules and any(c == "allreduce" for c, *_ in rules), rules
    again = json.loads(rep_path.read_text())
    assert again["critical_path"]["bound_by"]["rank"] == 2
    assert oa.diff_reports(again, rep)[
        "critical_bound_by_changed"] is False
