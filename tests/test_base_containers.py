"""Container tests mirroring the reference unit suite (test/class, SURVEY.md §4)."""
import time

from ompi_tpu.base.containers import (
    Bitmap,
    Fifo,
    Graph,
    Hotel,
    IntervalTree,
    Lifo,
    PointerArray,
    RingBuffer,
)


def test_fifo_order():
    f = Fifo()
    for i in range(5):
        f.push(i)
    assert [f.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
    assert f.pop() is None


def test_lifo_order():
    s = Lifo()
    for i in range(5):
        s.push(i)
    assert [s.pop() for _ in range(5)] == [4, 3, 2, 1, 0]


def test_pointer_array_reuse():
    pa = PointerArray(lowest_free=2)
    i = pa.add("x")
    assert i == 2
    j = pa.add("y")
    pa.remove(i)
    k = pa.add("z")
    assert k == i  # index reuse
    assert pa.get(j) == "y"
    assert dict(iter(pa)) == {j: "y", k: "z"}


def test_bitmap():
    b = Bitmap(8)
    b.set(3)
    b.set(7)
    assert b.is_set(3) and not b.is_set(4)
    assert list(b) == [3, 7]
    assert b.find_and_set_first_unset() == 0
    b.clear(3)
    assert not b.is_set(3)
    b.set_all()
    assert b.popcount() == 8


def test_ring_buffer_overwrites():
    r = RingBuffer(3)
    for i in range(5):
        r.push(i)
    assert r.snapshot() == [2, 3, 4]


def test_hotel_checkin_checkout_evict():
    evicted = []
    h = Hotel(2, eviction_s=0.0, on_evict=lambda room, occ: evicted.append(occ))
    r1 = h.checkin("a")
    r2 = h.checkin("b")
    assert h.checkin("c") == -1  # full
    assert h.checkout(r1) == "a"
    h.sweep(now=time.monotonic() + 1)
    assert evicted == ["b"]
    assert len(h) == 0


def test_interval_tree():
    t = IntervalTree()
    t.insert(0, 100, "big")
    t.insert(10, 20, "small")
    assert {v for *_, v in t.find_overlapping(15, 30)} == {"big", "small"}
    assert t.find_containing(12, 18)[2] == "small"  # smallest containing
    assert t.find_containing(50, 60)[2] == "big"
    t.delete(10, 20)
    assert t.find_containing(12, 18)[2] == "big"


def test_graph_shortest_path():
    g = Graph()
    g.add_edge("a", "b", 1)
    g.add_edge("b", "c", 1)
    g.add_edge("a", "c", 5)
    assert g.shortest_path("a", "c") == ["a", "b", "c"]
    assert g.shortest_path("c", "a") is None
