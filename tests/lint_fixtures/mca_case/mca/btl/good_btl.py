"""Known-good twin: the contract-complete btl component."""
from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType, registry

_ok_var = registry.register(            # group matches the framework
    "btl", "fine", "mode", vtype=VarType.STRING, default="")


class FineBtl(Component):
    name = "fine"
    priority = 5

    def register_vars(self, fw):
        self.register_var("eager_limit", vtype=VarType.SIZE, default="64k",
                          help="ok")

    def send(self, ep, frag):
        pass


COMPONENT = FineBtl()
