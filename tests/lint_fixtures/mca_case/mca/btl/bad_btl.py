"""Known-bad: a btl component breaking the framework contract."""
import os

from ompi_tpu.base.mca import Component
from ompi_tpu.base.var import VarType, registry

_side_var = registry.register(          # BAD: wrong group for mca/btl
    "transport", None, "mode", vtype=VarType.STRING, default="")


class BrokenBtl(Component):             # BAD: no 'send' slot, no name
    priority = 5

    def register_vars(self, fw):
        # BAD: raw env read instead of an MCA var
        self._mode = os.environ.get("OTPU_BROKEN_MODE", "")
        self.register_var("eager_limit", vtype=VarType.SIZE, default="64k",
                          help="ok")

# BAD: no COMPONENT export — discovery silently skips this module
