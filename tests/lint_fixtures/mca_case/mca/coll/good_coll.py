"""Known-good twin: the contract-complete coll component (the
coll/quant shape: a codec/config home whose comm_query declines)."""
from ompi_tpu.base.mca import Component


class FineCollComponent(Component):
    name = "finecoll"
    priority = 5

    def register_vars(self, fw):
        pass

    def comm_query(self, comm):
        return None


COMPONENT = FineCollComponent()
