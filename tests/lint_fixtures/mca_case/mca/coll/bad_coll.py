"""Known-bad: a coll component missing its required query slot."""
from ompi_tpu.base.mca import Component


class HalfCollComponent(Component):     # BAD: no 'comm_query' slot
    name = "halfcoll"
    priority = 5

    def register_vars(self, fw):
        pass


COMPONENT = HalfCollComponent()
