"""Known-bad: inconsistent lock-acquisition order (A->B and B->A)."""
import threading


class TwoLocks:
    _guarded_by = {"_a": "_lock_a", "_b": "_lock_b"}

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._a = {}
        self._b = {}

    def ab(self, k, v):
        with self._lock_a:
            self._a[k] = v
            with self._lock_b:          # edge a -> b
                self._b[k] = v

    def ba(self, k, v):
        with self._lock_b:
            self._b[k] = v
            with self._lock_a:          # edge b -> a: CYCLE
                self._a[k] = v
