"""Known-good twin: nested acquisition always in the same order."""
import threading


class TwoLocks:
    _guarded_by = {"_a": "_lock_a", "_b": "_lock_b"}

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._a = {}
        self._b = {}

    def ab(self, k, v):
        with self._lock_a:
            self._a[k] = v
            with self._lock_b:
                self._b[k] = v

    def also_ab(self, k):
        with self._lock_a:
            del self._a[k]
            with self._lock_b:          # same order: acyclic
                self._b.pop(k, None)
