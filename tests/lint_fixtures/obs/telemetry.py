"""Fixture stand-in for runtime/telemetry.py: the declared sample
schema the register_source rule checks literal names against."""

SCHEMA = {
    "tcp": "transport out-queue depth",
    "serving": "scheduler queue depth",
    "fleet": "serving-fleet pool/prefix/autoscale tables",
    "slo": "per-pool/per-tenant SLO burn accounting",
    "moe": "MoE dispatch/dropped-token and load-imbalance tables",
    "frontdoor": "serving admission plane: queue depths, sheds, holds",
}
