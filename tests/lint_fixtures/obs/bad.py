"""Known-bad: unregistered help key, undeclared counter, unclosed span."""
from ompi_tpu.base.output import register_help, show_help
from ompi_tpu.runtime import spc, trace

register_help("help-fix", "known-key", "A registered template {x}.")


def diagnose():
    show_help("help-fix", "typo-key", x=1)    # BAD: key never registered


def count():
    spc.record("fast_framez")                 # BAD: not in _COUNTERS


def timed(comm, buf):
    t0 = trace.now()                          # BAD: never reaches a span
    comm.allreduce(buf)
    return buf


def publish(telemetry):
    telemetry.register_source("mystery", dict)  # BAD: not a SCHEMA key


def crash(flight):
    flight.dump("mystery-reason")             # BAD: no help-flight key


def clocked(profile):
    t0 = profile.now()
    profile.stage_span("mystery_stage", t0)   # BAD: not in STAGES


def linked():
    trace.flow_start("mystery_flow", "1.2.3.4")  # BAD: no such category


def qcount():
    spc.record("quant_encodez")               # BAD: not in _COUNTERS


def qclocked(profile):
    profile.stage_mark("quant.encooode")      # BAD: not in STAGES


def rcount():
    spc.record("req_tracez")                  # BAD: not in _COUNTERS
    spc.record("slo_breachez")                # BAD: not in _COUNTERS


def rpublish(telemetry):
    telemetry.register_source("slo_extra", dict)  # BAD: not a SCHEMA key


def rlinked():
    trace.flow_start("serve_reqz", "9.1")     # BAD: no such category


def mcount():
    spc.record("moe_dispatch_tokenz")         # BAD: not in _COUNTERS


def mpublish(telemetry):
    telemetry.register_source("moe_extra", dict)  # BAD: not a SCHEMA key


def fcount():
    spc.record("serve_shedz")                 # BAD: not in _COUNTERS


def fpublish(telemetry):
    telemetry.register_source("frontdoorz", dict)  # BAD: not a SCHEMA key
