"""Known-good twin: registered keys, declared counters, closed spans."""
from ompi_tpu.base.output import register_help as _rh
from ompi_tpu.base.output import show_help
from ompi_tpu.runtime import spc, trace

_rh("help-fix", "good-key", "A registered template {x}.")


def diagnose():
    show_help("help-fix", "good-key", x=1)    # registered (via alias)


def count():
    spc.record("fast_frames")                 # declared in _COUNTERS
    spc.record("quant_encodes")               # declared in _COUNTERS
    spc.record("req_traced")                  # declared in _COUNTERS
    spc.record("slo_breaches")                # declared in _COUNTERS
    spc.record("moe_dispatch_tokens")         # declared in _COUNTERS
    spc.record("serve_shed")                  # declared in _COUNTERS
    spc.record("serve_spec_accepts")          # declared in _COUNTERS
    spc.record(_dynamic_name())               # non-literal: out of scope


def _dynamic_name():
    return "send"


def timed(comm, buf):
    t0 = trace.now()
    try:
        comm.allreduce(buf)
    finally:
        trace.span("allreduce", "coll", t0)   # begin consumed
    return buf


def timed_deferred(req):
    t0 = trace.now()
    req.on_complete(lambda r: trace.span("send", "pml", t0))


_rh("help-flight", "good-reason", "Dump at {path}.")


def publish(telemetry):
    telemetry.register_source("tcp", dict)    # declared in SCHEMA
    telemetry.register_source("fleet", dict)  # the fleet control plane
    telemetry.register_source("slo", dict)    # the otpu-req SLO plane
    telemetry.register_source("moe", dict)    # the expert-parallel plane
    telemetry.register_source("frontdoor", dict)  # the admission plane


def crash(flight):
    flight.dump("good-reason")                # registered help-flight key


def clocked(profile):
    t0 = profile.now()
    profile.stage_span("send.pack", t0)       # declared in STAGES
    profile.stage_mark("recv.parse")          # declared in STAGES
    profile.stage_mark("quant.encode")        # declared in STAGES
    profile.stage_span(_dynamic_name(), 0)    # non-literal: out of scope


def linked():
    trace.flow_start("pml_msg", "1.2.3.4")    # declared category
    trace.flow_finish("coll_round", "7.0")    # declared category
    trace.flow_start("serve_req", "9.1")      # declared category
    trace.flow_finish("serve_req", "9.1")     # declared category
    trace.flow_start(_dynamic_name(), "x")    # non-literal: out of scope
