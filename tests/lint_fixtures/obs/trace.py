"""Fixture stand-in for runtime/trace.py: the declared flow-key
category table the flow_start/flow_finish rule checks literals
against."""

FLOW_CATEGORIES = {
    "pml_msg": "point-to-point message flow",
    "coll_round": "collective round key",
    "serve_req": "per-serving-request hop key (rid.hop)",
}
