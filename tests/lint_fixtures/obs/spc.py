"""Fixture stand-in for runtime/spc.py: the declared counter set."""

_COUNTERS = (
    "send", "recv", "fast_frames", "quant_encodes",
    "req_traced", "slo_breaches", "moe_dispatch_tokens",
    "serve_shed", "serve_spec_accepts",
)
