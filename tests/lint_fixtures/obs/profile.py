"""Fixture stand-in for runtime/profile.py: the declared stage table
the stage_span/stage_mark rule checks literal names against."""

STAGES = {
    "send.pack": "convertor pack",
    "recv.parse": "frame parse",
    "quant.encode": "block-scale encode",
}
