"""Known-good twin: replies outside the lock; Condition.wait is exempt."""
import threading
import time


def _rpc(sock, payload):
    sock.sendall(payload)


class Server:
    _guarded_by = {"_kv": "_cond"}

    def __init__(self):
        self._cond = threading.Condition()
        self._kv = {}

    def serve(self, sock, key, value):
        with self._cond:
            self._kv[key] = value
        _rpc(sock, b"ok")               # after release: fine

    def get(self, key, deadline):
        with self._cond:
            while key not in self._kv:
                self._cond.wait(1.0)    # wait releases the lock: exempt
            return self._kv[key]

    def nap(self):
        time.sleep(0.01)                # no lock held: fine
