"""Known-bad: blocking calls while a declared guard lock is held."""
import threading
import time


def _rpc(sock, payload):
    sock.sendall(payload)               # marks _rpc itself as blocking


class Server:
    _guarded_by = {"_kv": "_cond"}

    def __init__(self):
        self._cond = threading.Condition()
        self._kv = {}

    def serve(self, sock, key, value):
        with self._cond:
            self._kv[key] = value
            _rpc(sock, b"ok")           # BAD: blocking helper under _cond

    def backoff(self, key):
        with self._cond:
            time.sleep(0.5)             # BAD: sleep under _cond
            return self._kv.get(key)
