"""mpi-typestate bad fixture: one violation per automaton family."""
import threading

from somepkg import Win, instance


def leak_started(comm, buf):
    # persistent request started, never waited/tested/freed, no escape
    req = comm.send_init(buf, dest=1, tag=7)
    req.start()


def double_free(comm, buf):
    req = comm.recv_init(buf, source=0, tag=7)
    req.start()
    req.wait()
    req.free()
    req.free()


def use_after_free(comm, buf):
    req = comm.send_init(buf, dest=1, tag=7)
    req.free()
    req.start()


def double_start(comm, buf):
    req = comm.send_init(buf, dest=1, tag=7)
    req.start()
    req.start()
    req.wait()
    req.free()


def pready_on_recv(comm, buf):
    req = comm.precv_init(buf, 4, source=0, tag=7)
    req.start()
    req.pready(0)
    req.wait()
    req.free()


def pready_before_start(comm, buf):
    req = comm.psend_init(buf, 4, dest=1, tag=7)
    req.pready(0)
    req.start()
    req.wait()
    req.free()


def parrived_on_send(comm, buf):
    req = comm.psend_init(buf, 4, dest=1, tag=7)
    req.start()
    req.pready_range(0, 3)
    if req.parrived(0):
        pass
    req.wait()
    req.free()


def dropped_isend(comm, buf):
    # nonblocking request ignored: completion and errors vanish
    req = comm.isend(buf, dest=1, tag=7)
    buf[0] = 0


def unlock_without_lock(comm, data):
    win = Win.create(comm, base=data)
    win.unlock(1)


def epoch_left_open(comm, data):
    win = Win.create(comm, base=data)
    win.lock(1)
    win.put(data, 1)


def flush_outside_epoch(comm, data):
    win = Win.create(comm, base=data)
    win.put(data, 1)
    win.flush(1)


def pscw_unclosed(comm, data, group):
    win = Win.create(comm, base=data)
    win.start(group)
    win.put(data, 1)


def acquire_without_release(argv):
    inst = instance.acquire(argv)
    return 1


class Pool:
    _guarded_by = {"_free": "_lock", "_out": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._free = []
        self._out = {}

    def handoff_window(self, key):
        # popped under the lock, re-registered in a LATER critical
        # section: the object is observable as neither free nor
        # checked out in between (the staging checkout-outside-lock
        # family)
        with self._lock:
            raw = self._free.pop()
        with self._lock:
            self._out[key] = raw
        return raw
