"""mpi-typestate good twin: the same lifecycles, protocols honored."""
import threading

from somepkg import Win, instance


def persistent_round_trips(comm, buf):
    req = comm.send_init(buf, dest=1, tag=7)
    for _ in range(4):
        req.start()
        req.wait()
    req.free()


def partitioned_send(comm, buf):
    req = comm.psend_init(buf, 4, dest=1, tag=7)
    req.start()
    req.pready(0)
    req.pready_range(1, 3)
    req.wait()
    req.free()


def partitioned_recv(comm, buf):
    req = comm.precv_init(buf, 4, source=0, tag=7)
    req.start()
    while not req.parrived_range(0, 3):
        pass
    req.wait()
    req.free()


def isend_waited(comm, buf):
    req = comm.isend(buf, dest=1, tag=7)
    req.wait()


def isend_escapes(comm, buf, registry):
    # handing the request out transfers the completion obligation
    req = comm.isend(buf, dest=1, tag=7)
    registry.append(req)


def startall_waitall(comm, buf, waitall, start_all):
    a = comm.send_init(buf, dest=1, tag=7)
    b = comm.recv_init(buf, source=1, tag=7)
    start_all([a, b])
    waitall([a, b])
    a.free()
    b.free()


def keyword_wait_and_escape(comm, buf, waitall, registry):
    # keyword arguments count: waitall(requests=[r]) observes
    # completion, registry.register(req=r) is an escape
    r = comm.irecv(buf, source=1, tag=7)
    waitall(requests=[r])
    s = comm.isend(buf, dest=1, tag=7)
    registry.register(req=s)


def branch_arms_are_not_sequenced(comm, buf, flag):
    req = comm.send_init(buf, dest=1, tag=7)
    req.start()
    req.wait()
    if flag:
        req.free()
    else:
        req.free()


def passive_epoch(comm, data):
    win = Win.create(comm, base=data)
    win.lock(1)
    win.put(data, 1)
    win.flush(1)
    win.unlock(1)


def lock_all_epoch(comm, data):
    win, buf = Win.allocate(comm, 16)
    win.lock_all()
    win.put(buf, 1)
    win.unlock_all()


def fence_epochs(comm, data):
    win = Win.create(comm, base=data)
    win.fence()
    win.put(data, 1)
    win.fence()


def pscw_paired(comm, data, group):
    win = Win.create(comm, base=data)
    win.start(group)
    win.put(data, 1)
    win.complete()
    win.post(group)
    win.wait()


def acquire_release_paired(argv):
    inst = instance.acquire(argv)
    try:
        return inst.pset_names()
    finally:
        instance.release()


def acquire_escapes(argv, holder):
    inst = instance.acquire(argv)
    holder.inst = inst


class Pool:
    _guarded_by = {"_free": "_lock", "_out": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._free = []
        self._out = {}

    def handoff_atomic(self, key):
        # pop and re-register inside ONE critical section: never
        # observable as neither free nor checked out
        with self._lock:
            raw = self._free.pop()
            self._out[key] = raw
        return raw
