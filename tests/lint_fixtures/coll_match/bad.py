"""collective-matching bad fixture: the classic MPI deadlock shapes."""
import numpy as np


def one_armed_bcast(comm, data):
    if comm.rank == 0:
        comm.bcast(data, root=0)
    return data


def mismatched_arms(comm, data):
    if comm.rank == 0:
        comm.allreduce(data)
    else:
        comm.barrier()


def early_return_skips(comm, data):
    rank = comm.rank
    if rank != 0:
        return None
    return comm.gather(data, root=0)


def unresolved_rank_is_conservative(rank, comm, data):
    # `rank` is a parameter the pass cannot tie to a comm: every
    # identity must match, and this one does not
    if rank == 0:
        comm.bcast(data, root=0)


def nested_early_return(comm, data, flag):
    if flag:
        if comm.rank != 0:
            return None
    out = comm.allgather(data)
    return out


def count_mismatch(comm, sizes, data):
    if comm.rank == 0:
        comm.bcast(sizes, root=0)
        comm.bcast(data, root=0)
        return data
    return comm.bcast(np.empty(1), root=0)


def mismatched_elif_ladder(comm, data):
    # the ladder is flattened: every arm must carry the same multiset
    if comm.rank == 0:
        comm.barrier()
    elif comm.rank == 1:
        comm.bcast(data, root=0)
    else:
        comm.bcast(data, root=0)
