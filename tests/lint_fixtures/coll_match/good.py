"""collective-matching good twin: legal rank-conditional shapes."""
import functools

import numpy as np


def matched_arms(comm, data):
    if comm.rank == 0:
        return comm.bcast(data, root=0)
    return comm.bcast(np.empty_like(data), root=0)


def matched_else(comm, data):
    if comm.rank == 0:
        out = comm.gather(data, root=0)
    else:
        out = comm.gather(data, root=0)
    return out


def early_return_matched(comm, sizes, data):
    # root bcasts twice then returns; the continuation bcasts twice too
    if comm.rank == 0:
        comm.bcast(sizes, root=0)
        comm.bcast(data, root=0)
        return data
    hdr = comm.bcast(np.empty(1), root=0)
    return comm.bcast(np.empty(int(hdr[0])), root=0)


def subcomm_is_membership_scoped(low, leaders, data):
    # the hierarchical shape: `leaders` only EXISTS on low.rank==0
    # ranks, so its collectives have no matching obligation
    red = low.reduce(data, root=0)
    if low.rank == 0:
        red = leaders.allreduce(red)
        return low.bcast(red, root=0)
    return low.bcast(np.empty_like(data), root=0)


def raising_arm_is_exempt(comm, data):
    if comm.rank == 0:
        raise ValueError("root cannot participate")
    return comm.barrier()


def module_style_provider(basic, comm, data):
    # provider-object collectives match on the comm ARGUMENT
    if comm.rank == 0:
        return basic.bcast(comm, data, 0)
    return basic.bcast(comm, np.empty_like(data), 0)


def numerics_are_not_collectives(rank, values):
    if rank == 0:
        return functools.reduce(lambda a, b: a + b, values)
    return np.add.reduce(values)


def rank_alias_resolves(comm, leaders, data):
    rank = comm.rank
    if rank == 0:
        leaders.barrier()
    return comm.barrier()


def symmetric_elif_ladder(comm, data):
    # a rank-role dispatch ladder where EVERY rank calls the same
    # collective exactly once is legal — arms are compared pairwise,
    # not one-vs-the-rest-of-the-chain
    rank = comm.rank
    if rank == 0:
        out = comm.bcast(data, root=0)
    elif rank == 1:
        out = comm.bcast(None, root=0)
    else:
        out = comm.bcast(None, root=0)
    return out
