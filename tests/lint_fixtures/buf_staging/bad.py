"""Known-bad: staging acquire/release pairs broken."""
from ompi_tpu.mca.accelerator import jax_acc


def leaks(n):
    tmp = jax_acc.staging_acquire(n, "uint8")
    tmp[:] = 0                          # BAD: never released/returned/stored


def early_return(comm, n):
    tmp = jax_acc.staging_acquire(n, "float32")
    if comm.size == 1:
        return None                     # BAD: skips the release below
    tmp[:] = 1
    jax_acc.staging_release(tmp)
    return True
