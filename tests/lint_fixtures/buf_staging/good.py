"""Known-good twin: try/finally pairing, ownership transfer."""
from ompi_tpu.mca.accelerator import jax_acc


def paired(comm, n):
    if comm.size == 1:
        return None                     # before the acquire: fine
    tmp = jax_acc.staging_acquire(n, "float32")
    try:
        tmp[:] = 1
        if comm.rank == 0:
            return 0                    # finally still releases
        return 1
    finally:
        jax_acc.staging_release(tmp)


def transfers(n):
    tmp = jax_acc.staging_acquire(n, "uint8")
    return tmp                          # ownership moves to the caller


class Holder:
    def adopts(self, n):
        tmp = jax_acc.staging_acquire(n, "uint8")
        self.scratch = tmp              # ownership moves onto self
