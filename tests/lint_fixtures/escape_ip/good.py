"""view-escape good twin: the same shapes, contracts honored."""


class Wire:
    def __init__(self, conv, ring):
        self.conv = conv
        self.ring = ring
        self.stash = None
        self.queue = []

    # owning copy before the return: the helper's result is owned
    def head(self, buf):
        return bytes(self.conv.pack_borrow(buf, 0, 64)[0])

    def remember(self, buf):
        data = self.head(buf)
        self.stash = data                  # owned: fine to store

    def relay(self, buf):
        data = self.head(buf)
        return data                        # owned: fine to return

    # parameter does NOT escape: consumed synchronously
    def consume(self, payload):
        return len(payload)

    def send(self, buf):
        data, _ = self.conv.pack_borrow(buf, 0, 64)
        self.consume(data)                 # callee keeps the contract

    def notify(self, req, buf):
        data, _ = self.conv.pack_borrow(buf, 0, 64)
        owned = bytes(data)
        req.on_complete(lambda r: self.queue.append(owned))

    # synchronous lambda consumers are not deferred escapes
    def pick(self, buf):
        data, _ = self.conv.pack_borrow(buf, 0, 64)
        return max(range(4), key=lambda i: data[i])


def fill_scratch(pool, n):
    buf = pool.staging_acquire(n, "u1")
    return buf


def use_scratch(pool, n):
    buf = fill_scratch(pool, n)
    try:
        buf[0] = 1
    finally:
        pool.staging_release(buf)
