"""view-escape bad fixture: every interprocedural escape family."""


class Wire:
    def __init__(self, conv, ring):
        self.conv = conv
        self.ring = ring
        self.stash = None
        self.queue = []

    # 1. helper returns a borrowed view straight from the producer —
    #    no name is ever bound, so the intraprocedural pass is blind
    def head(self, buf):
        return self.conv.pack_borrow(buf, 0, 64)

    # 2. the caller treats the helper's result as owned and stores it
    def remember(self, buf):
        data = self.head(buf)
        self.stash = data

    # 3. ... or returns it onward
    def relay(self, buf):
        data = self.head(buf)
        return data

    # 4. a parameter that escapes: stored on self
    def keep(self, payload):
        self.queue.append(payload)

    # 5. borrowed view passed to the escaping parameter
    def send(self, buf):
        data, _ = self.conv.pack_borrow(buf, 0, 64)
        self.keep(data)

    # 6. borrowed view captured by a deferred callback
    def notify(self, req, buf):
        data, _ = self.conv.pack_borrow(buf, 0, 64)
        req.on_complete(lambda r: self.queue.append(data))

    # 7. MULTI-HOP: borrowedness propagates through TWO helper layers —
    #    head2's summary depends on head's, so whichever is summarized
    #    first must be revisited when the other's summary lands (the
    #    worklist fixpoint, not a single sweep)
    def head2(self, buf):
        data = self.head(buf)
        return data

    def remember2(self, buf):
        data = self.head2(buf)
        self.stash = data


def fill_scratch(pool, n):
    buf = pool.staging_acquire(n, "u1")
    return buf


def leak_through_helper(pool, n):
    # 7. helper-acquired staging checkout never released
    buf = fill_scratch(pool, n)
    buf[0] = 1
