"""Known-bad: _guarded_by structures mutated outside their lock."""
import threading

_lock = threading.Lock()
_callbacks = []

_GUARDED_BY = {"_callbacks": "_lock"}


def register(cb):
    _callbacks.append(cb)               # BAD: module global, no lock


class Pool:
    _guarded_by = {"_free": "_lock", "_bytes": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._free = {}
        self._bytes = 0

    def put(self, key, buf):
        self._free[key] = buf           # BAD: subscript store, no lock
        self._bytes += buf.nbytes       # BAD: augassign, no lock

    def pop_alias(self, key):
        free = self._free
        return free.pop(key)            # BAD: mutation through an alias

    def drop(self, key):
        with self._lock:
            del self._free[key]         # fine
        self._free.clear()              # BAD: after the lock released
