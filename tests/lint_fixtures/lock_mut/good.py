"""Known-good twin: mutations under the declared lock; _locked helpers."""
import threading

_lock = threading.Lock()
_callbacks = []

_GUARDED_BY = {"_callbacks": "_lock"}


def register(cb):
    with _lock:
        _callbacks.append(cb)


def snapshot():
    return list(_callbacks)             # reads are lock-free by design


class Pool:
    _guarded_by = {"_free": "_lock", "_bytes": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._free = {}                 # __init__: not yet shared
        self._bytes = 0

    def put(self, key, buf):
        with self._lock:
            self._free[key] = buf
            self._bytes += buf.nbytes

    def drop(self, key):
        with self._lock:
            self._drop_locked(key)

    def _drop_locked(self, key):
        self._free.pop(key, None)       # *_locked: caller holds the lock
