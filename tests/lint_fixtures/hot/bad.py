"""Known-bad: @hot_path functions breaking the allocation budget."""
import pickle
import struct

from ompi_tpu.runtime.hotpath import hot_path


@hot_path
def send_slow(frag):
    hdr = pickle.dumps(frag.meta)       # BAD: pickle on the hot path
    label = f"frag {frag.seq}"          # BAD: f-string
    tag = "t{}".format(frag.tag)        # BAD: str.format
    note = "seq %d" % frag.seq          # BAD: %-formatting
    bufs = [hdr] + [label]              # BAD: list concatenation
    return bufs, tag, note


@hot_path
def bad_raise(buf):
    if len(buf) > 1 << 20:
        raise struct.error("too big")   # BAD: bare struct.error
    return buf
