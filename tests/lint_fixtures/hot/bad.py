"""Known-bad: @hot_path functions breaking the allocation budget."""
import pickle
import struct

from ompi_tpu.runtime.hotpath import hot_path


@hot_path
def send_slow(frag):
    hdr = pickle.dumps(frag.meta)       # BAD: pickle on the hot path
    label = f"frag {frag.seq}"          # BAD: f-string
    tag = "t{}".format(frag.tag)        # BAD: str.format
    note = "seq %d" % frag.seq          # BAD: %-formatting
    bufs = [hdr] + [label]              # BAD: list concatenation
    return bufs, tag, note


@hot_path
def bad_raise(buf):
    if len(buf) > 1 << 20:
        raise struct.error("too big")   # BAD: bare struct.error
    return buf


_REC = struct.Struct("<IiB")


@hot_path
def bad_drain(buf, n, byfd):
    # reactor-drain twin gone wrong: per-record serialization + string
    # building inside the per-tick loop
    pos = 0
    while pos < n:
        plen, fd, etype = _REC.unpack_from(buf, pos)
        pos += _REC.size
        meta = pickle.loads(buf[pos:pos + plen])    # BAD: pickle per record
        byfd[fd] = f"record {etype}: {meta}"        # BAD: f-string
        pos += plen
    return byfd
