"""Known-good twin: cold error paths may format; untagged code is free."""
import pickle
import struct

from ompi_tpu.runtime.hotpath import hot_path

_HDR = struct.Struct("!IIq")


@hot_path
def send_fast(frag):
    hdr = _HDR.pack(frag.cid, frag.src, frag.seq)   # preallocated struct
    if frag.total_len > 1 << 32:
        # error paths are cold: the f-string inside raise is fine
        raise ValueError(f"frame of {frag.total_len} bytes over the cap")
    return hdr


@hot_path
def drains(queue):
    try:
        return queue.popleft()
    except IndexError:
        # except handlers are cold too
        note = f"queue drained at {id(queue)}"
        return note


def untagged_slow(meta):
    return pickle.dumps(meta)           # not @hot_path: no budget
