"""Known-good twin: cold error paths may format; untagged code is free."""
import pickle
import struct

from ompi_tpu.runtime.hotpath import hot_path

_HDR = struct.Struct("!IIq")


@hot_path
def send_fast(frag):
    hdr = _HDR.pack(frag.cid, frag.src, frag.seq)   # preallocated struct
    if frag.total_len > 1 << 32:
        # error paths are cold: the f-string inside raise is fine
        raise ValueError(f"frame of {frag.total_len} bytes over the cap")
    return hdr


@hot_path
def drains(queue):
    try:
        return queue.popleft()
    except IndexError:
        # except handlers are cold too
        note = f"queue drained at {id(queue)}"
        return note


_REC = struct.Struct("<IiB")


@hot_path
def drain_records(buf, n, byfd):
    # reactor-drain shape: preallocated struct unpack + dict-get
    # dispatch, zero allocation sugar per record
    events = 0
    pos = 0
    while pos < n:
        plen, fd, etype = _REC.unpack_from(buf, pos)
        pos += _REC.size
        handler = byfd.get(fd)
        if handler is not None:
            events += handler(etype, buf[pos:pos + plen])
        pos += plen
    return events


def untagged_slow(meta):
    return pickle.dumps(meta)           # not @hot_path: no budget
