"""Known-bad: two classes guard the same attribute name under
different locks — the module-wide guard key is ambiguous."""
import threading


class A:
    _guarded_by = {"_table": "_lock_a"}

    def __init__(self):
        self._lock_a = threading.Lock()
        self._table = {}


class B:
    _guarded_by = {"_table": "_lock_b"}   # BAD: collides with A's key

    def __init__(self):
        self._lock_b = threading.Lock()
        self._table = {}
