"""Known-bad: borrowed views escaping the producing frame."""


class Sender:
    def stash_on_self(self, conv):
        data, borrowed = conv.pack_borrow()
        self.saved = data               # BAD: stored on self

    def queue_on_self(self, conv):
        chunk = conv.pack_borrow(4096)
        self.pending.append(chunk)      # BAD: queued on a self container

    def hand_back(self, conv):
        data, borrowed = conv.pack_borrow()
        return data                     # BAD: returned un-owned

    def stash_on_param(self, conv, conn):
        frame = ring.pop_frame()
        conn.frames.append(frame)       # BAD: queued on a parameter
