"""Known-good twin: owned copies may escape; call args may borrow."""


class Sender:
    def stash_owned(self, conv):
        data, borrowed = conv.pack_borrow()
        self.saved = bytes(data)        # owning copy: fine

    def queue_owned(self, conv):
        chunk = conv.pack_borrow(4096)
        self.pending.append(chunk.tobytes())   # owned: fine

    def hand_back_owned(self, conv):
        data, borrowed = conv.pack_borrow()
        return data.toreadonly()        # sanctioned per convention

    def pass_through(self, conv, btl, ep):
        data, borrowed = conv.pack_borrow()
        btl.send(ep, data)              # call arg: callee's contract

    def local_list(self, conv):
        data, borrowed = conv.pack_borrow()
        bufs = []
        bufs.append(data)               # local container: frame-scoped
        return len(bufs)
