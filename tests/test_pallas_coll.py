"""coll/pallas — explicit remote-DMA ring collectives, interpreter-mode
tested on the 8-virtual-CPU mesh (kernels: ompi_tpu/ops/pallas_collectives;
component: ompi_tpu/mca/coll/pallas_coll)."""
import numpy as np
import pytest

import ompi_tpu


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) != 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.array(devs), ("x",))


# -- kernel-level correctness -------------------------------------------

def test_kernel_right_permute(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    y = np.asarray(pc.right_permute(jax.device_put(x), mesh, "x"))
    np.testing.assert_array_equal(y, np.roll(x, 1, axis=0))


def test_kernel_all_gather(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(0).standard_normal((8, 6)).astype(np.float32)
    y = np.asarray(pc.all_gather(jax.device_put(x), mesh, "x"))
    np.testing.assert_allclose(y, x, rtol=1e-6)


@pytest.mark.parametrize("shape", [(8, 6), (8, 3, 5), (8, 1)])
def test_kernel_all_gather_bidi(mesh, shape):
    """Bidirectional all-gather delivers every block exactly once —
    the duplex chain arithmetic (my-k right / my+k left) must tile the
    ring with no overlap for even n."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(3).standard_normal(shape).astype(np.float32)
    y = np.asarray(pc.all_gather(jax.device_put(x), mesh, "x",
                                 variant="bidi"))
    np.testing.assert_allclose(y, x, rtol=1e-6)


def test_kernel_all_gather_bidi_odd_ring():
    """Odd ring size: r_cnt == l_cnt — every step is paired and the
    even-n right-only tail branch is dead (that branch is exercised by
    the n=8 mesh fixture above)."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    devs = jax.devices("cpu")[:5]
    if len(devs) < 5:
        pytest.skip("needs 5 virtual devices")
    m5 = Mesh(np.array(devs), ("x",))
    x = np.random.default_rng(5).standard_normal((5, 4)).astype(np.float32)
    y = np.asarray(pc.all_gather(jax.device_put(x), m5, "x",
                                 variant="bidi"))
    np.testing.assert_allclose(y, x, rtol=1e-6)


def test_component_persistent_allgather(pallas_world):
    """MPI_Allgather_init analog binds the pallas ring (and the bidi
    schedule under the duplex flag) — same results as one-shot."""
    w = pallas_world
    mod = w.c_coll["persistent_coll"].__self__
    assert mod.__class__.__name__ == "PallasCollModule"
    x = np.random.default_rng(19).standard_normal(
        (8, 16)).astype(np.float32)
    h = w.c_coll["persistent_coll"](w, "allgather", x)
    np.testing.assert_allclose(np.asarray(h(x)), x, rtol=1e-6)
    old = mod.bidirectional
    mod.bidirectional = True
    try:
        hb = w.c_coll["persistent_coll"](w, "allgather", x)
        np.testing.assert_allclose(np.asarray(hb(x)), x, rtol=1e-6)
    finally:
        mod.bidirectional = old


def test_component_allgather_bidi_routing(pallas_world):
    """--mca coll_pallas_bidirectional 1 routes allgather through the
    duplex schedule with identical results."""
    w = pallas_world
    mod = w.c_coll["allgather_array"].__self__
    assert mod.__class__.__name__ == "PallasCollModule"
    old = mod.bidirectional
    mod.bidirectional = True
    try:
        x = np.random.default_rng(7).standard_normal(
            (8, 12)).astype(np.float32)
        out = np.asarray(w.allgather_array(x))
        np.testing.assert_allclose(out, x, rtol=1e-6)
    finally:
        mod.bidirectional = old


@pytest.mark.parametrize("payload", [(24,), (23,), (5, 7)])
def test_kernel_all_reduce_sum(mesh, payload):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(1).standard_normal(
        (8, *payload)).astype(np.float32)
    y = np.asarray(pc.all_reduce_sum(jax.device_put(x), mesh, "x"))
    np.testing.assert_allclose(y, x.sum(axis=0), rtol=1e-4, atol=1e-5)


# -- component selection + dispatch -------------------------------------

@pytest.fixture()
def pallas_world():
    """Device world with coll/pallas raised above coll/xla."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.mca.coll.base import coll_framework
    from ompi_tpu.runtime import init as rt

    coll_framework().select_all()   # ensure component vars are registered
    var = registry.lookup("otpu_coll_pallas_priority")
    assert var is not None, "coll/pallas did not register its vars"
    old = var._value
    var._value = 95
    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        var._value = old
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()
    var._value = old


def test_component_owns_slots_when_raised(pallas_world):
    w = pallas_world
    for slot in ("allreduce_array", "allgather_array",
                 "reduce_scatter_array", "ppermute_array",
                 "alltoall_array", "bcast_array"):
        assert w.c_coll[slot].__self__.__class__.__name__ \
            == "PallasCollModule", slot
    # slots pallas does not implement stay with xla
    assert w.c_coll["scan_array"].__self__.__class__.__name__ \
        == "XlaCollModule"


def test_component_allreduce_and_fallthrough(pallas_world):
    from ompi_tpu.api import op

    w = pallas_world
    host = np.random.default_rng(2).standard_normal(
        (8, 12)).astype(np.float32)
    out = np.asarray(w.allreduce_array(host))
    np.testing.assert_allclose(out, host.sum(0), rtol=1e-4, atol=1e-5)
    # MAX/MIN/PROD ride the parameterized ring since round 4
    mx = np.asarray(w.allreduce_array(host, op.MAX))
    np.testing.assert_allclose(mx, host.max(0), rtol=1e-6)
    mn = np.asarray(w.allreduce_array(host, op.MIN))
    np.testing.assert_allclose(mn, host.min(0), rtol=1e-6)
    # integer payloads are not a ring shape (float-only kernels): must
    # fall through to coll/xla and still be correct
    ints = np.arange(8 * 6, dtype=np.int32).reshape(8, 6)
    s = np.asarray(w.allreduce_array(ints, op.SUM))
    np.testing.assert_array_equal(s, ints.sum(0))


def test_component_allgather_and_permute(pallas_world):
    w = pallas_world
    host = np.random.default_rng(3).standard_normal(
        (8, 5)).astype(np.float32)
    g = np.asarray(w.allgather_array(host))
    np.testing.assert_allclose(g, host, rtol=1e-6)
    rot = [(i, (i + 1) % 8) for i in range(8)]
    p = np.asarray(w.ppermute_array(host, rot))
    np.testing.assert_allclose(p, np.roll(host, 1, axis=0), rtol=1e-6)
    # a non-rotation permutation falls through to coll/xla
    swap = [(i, i ^ 1) for i in range(8)]
    s = np.asarray(w.ppermute_array(host, swap))
    np.testing.assert_allclose(
        s, host[[i ^ 1 for i in range(8)]], rtol=1e-6)


@pytest.mark.parametrize("payload", [(6,), (3, 5)])
def test_kernel_reduce_scatter_sum(mesh, payload):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(4).standard_normal(
        (8, 8, *payload)).astype(np.float32)
    y = np.asarray(pc.reduce_scatter_sum(jax.device_put(x), mesh, "x"))
    want = x.sum(axis=0)         # (8, *payload): block i to rank i
    np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-5)


# -- round-4 variants: parameterized ops, segmented, bidi, bcast --------

@pytest.mark.parametrize("op,ref", [("max", np.max), ("min", np.min),
                                    ("prod", np.prod)])
def test_kernel_all_reduce_ops(mesh, op, ref):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    rng = np.random.default_rng(6)
    # keep prod well-conditioned
    x = (1.0 + 0.05 * rng.standard_normal((8, 33))).astype(np.float32)
    y = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", op))
    np.testing.assert_allclose(y, ref(x, axis=0), rtol=1e-4)


@pytest.mark.parametrize("op,ref", [("sum", np.sum), ("max", np.max)])
def test_kernel_all_reduce_segmented(mesh, op, ref):
    """HBM-resident accumulator + bounded VMEM window: payload (1000
    elems/rank) deliberately not a multiple of the 32-elem window, so
    both the ring-block pad and the segment pad are exercised."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(7).standard_normal(
        (8, 1000)).astype(np.float32)
    y = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", op,
                                 variant="seg", seg_elems=32))
    np.testing.assert_allclose(y, ref(x, axis=0), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("op,ref", [("sum", np.sum), ("max", np.max)])
def test_kernel_all_reduce_seg_bidi(mesh, op, ref):
    """Segmented AND bidirectional: HBM-resident halves ride both ring
    directions concurrently, folds stream through one shared VMEM
    window.  Odd payload exercises both the half-split and segment
    pads."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(21).standard_normal(
        (8, 999)).astype(np.float32)
    y = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", op,
                                 variant="seg_bidi", seg_elems=32))
    np.testing.assert_allclose(y, ref(x, axis=0), rtol=1e-4, atol=1e-5)


def test_component_seg_bidi_route(pallas_world):
    """bidirectional + above the VMEM crossover routes to seg_bidi."""
    w = pallas_world
    mod = w.c_coll["allreduce_array"].__self__
    old_vmem, old_seg, old_bidi = (mod.vmem_max_bytes, mod.seg_bytes,
                                   mod.bidirectional)
    try:
        mod.vmem_max_bytes, mod.seg_bytes = 64, 128
        mod.bidirectional = True
        host = np.random.default_rng(22).standard_normal(
            (8, 300)).astype(np.float32)
        assert mod._route(np.asarray(host))[0] == "seg_bidi"
        out = np.asarray(w.allreduce_array(host))
        np.testing.assert_allclose(out, host.sum(0), rtol=1e-4,
                                   atol=1e-5)
    finally:
        mod.vmem_max_bytes, mod.seg_bytes = old_vmem, old_seg
        mod.bidirectional = old_bidi


def test_kernel_all_reduce_bidi(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(8).standard_normal(
        (8, 407)).astype(np.float32)   # odd size: exercises the even pad
    y = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", "sum",
                                 variant="bidi"))
    np.testing.assert_allclose(y, x.sum(0), rtol=1e-4, atol=1e-5)


def test_kernel_reduce_scatter_segmented(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(9).standard_normal(
        (8, 8, 50)).astype(np.float32)
    y = np.asarray(pc.reduce_scatter(jax.device_put(x), mesh, "x", "sum",
                                     variant="seg", seg_elems=16))
    np.testing.assert_allclose(y, x.sum(0), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("shape,op,ref", [
    ((4, 2), "sum", np.sum), ((2, 4), "sum", np.sum),
    ((4, 2), "max", np.max)])
def test_kernel_all_reduce_torus(mesh, shape, op, ref):
    """2D-torus composition: reduce-scatter rings along axis 0,
    all-reduce rings along axis 1 on the scattered blocks, all-gather
    back — sub-rings of a flattened mesh via index arithmetic."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    n0, n1 = shape
    mesh2d = Mesh(np.array(jax.devices()).reshape(n0, n1), ("x", "y"))
    x = np.random.default_rng(17).standard_normal(
        (n0, n1, 1000)).astype(np.float32)
    y = np.asarray(pc.all_reduce_torus(jax.device_put(x), mesh2d,
                                       ("x", "y"), op))
    np.testing.assert_allclose(y, ref(x, axis=(0, 1)), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("axes", [("x", "y"), ("y", "x")])
def test_kernel_reduce_scatter_torus(mesh, shape, axes):
    """Two-phase torus scatter-reduce (columns then rows): device
    (i0, i1) ends with global block i0*n1+i1 fully reduced; both axes
    orders must transpose onto physical sub-rings identically."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    n0, n1 = shape
    mesh2d = Mesh(np.array(jax.devices()).reshape(n0, n1), ("x", "y"))
    x = np.random.default_rng(21).standard_normal(
        (8, 8, 200)).astype(np.float32)
    y = np.asarray(pc.reduce_scatter_torus(jax.device_put(x), mesh2d,
                                           axes))
    np.testing.assert_allclose(y, x.sum(0), rtol=1e-4, atol=1e-5)
    m = np.asarray(pc.reduce_scatter_torus(jax.device_put(x), mesh2d,
                                           axes, op="max"))
    np.testing.assert_allclose(m, x.max(0), rtol=1e-6)


@pytest.mark.parametrize("axes", [("x", "y"), ("y", "x")])
def test_kernel_all_gather_torus(mesh, axes):
    """Row rings then column rings: (n1-1)+(n0-1) steps, flat-id block
    order preserved."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    mesh2d = Mesh(np.array(jax.devices()).reshape(2, 4), ("x", "y"))
    g = np.random.default_rng(23).standard_normal(
        (8, 3, 5)).astype(np.float32)
    y = np.asarray(pc.all_gather_torus(jax.device_put(g), mesh2d, axes))
    np.testing.assert_allclose(y, g, rtol=1e-6)


def test_kernel_torus_degenerate_axis(mesh):
    """A 1-wide torus axis falls back to the plain 1-D ring (an n=1
    sub-ring cannot build its recv scratch)."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    mesh1 = Mesh(np.array(jax.devices()).reshape(1, 8), ("x", "y"))
    x = np.random.default_rng(29).standard_normal(
        (8, 8, 40)).astype(np.float32)
    y = np.asarray(pc.reduce_scatter_torus(jax.device_put(x), mesh1))
    np.testing.assert_allclose(y, x.sum(0), rtol=1e-4, atol=1e-5)
    g = np.random.default_rng(31).standard_normal(
        (8, 12)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(pc.all_gather_torus(jax.device_put(g), mesh1)), g,
        rtol=1e-6)


@pytest.mark.parametrize("m", [32, 33])
def test_kernel_fused_matmul_allreduce(mesh, m):
    """The collective matmul (ops/pallas_overlap): contraction-sharded
    A_i @ B_i with just-in-time block compute overlapping each ring
    step's DMA — result must equal the unfused sum of partials."""
    import jax

    from ompi_tpu.ops import pallas_overlap as po

    rng = np.random.default_rng(18)
    n, K, N = 8, 64, 16
    a = rng.standard_normal((n, m, K // n)).astype(np.float32)
    b = rng.standard_normal((n, K // n, N)).astype(np.float32)
    y = np.asarray(po.matmul_allreduce(
        jax.device_put(a), jax.device_put(b), mesh, "x"))
    want = sum(a[i] @ b[i] for i in range(n))
    np.testing.assert_allclose(y, want, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("m", [32, 30])
def test_kernel_fused_matmul_reduce_scatter(mesh, m):
    """Row-parallel TP form: device i keeps row-block i of the reduced
    product (owner-aligned ring, no all-gather phase).  m=30 exercises
    the pad branch (callers slice the tail block)."""
    import jax

    from ompi_tpu.ops import pallas_overlap as po

    rng = np.random.default_rng(19)
    n, K, N = 8, 64, 16
    m_blk = -(-m // n)
    a = rng.standard_normal((n, m, K // n)).astype(np.float32)
    b = rng.standard_normal((n, K // n, N)).astype(np.float32)
    y = np.asarray(po.matmul_reduce_scatter(
        jax.device_put(a), jax.device_put(b), mesh, "x"))
    full = sum(a[i] @ b[i] for i in range(n))
    padded = np.zeros((n * m_blk, N), np.float32)
    padded[:m] = full
    np.testing.assert_allclose(y, padded.reshape(n, m_blk, N),
                               rtol=1e-3, atol=1e-3)


def test_kernel_fused_matmul_contraction_mismatch(mesh):
    import jax

    from ompi_tpu.ops import pallas_overlap as po

    a = np.zeros((8, 4, 8), np.float32)
    b = np.zeros((8, 7, 5), np.float32)
    with pytest.raises(ValueError, match="contraction mismatch"):
        po.matmul_allreduce(jax.device_put(a), jax.device_put(b),
                            mesh, "x")


def test_kernel_all_to_all(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(15).standard_normal(
        (8, 8, 5)).astype(np.float32)
    y = np.asarray(pc.all_to_all(jax.device_put(x), mesh, "x"))
    # x[i, j] -> out[j, i] (the coll/xla alltoall_array convention)
    np.testing.assert_allclose(y, x.swapaxes(0, 1), rtol=1e-6)


def test_component_alltoall(pallas_world):
    w = pallas_world
    host = np.random.default_rng(16).standard_normal(
        (8, 8, 3)).astype(np.float32)
    out = np.asarray(w.alltoall_array(host))
    np.testing.assert_allclose(out, host.swapaxes(0, 1), rtol=1e-6)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_kernel_bcast(mesh, root):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(10).standard_normal(
        (8, 1000)).astype(np.float32)
    y = np.asarray(pc.bcast(jax.device_put(x), mesh, "x", root=root,
                            seg_elems=64))
    np.testing.assert_allclose(
        y, np.broadcast_to(x[root], (8, 1000)), rtol=1e-6)


def test_kernel_bcast_single_segment(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.random.default_rng(11).standard_normal(
        (8, 40)).astype(np.float32)
    y = np.asarray(pc.bcast(jax.device_put(x), mesh, "x", root=1,
                            seg_elems=4096))
    np.testing.assert_allclose(
        y, np.broadcast_to(x[1], (8, 40)), rtol=1e-6)


@pytest.mark.slow
def test_kernel_segmented_large_payload(mesh):
    """The segmented kernel's reason to exist: a per-rank payload far
    beyond any VMEM budget (64MB f32) through a 512KB window."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n_el = 16 * 2**20
    x = np.random.default_rng(12).standard_normal(
        (8, n_el)).astype(np.float32)
    y = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", "sum",
                                 variant="seg", seg_elems=131072))
    np.testing.assert_allclose(y, x.sum(0), rtol=1e-3, atol=1e-3)


def test_component_bcast_and_large_route(pallas_world):
    w = pallas_world
    host = np.random.default_rng(13).standard_normal(
        (8, 300)).astype(np.float32)
    b = np.asarray(w.bcast_array(host, root=2))
    np.testing.assert_allclose(
        b, np.broadcast_to(host[2], (8, 300)), rtol=1e-6)
    assert w.c_coll["bcast_array"].__self__.__class__.__name__ \
        == "PallasCollModule"
    # shrink the vmem crossover so this payload routes to the segmented
    # kernel through the component
    mod = w.c_coll["allreduce_array"].__self__
    old_vmem, old_seg = mod.vmem_max_bytes, mod.seg_bytes
    try:
        mod.vmem_max_bytes, mod.seg_bytes = 64, 128
        out = np.asarray(w.allreduce_array(host))
        np.testing.assert_allclose(out, host.sum(0), rtol=1e-4, atol=1e-5)
    finally:
        mod.vmem_max_bytes, mod.seg_bytes = old_vmem, old_seg


def test_component_persistent_binds_pallas(pallas_world):
    """MPI_Allreduce_init analog: with coll/pallas raised, the
    persistent handle dispatches the explicit-DMA ring program, and
    unsupported shapes bind through the coll/xla fallback."""
    from ompi_tpu.api import op

    w = pallas_world
    assert w.c_coll["persistent_coll"].__self__.__class__.__name__ \
        == "PallasCollModule"
    host = np.random.default_rng(23).standard_normal(
        (8, 24)).astype(np.float32)
    h = w.allreduce_array_init(host)
    for _ in range(2):
        out = np.asarray(h(host))
        np.testing.assert_allclose(out, host.sum(0), rtol=1e-4,
                                   atol=1e-5)
    # bcast binds too (runtime-root program)
    hb = w.c_coll["persistent_coll"](w, "bcast", host, 3)
    b = np.asarray(hb(host))
    np.testing.assert_allclose(b, np.broadcast_to(host[3], host.shape),
                               rtol=1e-6)
    # an int payload is not a ring shape: binds through coll/xla
    ints = np.arange(8 * 4, dtype=np.int32).reshape(8, 4)
    hi = w.c_coll["persistent_coll"](w, "allreduce", ints, op.SUM)
    np.testing.assert_array_equal(np.asarray(hi(ints)), ints.sum(0))


def test_component_min_bytes_crossover(pallas_world):
    """Below min_bytes the call falls through to coll/xla (the ladder
    crossover knob for latency-bound small payloads).  Delegation is
    spied directly — both paths are numerically identical, so allclose
    alone cannot detect a broken gate."""
    w = pallas_world
    mod = w.c_coll["allreduce_array"].__self__
    old = mod.min_bytes
    delegated = []
    orig = mod._delegate
    mod._delegate = lambda *a, **k: (delegated.append(a[0]),
                                     orig(*a, **k))[1]
    try:
        mod.min_bytes = 1 << 20
        host = np.random.default_rng(20).standard_normal(
            (8, 16)).astype(np.float32)    # 64B/rank << 1MB -> delegate
        out = np.asarray(w.allreduce_array(host))
        np.testing.assert_allclose(out, host.sum(0), rtol=1e-5,
                                   atol=1e-6)
        assert delegated == ["allreduce_array"], delegated
        mod.min_bytes = 0
        delegated.clear()
        np.asarray(w.allreduce_array(host))
        assert delegated == [], delegated    # gate open: pallas serves
    finally:
        mod.min_bytes = old
        mod._delegate = orig


def test_component_bidirectional_route(pallas_world):
    w = pallas_world
    mod = w.c_coll["allreduce_array"].__self__
    old = mod.bidirectional
    try:
        mod.bidirectional = True
        host = np.random.default_rng(14).standard_normal(
            (8, 41)).astype(np.float32)
        out = np.asarray(w.allreduce_array(host))
        np.testing.assert_allclose(out, host.sum(0), rtol=1e-4, atol=1e-5)
    finally:
        mod.bidirectional = old


def test_component_reduce_scatter(pallas_world):
    from ompi_tpu.api import op

    w = pallas_world
    host = np.random.default_rng(5).standard_normal(
        (8, 8, 3)).astype(np.float32)
    out = np.asarray(w.reduce_scatter_array(host))
    np.testing.assert_allclose(out, host.sum(0), rtol=1e-4, atol=1e-5)
    # non-SUM falls through to coll/xla
    mx = np.asarray(w.reduce_scatter_array(host, op.MAX))
    np.testing.assert_allclose(mx, host.max(0), rtol=1e-6)
    assert w.c_coll["reduce_scatter_array"].__self__.__class__.__name__ \
        == "PallasCollModule"


def test_kernel_all_to_all_v_ragged(mesh):
    """Ragged pairwise alltoallv: rank i's block j rows [:counts[i,j]]
    land at rank j's out[i] (interpret mode moves whole blocks —
    symmetric rendezvous — so this validates addressing; the dynamic
    trip counts are AOT-compile-proven in test_pallas_aot)."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n, R, W = 8, 16, 128
    rng = np.random.default_rng(5)
    x = rng.standard_normal((n, n, R, W)).astype(np.float32)
    counts = rng.integers(0, R + 1, (n, n)).astype(np.int32)
    out = np.asarray(pc.all_to_all_v(jax.device_put(x), counts,
                                     mesh, "x"))
    for i in range(n):
        for j in range(n):
            c = counts[i, j]
            np.testing.assert_array_equal(out[j, i, :c], x[i, j, :c])


def test_all_to_all_v_wire_bytes_bound():
    """The ragged kernel's wire contract: per pair
    ceil(cnt/chunk)*chunk rows — ≤1.2x ideal for dispatch-sized counts,
    where the padded all_to_all always moves max_rows per pair."""
    n, R, chunk = 8, 512, 8
    rng = np.random.default_rng(6)
    # MoE-ish raggedness: mean ~R/2, wide spread
    counts = rng.integers(32, R + 1, (n, n))
    ideal = counts.sum()
    ragged = ((counts + chunk - 1) // chunk * chunk).sum()
    padded = n * n * R
    assert ragged <= 1.2 * ideal, (ragged, ideal)
    assert ragged < 0.8 * padded   # and far below the padded transport


def test_component_alltoallv_ragged(pallas_world):
    """coll/pallas owns alltoallv_array and honors the coll/xla
    return contract (out[i][j] = received by i from j)."""
    w = pallas_world
    n, R, W = 8, 8, 128
    rng = np.random.default_rng(7)
    host = rng.standard_normal((n, n, R, W)).astype(np.float32)
    counts = [[(2 * i + j) % (R + 1) for j in range(n)]
              for i in range(n)]
    outs = w.alltoallv_array(host, counts)
    owner = w.c_coll["alltoallv_array"].__self__.__class__.__name__
    assert owner == "PallasCollModule", owner
    for i in range(n):
        for j in range(n):
            c = counts[j][i]
            np.testing.assert_array_equal(
                np.asarray(outs[i][j]), host[j, i, :c])


def test_kernel_all_gather_v_ragged(mesh):
    """Ragged ring allgatherv: block i arrives with counts[i] valid
    rows everywhere (interpret mode moves whole blocks — symmetric
    DMA emulation; ragged trips are AOT-proven)."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n, R, W = 8, 12, 128          # R deliberately not a chunk multiple
    rng = np.random.default_rng(8)
    x = rng.standard_normal((n, R, W)).astype(np.float32)
    counts = rng.integers(0, R + 1, n).astype(np.int32)
    out = np.asarray(pc.all_gather_v(jax.device_put(x), counts,
                                     mesh, "x"))
    for i in range(n):
        np.testing.assert_array_equal(out[i, :counts[i]],
                                      x[i, :counts[i]])


def test_component_allgatherv_ragged(pallas_world):
    w = pallas_world
    n, R, W = 8, 8, 128
    rng = np.random.default_rng(9)
    host = rng.standard_normal((n, R, W)).astype(np.float32)
    counts = [(3 * i) % (R + 1) for i in range(n)]
    outs = w.allgatherv_array(host, counts)
    owner = w.c_coll["allgatherv_array"].__self__.__class__.__name__
    assert owner == "PallasCollModule", owner
    for i in range(n):
        np.testing.assert_array_equal(np.asarray(outs[i]),
                                      host[i, :counts[i]])


def test_kernel_all_reduce_wire16(mesh):
    """Wire-compressed allreduce: f32 accumulation, bf16 wire bytes.
    Error model: each partial takes one bf16 rounding per hop, so
    ABSOLUTE error is bounded by ~n * 2^-8 * max|partial| (relative
    error is unbounded where the true sum cancels to ~0 — inherent to
    any compressed reduction, and why it is opt-in)."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n = 8
    rng = np.random.default_rng(10)
    x = rng.standard_normal((n, 3000)).astype(np.float32)
    out = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x", "sum",
                                   variant="wire16"))
    want = x.sum(0)
    # partials along the ring are partial sums of ≤n normals
    bound = n * 2.0 ** -8 * np.abs(np.cumsum(
        np.sort(np.abs(x), axis=0)[::-1], axis=0)).max()
    assert np.abs(out - want).max() < max(bound, 0.25), (
        np.abs(out - want).max(), bound)
    # dtype contract: f32 out, bf16 value precision, exact padding tail
    assert out.dtype == np.float32


def test_kernel_all_reduce_wire16_rejects_non_f32(mesh):
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    x = np.ones((8, 64), np.int32)
    with pytest.raises(ValueError):
        pc.all_reduce(jax.device_put(x), mesh, "x", "sum",
                      variant="wire16")


def test_component_wire16_opt_in(pallas_world):
    """--mca coll_pallas_wire16 1 routes fused f32 SUM allreduce
    through the compressed-wire kernel; other ops keep full wire."""
    w = pallas_world
    mod = w.c_coll["allreduce_array"].__self__
    assert mod.__class__.__name__ == "PallasCollModule"
    old = mod.wire16
    mod.wire16 = True
    try:
        rng = np.random.default_rng(11)
        host = rng.standard_normal((8, 1024)).astype(np.float32)
        out = np.asarray(w.allreduce_array(host))
        want = host.sum(0)
        assert np.abs(out - want).max() < 0.25      # bf16-wire precision
        assert not np.allclose(out, want, rtol=1e-6)  # and NOT exact:
        # proves the compressed path actually ran, not full-precision
        from ompi_tpu.api import op

        exact = np.asarray(w.allreduce_array(host, op.MAX))
        np.testing.assert_array_equal(exact, host.max(0))  # MAX untouched
    finally:
        mod.wire16 = old


def test_component_wire16_persistent_matches_oneshot(pallas_world):
    """A persistent reduce_scatter handle must route through the SAME
    wire16 upgrade as the one-shot slot — identical inputs, identical
    (compressed-wire) answers (regression: the persistent branch once
    skipped the wire16 remap and silently diverged numerically)."""
    w = pallas_world
    mod = w.c_coll["reduce_scatter_array"].__self__
    assert mod.__class__.__name__ == "PallasCollModule"
    old = mod.wire16
    mod.wire16 = True
    try:
        rng = np.random.default_rng(13)
        host = rng.standard_normal((8, 8, 128)).astype(np.float32)
        from ompi_tpu.api import op

        one_shot = np.asarray(w.reduce_scatter_array(host, op.SUM))
        handle = w.c_coll["persistent_coll"](w, "reduce_scatter", host,
                                             op.SUM)
        persistent = np.asarray(handle(host))
        np.testing.assert_array_equal(persistent, one_shot)
        # and the compressed wire really ran: full-precision answer
        # (wire16 off) must differ
        mod.wire16 = False
        exact = np.asarray(w.reduce_scatter_array(host, op.SUM))
        assert not np.allclose(one_shot, exact, rtol=1e-6)
    finally:
        mod.wire16 = old


@pytest.mark.parametrize("op", ["max", "min"])
def test_kernel_all_reduce_bf16_extrema_ops(mesh, op):
    """bfloat16 MAX/MIN rings: the pad neutral must come from
    ml_dtypes' finfo — numpy reports bf16 as kind 'V' and the old
    finfo/iinfo split raised \"Invalid integer data type 'V'\"
    (regression: found by the randomized kernel sweep)."""
    import jax
    import ml_dtypes

    from ompi_tpu.ops import pallas_collectives as pc

    x = (np.random.default_rng(41).standard_normal((8, 37)) * 3
         ).astype(ml_dtypes.bfloat16)
    ref = {"max": np.max, "min": np.min}[op](x.astype(np.float32), 0)
    for variant, seg in (("fused", None), ("seg", 16), ("bidi", None),
                         ("seg_bidi", 16)):
        got = np.asarray(pc.all_reduce(jax.device_put(x), mesh, "x",
                                       op, variant=variant,
                                       seg_elems=seg))
        np.testing.assert_allclose(got.astype(np.float32), ref,
                                   atol=0.1)


def test_kernel_reduce_scatter_wire16(mesh):
    """Wire-compressed reduce-scatter: bf16 on the wire, f32 folds and
    f32 owner output (no cross-rank rounding needed: each block lives
    on exactly one rank)."""
    import jax

    from ompi_tpu.ops import pallas_collectives as pc

    n = 8
    rng = np.random.default_rng(12)
    x = rng.standard_normal((n, n, 300)).astype(np.float32)
    out = np.asarray(pc.reduce_scatter(jax.device_put(x), mesh, "x",
                                       "sum", variant="wire16"))
    want = x.sum(0)
    assert np.abs(out - want).max() < 0.25
    assert out.dtype == np.float32
