"""ft/chaos — deterministic fault injection + the self-healing
coord/wire layer it exists to exercise.

Four layers of coverage:

* spec grammar: parse/format round-trip, loud errors on malformed
  specs;
* determinism: the same (seed, rank, site) replays the identical fault
  sequence; per-hook unit semantics (loss faults only on CTL, wire
  faults only on tcp, kill-point counting);
* the self-healing coord client: an injected mid-RPC disconnect —
  including during a fence — heals via idempotent reconnect-retry
  (fetch_add applied exactly once: the acceptance-pinned regression);
* the armed wire checksum: a corrupted checksummed tcp frame is a
  loud, attributed error, never a silent delivery;
* chaos matrix (tpurun): drop/delay/dup/corrupt x 3 seeds over the
  host-collective fuzz — every job completes or fails loudly, never
  hangs; a `slow`-lane soak widens to reset/kill across 8 seeds.
"""
import os
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from ompi_tpu.ft import chaos

REPO = Path(__file__).resolve().parent.parent
HOSTCOLL = Path(__file__).resolve().parent / "fuzz_hostcoll_worker.py"


@pytest.fixture(autouse=True)
def _disarm():
    yield
    chaos.uninstall()


# ------------------------------------------------------------------ spec

def test_spec_parse_format_roundtrip():
    spec = "drop:p=0.01;delay:ms=5,p=0.05;kill:rank=2,step=7"
    rules = chaos.parse_spec(spec)
    assert [r["fault"] for r in rules] == ["drop", "delay", "kill"]
    assert rules[0]["p"] == 0.01
    assert rules[1]["ms"] == 5.0 and rules[1]["p"] == 0.05
    assert rules[2]["rank"] == 2 and rules[2]["step"] == 7
    # round trip: format -> parse is the identity on the rule list
    assert chaos.parse_spec(chaos.format_spec(rules)) == rules
    # whitespace and empty rules are tolerated
    assert chaos.parse_spec(" drop:p=0.5 ;; ") == [
        {"fault": "drop", "p": 0.5}]


def test_spec_errors_are_loud():
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("explode:p=1")          # unknown fault
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("drop:ms=2")            # param not allowed
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("delay:ms=abc")         # unparsable value
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("drop:p")               # missing '='
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("kill:rank=2")          # kill with no trigger


# ----------------------------------------------------------- determinism

def _decision_trace(seed, rank, events=200):
    eng = chaos._Engine(chaos.parse_spec(
        "drop:p=0.3;delay:p=0.2,ms=1;corrupt:p=0.1"), seed, rank)
    out = []
    for _ in range(events):
        r = eng.match(eng.wire_rules, "tcp:send")
        out.append(None if r is None else r["fault"])
    return out


def test_same_seed_identical_fault_sequence():
    a = _decision_trace(11, 0)
    b = _decision_trace(11, 0)
    assert a == b
    assert any(x is not None for x in a)         # faults actually fire
    # a different seed (or rank, or site) is a different stream
    assert a != _decision_trace(12, 0)
    assert a != _decision_trace(11, 1)


def test_n_cap_limits_rule_firings():
    eng = chaos._Engine(chaos.parse_spec("drop:p=1,n=3"), 0, 0)
    fired = [eng.match(eng.wire_rules, "s") for _ in range(10)]
    assert [r is not None for r in fired] == [True] * 3 + [False] * 7


def test_inapplicable_events_do_not_consume_caps():
    """A capped tcp-only fault offered first to sm events must still
    fire on the first tcp event — inapplicable events never burn the
    n= budget (the review-pass finding)."""
    chaos.install_spec("reset:p=1,n=1", rank=0)
    for _ in range(5):
        assert chaos.wire_send("sm", False) is None   # inapplicable
    assert chaos.wire_send("tcp", False)["fault"] == "reset"
    assert chaos.wire_send("tcp", False) is None      # cap spent NOW


# ------------------------------------------------------------- per-hook

def test_wire_hook_semantics():
    chaos.install_spec("drop:p=1", rank=0)
    # loss faults only touch best-effort CTL traffic
    assert chaos.wire_send("tcp", True)["fault"] == "drop"
    assert chaos.wire_send("tcp", False) is None
    chaos.install_spec("reset:p=1", rank=0)
    # reset is a tcp send-side fault only
    assert chaos.wire_send("tcp", False)["fault"] == "reset"
    assert chaos.wire_send("sm", False) is None
    assert chaos.wire_recv("tcp", False) is None
    chaos.install_spec("corrupt:p=1", rank=0)
    assert chaos.wire_recv("tcp", False)["fault"] == "corrupt"
    assert chaos.wire_send("sm", True) is None   # sm is host RAM


def test_kill_point_count_and_step(monkeypatch):
    killed = []
    monkeypatch.setattr(chaos, "_exit",
                        lambda code: killed.append(code))
    chaos.install_spec("kill:rank=0,site=agree_prepare,count=2", rank=0)
    chaos.kill_point("agree_prepare")
    chaos.kill_point("agree_prepare")
    assert not killed                            # 2 hits permitted
    chaos.kill_point("agree_prepare")
    assert killed == [chaos.KILL_EXIT_CODE]      # dies on the 3rd
    killed.clear()
    chaos.install_spec("kill:rank=0,step=7", rank=0)
    for s in range(7):
        chaos.kill_point("step", n=s)
    assert not killed
    chaos.kill_point("step", n=7)
    assert killed == [chaos.KILL_EXIT_CODE]
    killed.clear()
    # a rank-scoped schedule never fires on another rank
    chaos.install_spec("kill:rank=3,step=1", rank=0)
    chaos.kill_point("step", n=1)
    assert not killed


def test_rank_scoped_wire_and_pace_rules():
    """A fault carrying ``rank=`` arms only on that rank (the
    designed-straggler scoping otpu_analyze's acceptance run uses);
    a ``delay`` carrying ``site=`` moves off the wire onto the named
    chaos.pace point."""
    # rank-scoped wire rule: fires on its rank only
    chaos.install_spec("delay:ms=1,p=1,rank=2", rank=2)
    assert chaos.wire_send("tcp", False)["fault"] == "delay"
    chaos.install_spec("delay:ms=1,p=1,rank=2", rank=0)
    assert chaos.wire_send("tcp", False) is None
    # site-scoped delay: never on the wire, fires at its pace point
    chaos.install_spec("delay:ms=1,p=1,rank=0,site=step", rank=0)
    assert chaos.wire_send("tcp", False) is None
    t0 = __import__("time").perf_counter()
    chaos.pace("step")
    assert __import__("time").perf_counter() - t0 >= 0.8e-3
    chaos.pace("other_site")                     # wrong site: no sleep
    # spec round-trips with the new params
    rules = chaos.parse_spec("delay:ms=8,p=1,rank=2,site=step")
    assert chaos.parse_spec(chaos.format_spec(rules)) == rules
    # the fault log recorded the pace injection (flight-recorder tail)
    assert any(f == "delay" and s == "pace:step"
               for _t, f, s in chaos.event_log())


def test_chaos_off_hooks_are_inert():
    assert chaos.enabled is False
    assert chaos.wire_send("tcp", True) is None
    assert chaos.wire_recv("sm", True) is None
    assert chaos.coord_stall("put") is None
    assert chaos.coord_disconnect("put") is False
    chaos.kill_point("step", n=0)                # no engine: no-op


# ------------------------------------------- self-healing coord client

def _server(n=2):
    from ompi_tpu.rte.coord import CoordServer

    srv = CoordServer(n)
    os.environ["OTPU_COORD"] = f"{srv.addr[0]}:{srv.addr[1]}"
    return srv


def test_coord_fetch_add_exactly_once_across_disconnect():
    """THE idempotent-retry pin: a mid-RPC disconnect (reply lost after
    the server applied the op) must not double-apply on retry —
    fetch_add is the op where a replay would be visible."""
    from ompi_tpu.rte.coord import CoordClient

    srv = _server()
    try:
        c = CoordClient(retries=8)
        chaos.install_spec("disconnect:n=2", rank=0)
        assert c.fetch_add(-1, "ctr", 1) == 0    # injected reset, healed
        assert c.fetch_add(-1, "ctr", 1) == 1    # applied exactly once
        chaos.uninstall()
        assert c.fetch_add(-1, "ctr", 1) == 2
        c.close()
    finally:
        srv.close()


def test_coord_fence_survives_mid_rpc_disconnect():
    """Acceptance pin: a fence interrupted by a client-side reset
    completes via idempotent retry against the reconnected socket —
    the retried arrival is absorbed (set-idempotent) or replayed from
    the server's cache, never double-counted or lost."""
    from ompi_tpu.rte.coord import CoordClient

    srv = _server(2)
    try:
        a = CoordClient(retries=8)
        b = CoordClient(retries=8)
        chaos.install_spec("disconnect:n=1", rank=0)
        done = []
        t1 = threading.Thread(
            target=lambda: (a.fence("F", rank=0), done.append(0)))
        t2 = threading.Thread(
            target=lambda: (b.fence("F", rank=1), done.append(1)))
        t1.start()
        t2.start()
        t1.join(30)
        t2.join(30)
        assert sorted(done) == [0, 1], done
        # the healed client keeps working on the reconnected socket
        a.put(0, "k", "v")
        assert b.get(0, "k") == "v"
        a.close()
        b.close()
    finally:
        srv.close()


def test_coord_rpc_timeout_is_loud_and_client_stays_usable():
    """An RPC that expires (stuck fence) fails with the loud
    otpu_coord_rpc_timeout error AND closes the socket — the next RPC
    on the same client reconnects instead of queueing behind the stuck
    op or mis-reading its stale reply (the review-pass finding)."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.rte.coord import CoordClient

    srv = _server(2)
    var = registry.lookup("otpu_coord_rpc_timeout")
    old = var.value
    var.set(1.0)
    try:
        c = CoordClient(retries=2)
        with pytest.raises(RuntimeError, match="timed out"):
            # expects rank 1 too: blocks server-side past the timeout
            c.fence("stuck", rank=0, expect=[0, 1])
        # the client healed: fresh socket, ordinary RPCs work
        c.put(0, "k", "v")
        assert c.get(0, "k") == "v"
        c.close()
    finally:
        var.set(old)
        srv.close()


def test_coord_timeout_on_non_fence_op_retries_exactly_once():
    """The fleet-soak shrink-path flake: a recovery-path coord RPC
    (pset/KV traffic, NOT a fence) that expires because the coord was
    too loaded to answer in time must retry within otpu_coord_retry_max
    instead of surfacing as a survivor exception — and the replay cache
    must keep the retried op exactly-once.  Induced via the chaos coord
    hooks: the server consults the same ``stall`` rules, so firing 2
    stalls the server past a shrunken otpu_coord_rpc_timeout while the
    op is in flight (firing 1 is consumed by the harmless client-side
    pre-send hook)."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.rte.coord import CoordClient
    from ompi_tpu.runtime import spc

    srv = _server()
    var = registry.lookup("otpu_coord_rpc_timeout")
    old = var.value
    var.set(0.5)
    try:
        spc.init()
        before = spc.read("coord_rpc_retries")
        c = CoordClient(retries=4)
        chaos.install_spec("stall:p=1,ms=900,n=2", rank=0)
        # firing 1: client pre-send (a delay, harmless); firing 2: the
        # SERVER stalls past the timeout -> client times out mid-RPC,
        # retries, and the retry is adopted against the in-flight
        # original (exactly-once: the counter advances by 1 total)
        assert c.fetch_add(-1, "ctr", 1) == 0
        assert spc.read("coord_rpc_retries") >= before + 1
        chaos.uninstall()
        assert c.fetch_add(-1, "ctr", 1) == 1    # applied exactly once
        c.close()
    finally:
        var.set(old)
        srv.close()


def test_coord_malformed_request_is_loud_not_stuck():
    """A request whose server-side handling raises (malformed /
    version-skewed frame) must come back as a loud error response, not
    strand its in-flight claim for a retry to spin on forever (the
    review-pass finding)."""
    from ompi_tpu.rte.coord import CoordClient

    srv = _server()
    try:
        c = CoordClient(retries=2)
        with pytest.raises(RuntimeError, match="server error"):
            c._rpc(op="get")          # missing rank/key -> KeyError
        # the claim was released and the client keeps working
        c.put(0, "k", "v")
        assert c.get(0, "k") == "v"
        c.close()
    finally:
        srv.close()


def test_coord_stall_injection_counts():
    from ompi_tpu.rte.coord import CoordClient
    from ompi_tpu.runtime import spc

    srv = _server()
    try:
        spc.init()
        before = spc.read("chaos_stall")
        c = CoordClient(retries=2)
        chaos.install_spec("stall:p=1,ms=1,n=3", rank=0)
        for _ in range(5):
            c.put(0, "k", 1)
        assert spc.read("chaos_stall") == before + 3
        c.close()
    finally:
        srv.close()


# --------------------------------------------------- wire checksum (tcp)

def _mk_conn():
    import socket

    from ompi_tpu.mca.btl import tcp as tcp_mod

    s1, s2 = socket.socketpair()
    conn = tcp_mod._Conn(s1)
    conn.rank = 9
    return tcp_mod, conn, (s1, s2)


def _ck_frame(tcp_mod, payload: bytes) -> bytearray:
    """A checksummed fast-header frame, built the way send() builds it."""
    import struct
    import zlib

    from ompi_tpu.mca.btl.base import MATCH, Frag

    hdr = tcp_mod._fast_header(Frag(0, 9, 0, 5, 1, MATCH, payload))
    crc = zlib.crc32(payload, zlib.crc32(hdr))
    frame_len = 1 + tcp_mod._CKSUM.size + len(hdr) + len(payload)
    return bytearray(
        tcp_mod._LEN.pack(frame_len)
        + bytes((tcp_mod._H_FAST + tcp_mod._H_CK_BASE,))
        + tcp_mod._CKSUM.pack(crc) + hdr + payload)


def test_checksummed_frame_verifies_and_delivers():
    tcp_mod, conn, socks = _mk_conn()
    btl = tcp_mod.TcpBtl()
    got = []
    btl.set_recv_callback(got.append)
    try:
        frame = _ck_frame(tcp_mod, b"hello-kv")
        n = btl._on_bytes(conn, memoryview(frame))
        assert n == 1 and bytes(got[0].data) == b"hello-kv"
    finally:
        for s in socks:
            s.close()


def test_corrupted_frame_is_loud_and_attributed(capsys):
    from ompi_tpu.runtime import sanitizer, spc

    spc.init()
    before = spc.read("wire_cksum_fail")
    tcp_mod, conn, socks = _mk_conn()
    btl = tcp_mod.TcpBtl()
    btl.set_recv_callback(lambda frag: None)
    try:
        frame = _ck_frame(tcp_mod, b"hello-kv")
        frame[-1] ^= 0x40                        # wire bit rot
        with pytest.raises(sanitizer.SanitizeError) as ei:
            btl._on_bytes(conn, memoryview(frame))
        assert "rank 9" in str(ei.value)         # attributed
        assert spc.read("wire_cksum_fail") == before + 1
        err = capsys.readouterr().err
        assert "corrupted on the wire" in err    # show_help fired
    finally:
        for s in socks:
            s.close()


def test_unchecksummed_frame_still_parses():
    """Mixed arming interoperates: a plain (htype<2) frame from an
    unarmed sender parses normally on an armed receiver."""
    tcp_mod, conn, socks = _mk_conn()
    btl = tcp_mod.TcpBtl()
    got = []
    btl.set_recv_callback(got.append)
    try:
        from ompi_tpu.mca.btl.base import MATCH, Frag

        payload = b"plain"
        hdr = tcp_mod._fast_header(Frag(0, 9, 0, 5, 1, MATCH, payload))
        frame = (tcp_mod._LEN.pack(1 + len(hdr) + len(payload))
                 + bytes((tcp_mod._H_FAST,)) + hdr + payload)
        n = btl._on_bytes(conn, memoryview(bytearray(frame)))
        assert n == 1 and bytes(got[0].data) == b"plain"
    finally:
        for s in socks:
            s.close()


# ------------------------------------------------- chaos matrix (tpurun)

def _run_matrix_job(spec: str, seed: int, timeout=150):
    env = dict(os.environ, JAX_PLATFORMS="cpu", HF_SEED=str(seed),
               HF_ITERS="4")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
           "--mca", "otpu_chaos_spec", spec,
           "--mca", "otpu_chaos_seed", str(seed),
           # detector on: CTL heartbeat traffic gives the loss faults
           # something to chew on; generous envelope so injected delays
           # don't read as deaths
           "--mca", "ft_detector", "true",
           "--mca", "ft_detector_period", "0.3",
           "--mca", "ft_detector_timeout", "6.0",
           "--mca", "ft_detector_startup_grace", "6.0",
           sys.executable, str(HOSTCOLL)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


_MATRIX = ["drop:p=0.05", "delay:ms=2,p=0.2", "dup:p=0.2",
           "corrupt:p=0.02"]


@pytest.mark.parametrize("seed", [1, 2, 3])
@pytest.mark.parametrize("spec", _MATRIX)
def test_chaos_matrix_completes_or_fails_loudly(spec, seed):
    """Every (fault, seed) cell either completes the randomized
    host-collective shake or dies LOUDLY (attributed corruption error /
    injected-fault marker) — never a hang (subprocess timeout) and
    never silent corruption (the worker checks every result against
    numpy)."""
    r = _run_matrix_job(spec, seed)
    out = r.stdout + r.stderr
    if r.returncode == 0:
        assert "randomized iterations OK" in out
    else:
        assert ("corrupted on the wire" in out
                or "crc32" in out
                or "[chaos]" in out
                or "chaos" in out), (
            f"{spec} seed {seed}: failed WITHOUT a loud attributed "
            f"error\n{out[-3000:]}")


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(8)))
def test_chaos_soak(seed):
    """The full-menu soak: drop/delay/dup/corrupt/reset/kill across 8
    seeds, recovery mode on.  Zero hangs; every fault heals or fails
    loudly."""
    spec = ("drop:p=0.02;delay:ms=1,p=0.05;dup:p=0.05;"
            "corrupt:p=0.005;reset:p=0.01;kill:rank=1,after=4.0")
    env = dict(os.environ, JAX_PLATFORMS="cpu", HF_SEED=str(seed),
               HF_ITERS="12")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--enable-recovery",
           "--mca", "otpu_chaos_spec", spec,
           "--mca", "otpu_chaos_seed", str(seed),
           "--mca", "ft_detector", "true",
           "--mca", "ft_detector_period", "0.3",
           "--mca", "ft_detector_timeout", "6.0",
           "--mca", "ft_detector_startup_grace", "6.0",
           sys.executable, str(HOSTCOLL)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=240, cwd=REPO, env=env)
    out = r.stdout + r.stderr
    if r.returncode != 0:
        assert ("corrupted on the wire" in out or "crc32" in out
                or "[chaos]" in out or "chaos" in out
                or "failed" in out), (
            f"soak seed {seed}: failed silently\n{out[-3000:]}")
