"""Randomized RMA fuzz: fence-epoch schedules of put/get/accumulate/
fetch_and_op against a replicated numpy model.  The same seeded plan is
generated on every rank; each epoch assigns disjoint target slots per
origin so the model is deterministic."""
import os
import sys

import numpy as np


import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.api.win import Win

seed = int(os.environ["OF_SEED"])
epochs = int(os.environ.get("OF_EPOCHS", "12"))
ompi_tpu.init()
w = ompi_tpu.COMM_WORLD
me, n = w.rank, w.size
SLOTS = 8 * n                      # per-rank window: one region per origin
win = Win.create(w, size=SLOTS, dtype=np.float64, name="fuzzwin")
rng = np.random.default_rng(seed)  # same stream everywhere

model = np.zeros((n, SLOTS))       # model[r] = rank r's window
win.local[:] = 0.0
win.fence()

for ep in range(epochs):
    # every rank draws the SAME full plan: (origin, kind, target, slotbase)
    plan = []
    for origin in range(n):
        kind = rng.choice(["put", "acc", "fao", "get"])
        target = int(rng.integers(0, n))
        base = origin * 8           # my region on the target: disjoint
        vals = rng.standard_normal(4)
        plan.append((origin, kind, target, base, vals))
    for origin, kind, target, base, vals in plan:
        if origin != me:
            continue
        if kind == "put":
            win.put(vals.copy(), target, offset=base)
        elif kind == "acc":
            win.accumulate(vals.copy(), target, offset=base, op=op.SUM)
        elif kind == "fao":
            win.fetch_and_op(float(vals[0]), target, offset=base,
                             op=op.SUM)
        elif kind == "get":
            got = win.get(4, target, offset=base)
    # model update (all ranks, deterministically)
    for origin, kind, target, base, vals in plan:
        if kind == "put":
            model[target, base:base + 4] = vals
        elif kind == "acc":
            model[target, base:base + 4] += vals
        elif kind == "fao":
            model[target, base] += vals[0]
    win.fence()
    np.testing.assert_allclose(np.asarray(win.local), model[me],
                               atol=1e-9), ep
    # mapped-window puts may land as soon as issued: nobody may open
    # the next access epoch until every rank finished checking ITS
    # exposure epoch (MPI separation-of-epochs responsibility)
    w.barrier()
# passive target: lock/unlock CAS token ring
token_home = 0
win.fence()
if me == token_home:
    win.local[SLOTS - 1] = 0.0
win.fence()
for _ in range(5):
    win.lock(token_home)
    cur = float(win.get(1, token_home, offset=SLOTS - 1)[0])
    win.put(np.array([cur + 1.0]), token_home, offset=SLOTS - 1)
    win.unlock(token_home)
w.barrier()
if me == token_home:
    assert win.local[SLOTS - 1] == 5.0 * n, win.local[SLOTS - 1]
    print("osc fuzz ok", flush=True)
win.free()
ompi_tpu.finalize()
