"""Algorithm-menu cross-check: every tuned algorithm for every
collective produces the same answer as numpy, on random payloads —
the decision ladder may pick any entry, so every entry must agree."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.base.var import registry
from ompi_tpu.mca.coll import algorithms as algs

seed = int(os.environ.get("AF_SEED", "1"))
ompi_tpu.init()
w = ompi_tpu.COMM_WORLD
me, n = w.rank, w.size
rng = np.random.default_rng(seed)

MENUS = {
    "allreduce": sorted(algs.ALLREDUCE),
    "bcast": sorted(algs.BCAST),
    "reduce": sorted(algs.REDUCE),
    "allgather": sorted(algs.ALLGATHER),
    "alltoall": sorted(algs.ALLTOALL),
    "barrier": sorted(algs.BARRIER),
    "reduce_scatter": sorted(algs.REDUCE_SCATTER),
    "gather": sorted(algs.GATHER),
    "scatter": sorted(algs.SCATTER),
}

for coll, menu in MENUS.items():
    var = registry.lookup(f"otpu_coll_tuned_{coll}_algorithm")
    assert var is not None, coll
    for alg in menu:
        sz = int(rng.integers(1, 3000))
        base = rng.standard_normal((n, sz)).astype(np.float32)
        mine = base[me].copy()
        var.set(alg)
        try:
            if coll == "allreduce":
                got = np.asarray(w.allreduce(mine, op.SUM))
                ref = base.astype(np.float64).sum(0)
                assert np.allclose(got, ref, atol=1e-3), (coll, alg)
            elif coll == "bcast":
                got = np.asarray(w.bcast(mine.copy(), root=1))
                assert np.allclose(got, base[1]), (coll, alg)
            elif coll == "reduce":
                got = w.reduce(mine, op.SUM, root=2 % n)
                if me == 2 % n:
                    assert np.allclose(np.asarray(got),
                                       base.astype(np.float64).sum(0),
                                       atol=1e-3), (coll, alg)
            elif coll == "allgather":
                got = np.vstack([np.asarray(g)
                                 for g in w.allgather(mine)])
                assert np.allclose(got, base), (coll, alg)
            elif coll == "alltoall":
                blk = sz // n if sz >= n else 1
                m2 = base[me, : blk * n].reshape(n, blk)
                got = w.alltoall(m2)
                for src in range(n):
                    exp = base[src, : blk * n].reshape(n, blk)[me]
                    assert np.allclose(np.asarray(got[src]), exp), \
                        (coll, alg, src)
            elif coll == "barrier":
                w.barrier()
            elif coll == "reduce_scatter":
                cnt = [sz // n] * n
                got = w.reduce_scatter(mine[: sum(cnt)], cnt)
                off = sum(cnt[:me])
                ref = base[:, : sum(cnt)].astype(np.float64).sum(0)
                assert np.allclose(np.asarray(got),
                                   ref[off:off + cnt[me]], atol=1e-3), \
                    (coll, alg)
            elif coll == "gather":
                got = w.gather(mine, root=0)
                if me == 0:
                    assert np.allclose(np.vstack(got), base), (coll, alg)
            elif coll == "scatter":
                # root passes the (size, ...) stack; non-roots a template
                sendbuf = base if me == 1 else np.empty_like(base[me])
                got = np.asarray(w.scatter(sendbuf, root=1))
                assert np.allclose(got, base[me]), (coll, alg)
        finally:
            var.set("")
        w.barrier()
print(f"rank {me}: all algorithm menus agree", flush=True)
ompi_tpu.finalize()
