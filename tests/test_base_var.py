"""Var registry tests (config/flag subsystem, SURVEY.md §5.6)."""
import os

import pytest

from ompi_tpu.base.var import (
    Pvar,
    PvarClass,
    VarScope,
    VarSource,
    VarType,
    registry,
)


def test_register_and_default(fresh_registry):
    v = registry.register("testfw", "comp", "limit", vtype=VarType.INT, default=42)
    assert v.name == "otpu_testfw_comp_limit"
    assert v.value == 42
    assert v.source is VarSource.DEFAULT


def test_size_suffixes(fresh_registry):
    v = registry.register("testfw", "comp", "eager", vtype=VarType.SIZE, default="64k")
    assert v.value == 65536
    v.set("4m")
    assert v.value == 4 << 20
    assert v.source is VarSource.API


def test_bool_parsing(fresh_registry):
    v = registry.register("testfw", "comp", "flag", vtype=VarType.BOOL, default="no")
    assert v.value is False
    v.set("yes")
    assert v.value is True
    with pytest.raises(ValueError):
        v.set("maybe")


def test_env_beats_default(fresh_registry, monkeypatch):
    monkeypatch.setenv("OTPU_MCA_testfw_comp_envy", "7")
    v = registry.register("testfw", "comp", "envy", vtype=VarType.INT, default=1)
    assert v.value == 7
    assert v.source is VarSource.ENV
    assert "OTPU_MCA" in v.source_detail


def test_cli_beats_env(fresh_registry, monkeypatch):
    monkeypatch.setenv("OTPU_MCA_testfw_comp_clash", "7")
    rest = registry.parse_cli(["prog", "--mca", "testfw_comp_clash", "9", "arg"])
    assert rest == ["prog", "arg"]
    v = registry.register("testfw", "comp", "clash", vtype=VarType.INT, default=1)
    assert v.value == 9
    assert v.source is VarSource.CLI


def test_param_file(fresh_registry, tmp_path, monkeypatch):
    f = tmp_path / "params.conf"
    f.write_text("# comment\notpu_testfw_comp_filed = 123\n")
    monkeypatch.setenv("OTPU_PARAM_FILES", str(f))
    registry._files_loaded = False
    registry._file.clear()
    v = registry.register("testfw", "comp", "filed", vtype=VarType.INT, default=0)
    assert v.value == 123
    assert v.source is VarSource.FILE
    assert str(f) in v.source_detail


def test_enum_var(fresh_registry):
    v = registry.register(
        "testfw", "comp", "mode",
        enum_values={"eager": 0, "rndv": 1}, default="eager",
    )
    v.set("rndv")
    assert v.value == "rndv"
    v.set(0)  # by integer value
    assert v.value == "eager"
    with pytest.raises(ValueError):
        v.set("bogus")


def test_alias(fresh_registry, monkeypatch):
    monkeypatch.setenv("OTPU_MCA_oldname", "5")
    v = registry.register("testfw", "comp", "newname", vtype=VarType.INT,
                          default=1, aliases=("otpu_oldname",))
    assert v.value == 5
    assert registry.lookup("otpu_oldname") is v


def test_constant_scope_rejects_set(fresh_registry):
    v = registry.register("testfw", "comp", "const", vtype=VarType.INT,
                          default=3, scope=VarScope.CONSTANT)
    v.set(9)
    assert v.value == 3


def test_reflection(fresh_registry):
    registry.register("alpha", "x", "a", default="1")
    registry.register("alpha", "y", "b", default="2")
    registry.register("beta", "z", "c", default="3")
    assert len(registry.all_vars("alpha")) == 2
    names = [v.name for v in registry.all_vars()]
    assert names == sorted(names)


def test_pvar_counter_and_watermark(fresh_registry):
    c = registry.register_pvar("pml", "base", "bytes_sent",
                               pclass=PvarClass.COUNTER)
    c.add(10)
    c.add(5)
    assert c.read() == 15
    c.reset()
    assert c.read() == 0
    hw = registry.register_pvar("pml", "base", "max_unexpected",
                                pclass=PvarClass.HIGHWATERMARK)
    hw.set(4)
    hw.set(2)
    assert hw.read() == 4


def test_on_set_callback(fresh_registry):
    seen = []
    v = registry.register("testfw", "comp", "cb", vtype=VarType.INT, default=1,
                          on_set=seen.append)
    v.set(5)
    assert seen[-1] == 5
