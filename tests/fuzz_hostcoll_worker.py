"""Randomized host-path shake: collectives + derived datatypes + wildcard
p2p, same plan on every rank from the shared seed, checked vs numpy."""
import os
import sys

import numpy as np


import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.datatype import core

seed = int(os.environ["HF_SEED"])
iters = int(os.environ.get("HF_ITERS", "25"))
ompi_tpu.init()
w = ompi_tpu.COMM_WORLD
me, n = w.rank, w.size
rng = np.random.default_rng(seed)          # same stream on every rank

for it in range(iters):
    kind = rng.choice(["allreduce", "bcast", "gather", "alltoallv",
                       "sendrecv", "vecsend", "reduce", "allgatherv"])
    sz = int(rng.integers(1, 5000))
    root = int(rng.integers(0, n))
    base = rng.standard_normal((n, sz))    # all ranks know all inputs
    mine = base[me].astype(np.float32)
    if kind == "allreduce":
        o = rng.choice([op.SUM, op.MAX, op.MIN])
        got = np.asarray(w.allreduce(mine, o))
        ref = {op.SUM: np.sum, op.MAX: np.max, op.MIN: np.min}[o](
            base.astype(np.float32).astype(np.float64), 0)
        assert np.allclose(got, ref, atol=1e-3), (it, kind)
    elif kind == "bcast":
        buf = mine.copy()
        out = np.asarray(w.bcast(buf, root=root))
        assert np.allclose(out, base[root].astype(np.float32)), (it, kind)
    elif kind == "reduce":
        got = w.reduce(mine, op.SUM, root=root)
        if me == root:
            assert np.allclose(np.asarray(got),
                               base.astype(np.float32).sum(0),
                               atol=1e-3), (it, kind)
    elif kind == "gather":
        got = w.gather(mine, root=root)
        if me == root:
            assert np.allclose(np.vstack(got),
                               base.astype(np.float32)), (it, kind)
    elif kind == "allgatherv":
        cnt = [int(c) for c in rng.integers(0, sz + 1, n)]
        got = w.allgatherv(mine[:cnt[me]])
        for r in range(n):
            g = np.asarray(got[r]).view(np.float32)
            assert np.allclose(g, base[r, :cnt[r]].astype(np.float32)), \
                (it, kind, r)
    elif kind == "alltoallv":
        cnts = rng.integers(0, 50, (n, n))
        send = [base[me, :cnts[me][j]].astype(np.float32)
                for j in range(n)]
        got = w.alltoallv(send)
        for src in range(n):
            assert np.allclose(np.asarray(got[src]),
                               base[src, :cnts[src][me]]
                               .astype(np.float32)), (it, kind, src)
    elif kind == "sendrecv":
        # ring with wildcard receive
        dst, src = (me + 1) % n, (me - 1) % n
        out = np.zeros(sz, np.float32)
        r = w.irecv(out)
        w.send(mine, dest=dst, tag=it)
        st = r.wait()
        assert np.allclose(out, base[src].astype(np.float32)), (it, kind)
    elif kind == "vecsend":
        # strided vector datatype through the pack engine
        vec = core.vector(2, 1, 2, core.FLOAT32)
        nel = max(1, sz // 3)
        buf = base[me, : nel * 3].astype(np.float32).copy()
        dst, src = (me + 1) % n, (me - 1) % n
        out = np.zeros(nel * 3, np.float32)
        r = w.irecv((out, nel, vec))
        w.send((buf, nel, vec), dest=dst, tag=100 + it)
        r.wait()
        idx = (np.arange(nel)[:, None] * 3 + np.array([0, 2])).reshape(-1)
        assert np.allclose(out[idx],
                           base[src, : nel * 3].astype(np.float32)[idx]), \
            (it, kind)
    w.barrier()
print(f"rank {me}: {iters} randomized iterations OK", flush=True)
ompi_tpu.finalize()
