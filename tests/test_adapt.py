"""coll/adapt — event-driven segmented bcast/reduce (off by default)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_adapt_pipelined_bcast_reduce(tmp_path):
    script = tmp_path / "adapt.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        r = w.rank
        mod = w.c_coll['bcast'].__self__
        assert type(mod).__name__ == 'AdaptModule', type(mod).__name__
        # many 4k segments pipeline through the binomial tree
        data = np.arange(5000, dtype=np.float64)
        out = w.bcast(data if r == 2 else np.zeros(5000), root=2)
        assert np.array_equal(out, data)
        red = w.reduce(np.full(3000, float(r + 1)), root=1)
        if r == 1:
            assert np.allclose(red, sum(range(1, w.size + 1)))
        else:
            assert red is None
        # the nonblocking form is the native one
        req = mod.ibcast(w, data if r == 0 else np.zeros(5000), root=0)
        req.wait()
        w.barrier()
        print(f"adapt OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)],
                extra=("--mca", "coll_adapt_priority", "60",
                       "--mca", "coll_adapt_segsize", "4k"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("adapt OK") == 4


def test_adapt_disabled_by_default(tmp_path):
    script = tmp_path / "noadapt.py"
    script.write_text(textwrap.dedent("""
        import ompi_tpu
        w = ompi_tpu.init()
        assert type(w.c_coll['bcast'].__self__).__name__ != 'AdaptModule'
        print("noadapt OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("noadapt OK") == 2
