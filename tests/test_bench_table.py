"""The committed bench tables must stay trustworthy regression guards.

Round-4 verdict: the global [0.8, 1.25] band would pass a systematic
20% dispatch regression on every collective, and the sm-RGET ratio slip
(2.38 -> 2.06) sailed through unremarked.  So every committed row is
now pinned individually in ``tests/bench_pins.json`` (written from the
table being committed): refreshing the tables with a regressed build
fails the matching pin, and an intentional perf change must update the
pins in the same commit — which is exactly the review surface we want.

Tolerances: multidev ratios ±20% relative (virtual-CPU ratios carry
noise but a real regression moves them further), host latency pins ±2x
absolute (CI-host load), host bandwidth ≥0.5x pin, rget speedups ≥0.8x
pin (and the sm rows must stay >1.5x: RGET exists because it wins).
"""
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    with open(os.path.join(REPO, name)) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def pins():
    return _load(os.path.join("tests", "bench_pins.json"))


def test_committed_8dev_table_per_row_pins(pins):
    table = _load("BENCH_SWEEP_8DEV.json")
    rows = {f"{r['coll']}/{r['nbytes']}": r for r in table["results"]
            if "ratio" in r}
    assert rows, "8-device table is empty"
    checked = 0
    for key, pin in pins["multidev_ratio"].items():
        assert key in rows, f"pinned row {key} vanished from the table"
        got = rows[key]["ratio"]
        assert got >= 0.8 * pin, (
            f"{key}: ratio {got} fell >20% below its pin {pin} — "
            f"dispatch/selection regression (update bench_pins.json "
            f"only with an explanation)")
        assert got <= 1.3 * pin, (
            f"{key}: ratio {got} rose >30% above its pin {pin} — the "
            f"raw baseline diverged from the framework program shape")
        checked += 1
    assert checked >= 5, f"only {checked} pinned multidev rows"


def test_committed_host_rows_pinned(pins):
    sweep = _load("BENCH_SWEEP.json")
    rows = {f"{r.get('coll')}/{r.get('nbytes', 0)}": r
            for r in sweep["results"]}
    for key, pin in pins["host_lat_us"].items():
        r = rows.get(key)
        assert r is not None, f"pinned host row {key} vanished"
        got = r["fw_lat_us"]
        assert got <= 2.0 * pin, (
            f"{key}: {got}us vs pin {pin}us — >2x latency regression")
    for key, pin in pins["host_bw_gbs"].items():
        r = rows.get(key)
        assert r is not None, f"pinned pt2pt row {key} vanished"
        got = r["fw_bw_gbs"]
        assert got >= 0.5 * pin, (
            f"{key}: {got} GB/s vs pin {pin} — >2x bandwidth collapse")


def test_rget_speedup_pinned(pins):
    """sm-RGET must keep beating the FRAG stream decisively: the round-4
    slip (2.38 -> 2.06) stays visible, a further slide fails."""
    sweep = _load("BENCH_SWEEP.json")
    rows = {f"{r.get('coll')}/{r.get('nbytes', 0)}": r
            for r in sweep["results"]}
    for key, pin in pins["rget_speedup"].items():
        r = rows.get(key)
        assert r is not None, f"pinned rget row {key} vanished"
        got = r["ratio"]
        assert got >= 0.8 * pin, (
            f"{key}: speedup {got} fell >20% below pin {pin}")
        if "_sm/" in key:
            # fastpath (PR 4) made the FRAG stream itself faster
            # (zero-copy convertor views + schedule caches), so RGET's
            # margin legitimately narrowed; it must still WIN
            assert got > 1.3, (
                f"{key}: sm RGET speedup {got} no longer decisive — "
                f"the zero-copy path degraded")


def test_serving_rows_pinned(pins):
    """The serving benchmark rows (bench.py --serving: Poisson driver
    against the continuous-batching engine) must stay in the committed
    sweep with sane throughput/latency.  Wide tolerances — an open-loop
    queueing benchmark on a loaded CI host is noisy — but a collapse
    (4x latency, 4x throughput loss) fails."""
    sweep = _load("BENCH_SWEEP.json")
    rows = {r.get("coll"): r for r in sweep["results"]}
    for key, pin in pins["serving_tokens_per_s"].items():
        r = rows.get(key)
        assert r is not None, f"pinned serving row {key} vanished"
        assert r.get("ok", True), f"{key}: serving bench FAILED"
        got = r["tokens_per_s"]
        assert got >= 0.25 * pin, (
            f"{key}: {got} tokens/s vs pin {pin} — >4x throughput "
            "collapse in the serving engine")
    for key, pin in pins["serving_p99_ms"].items():
        r = rows[key]
        got = r["p99_ms"]
        assert got <= 4.0 * pin, (
            f"{key}: p99 {got}ms vs pin {pin}ms — >4x tail-latency "
            "regression")
        # the histogram estimator must agree with the driver's exact
        # sample to within its one-log2-bin contract
        assert r["p99_ms"] <= 2.0 * r["p99_exact_ms"] + 1.0
        assert r["p99_exact_ms"] <= 2.0 * r["p99_ms"] + 1.0


def test_serving_stage_medians_pinned(pins):
    """Every serving row must carry the otpu-req per-request stage
    decomposition (all six stages present; a vanished column means the
    --serving run stopped arming otpu_trace_requests or the analyzer
    stopped decomposing), the decomposed count must cover the row's
    requests, and the decode median — the dominant compute stage —
    must not collapse by more than the same wide open-loop band the
    p99 pins use."""
    sweep = _load("BENCH_SWEEP.json")
    rows = {r.get("coll"): r for r in sweep["results"]}
    for key, pin in pins["serving_stage_median_ms"].items():
        r = rows.get(key)
        assert r is not None, f"pinned serving row {key} vanished"
        assert r.get("ok", True), f"{key}: serving bench FAILED"
        med = r.get("stage_median_ms")
        assert med, f"{key}: stage_median_ms column vanished"
        assert set(med) >= {"queue", "dispatch", "prefill", "kv",
                            "decode", "stream"}, (
            f"{key}: incomplete stage decomposition {sorted(med)}")
        # fleet rows share one fleet-wide decomposition, so the floor
        # is per-run, not per-tenant
        assert r.get("req_decomposed", 0) >= 0.5 * r["nbytes"], (
            f"{key}: only {r.get('req_decomposed')} of {r['nbytes']} "
            "requests decomposed")
        got = med["decode"]
        assert 0.0 < got <= 4.0 * pin, (
            f"{key}: decode median {got}ms vs pin {pin}ms — >4x "
            "regression in the per-request decode stage")


def test_frontdoor_rows_pinned(pins):
    """The front-door rows (bench.py --serving: speculative-decode A/B
    and the sustained-overload contract) must stay in the committed
    sweep.  The multiplier pin is the whole point of speculation — at
    matched chips the k=4 leg must emit tokens FASTER than plain
    decode, or the draft/verify machinery is a net loss.  The overload
    row pins the SLO contract itself: interactive exact p99 held under
    `otpu_serving_slo_p99_ms` while the door sheds (with every shed
    retried), and batch degrades — never the other way around."""
    sweep = _load("BENCH_SWEEP.json")
    rows = {r.get("coll"): r for r in sweep["results"]}
    fd = pins["frontdoor"]
    mult = rows.get("serving_spec_multiplier")
    assert mult is not None, "serving_spec_multiplier row vanished"
    assert mult.get("ok", True), "spec A/B bench FAILED"
    got = mult["multiplier"]
    assert got > 1.0, (
        f"speculative decode multiplier {got} <= 1 — draft/verify is "
        "a net loss at matched chips")
    assert got >= 0.5 * fd["spec_multiplier"], (
        f"multiplier {got} fell >2x below pin {fd['spec_multiplier']}")
    k4 = rows.get("serving_spec_k4")
    assert k4 is not None and k4.get("ok", True)
    assert k4["tokens_per_s"] >= 0.25 * fd["spec_k4_tokens_per_s"], (
        f"spec k=4 {k4['tokens_per_s']} tokens/s vs pin "
        f"{fd['spec_k4_tokens_per_s']} — >4x collapse")
    inter = rows.get("serving_overload_interactive")
    batch = rows.get("serving_overload_batch")
    assert inter is not None and inter.get("ok", True), (
        "serving_overload_interactive row vanished")
    assert batch is not None and batch.get("ok", True), (
        "serving_overload_batch row vanished")
    assert inter["p99_exact_ms"] <= fd["overload_slo_p99_ms"], (
        f"interactive p99 {inter['p99_exact_ms']}ms breached the "
        f"{fd['overload_slo_p99_ms']}ms SLO under overload")
    assert inter["p99_exact_ms"] <= 4.0 * fd[
        "overload_interactive_p99_ms"], (
        f"interactive p99 {inter['p99_exact_ms']}ms vs pin "
        f"{fd['overload_interactive_p99_ms']}ms — >4x regression")
    assert batch["p99_exact_ms"] >= inter["p99_exact_ms"], (
        "overload degraded INTERACTIVE past batch — the SLO tiers "
        "inverted")
    for r in (inter, batch):
        assert r["shed"] > 0, (
            f"{r['coll']}: overload drive shed nothing — the bench "
            "is no longer above capacity")
        assert r["retried"] >= r["shed"], (
            f"{r['coll']}: {r['shed']} sheds but only {r['retried']} "
            "retries — the driver stopped honoring retry-after")


def test_recovery_rows_pinned(pins):
    """The recovery benchmark row (bench.py --recovery: elastic
    train-through-failure, detect→resume latency over 3 chaos-scheduled
    rank kills) must stay in the committed sweep with sane latency.
    Very wide tolerance — the agree/shrink phases carry scheduler
    throttles and CI-host noise — but an order-of-magnitude collapse
    (a recovery path that started blocking on a timeout) fails."""
    sweep = _load("BENCH_SWEEP.json")
    rows = {r.get("coll"): r for r in sweep["results"]}
    for key, pin in pins["recovery_p99_ms"].items():
        r = rows.get(key)
        assert r is not None, f"pinned recovery row {key} vanished"
        assert r.get("ok", True), f"{key}: recovery bench FAILED"
        assert r["nbytes"] >= 3, f"{key}: fewer than 3 recovery samples"
        got = r["p99_ms"]
        assert got <= 25.0 * pin, (
            f"{key}: p99 {got}ms vs pin {pin}ms — recovery latency "
            "collapsed by >25x (a recovery phase is blocking on a "
            "timeout instead of completing)")
        # phase accounting must cover the recovery it reports
        assert set(r.get("phase_median_ms", {})) >= {
            "revoke", "agree", "shrink", "restore"}


def test_mfu_rows_structure():
    """The MFU section (single-chip FLOPs utilization) must exist with
    all three rows once a sweep has been produced by a bench new enough
    to emit them; device-grade rows must carry a real mfu value."""
    sweep = _load("BENCH_SWEEP.json")
    mfu = sweep.get("mfu")
    if mfu is None:
        pytest.skip("committed sweep predates mfu rows")
    names = {r["metric"] for r in mfu}
    assert {"mfu_train_step", "mfu_flash_attention",
            "mfu_matmul_bf16"} <= names, names
    for r in mfu:
        assert r["tflops"] >= 0 and r["model_flops"] > 0
        if r["grade"] == "device":
            assert r["mfu"] is not None and 0 < r["mfu"] <= 1.0, r


def test_device_parent_salvages_stalled_child(tmp_path, monkeypatch):
    """The round-3/4/5 failure mode: the device child streams some rows,
    then the tunnel freezes it mid-RPC.  The parent must harvest every
    already-delivered row (including a burst sitting in one pipe chunk),
    kill the child at the deadline, and report stalled=True."""
    import subprocess
    import sys as _sys

    import bench

    fake_child = tmp_path / "fake_child.py"
    fake_child.write_text("""
import json, sys, time
print(json.dumps({"meta": {"ndev": 1, "device_kind": "fake",
                           "platform": "tpu"}}), flush=True)
# a burst of rows in ONE write: the parent must not strand buffered lines
sys.stdout.write(
    json.dumps({"row": {"coll": "allreduce", "nbytes": 16777216,
                        "fw_bw_gbs": 5.0, "raw_bw_gbs": 5.5,
                        "ratio": 0.9}}) + "\\n"
    + json.dumps({"row": {"coll": "allreduce", "nbytes": 8,
                          "fw_bw_gbs": 0.1, "raw_bw_gbs": 0.1,
                          "ratio": 1.0}}) + "\\n"
    + json.dumps({"mfu": {"metric": "mfu_matmul_bf16", "grade": "device",
                          "tflops": 100.0, "model_flops": 1,
                          "lat_us": 1.0, "mfu": 0.5}}) + "\\n")
sys.stdout.flush()
time.sleep(600)   # the stall: no 'done', no exit
""")

    real_popen = subprocess.Popen

    def fake_popen(cmd, **kw):
        return real_popen([_sys.executable, str(fake_child)], **kw)

    monkeypatch.setenv("OTPU_BENCH_DEVICE_BUDGET_S", "1")
    # generous grace: a saturated 1-core CI host may take seconds
    # just to exec the fake child — the deadline only bounds the
    # stall tail, the burst rows land well before it
    monkeypatch.setenv("OTPU_BENCH_PARENT_GRACE_S", "15")
    import subprocess as subprocess_mod

    monkeypatch.setattr(subprocess_mod, "Popen", fake_popen)
    import time as _t

    t0 = _t.monotonic()
    meta, rows, mfu, stalled, raw_only = bench.device_rows_parent(
        fast=True)
    elapsed = _t.monotonic() - t0
    assert meta.get("ndev") == 1
    assert len(rows) == 2, rows          # the whole burst survived
    assert rows[0]["nbytes"] == 16777216
    assert len(mfu) == 1 and mfu[0]["mfu"] == 0.5
    assert stalled and raw_only is None
    assert elapsed < 60, "parent failed to enforce its deadline"


def test_device_parent_handles_clean_done(tmp_path, monkeypatch):
    """A child that finishes cleanly yields stalled=False and raw_only
    pass-through."""
    import subprocess as subprocess_mod
    import sys as _sys

    import bench

    fake_child = tmp_path / "fake_child2.py"
    fake_child.write_text("""
import json
print(json.dumps({"meta": {"ndev": 8}}), flush=True)
print(json.dumps({"raw_only": {"raw_bw_gbs": 7.5, "why": "x"}}),
      flush=True)
print(json.dumps({"done": True}), flush=True)
""")
    real_popen = subprocess_mod.Popen

    def fake_popen(cmd, **kw):
        return real_popen([_sys.executable, str(fake_child)], **kw)

    monkeypatch.setattr(subprocess_mod, "Popen", fake_popen)
    monkeypatch.setenv("OTPU_BENCH_DEVICE_BUDGET_S", "30")
    meta, rows, mfu, stalled, raw_only = bench.device_rows_parent(
        fast=True)
    assert not stalled and rows == [] and raw_only["raw_bw_gbs"] == 7.5
