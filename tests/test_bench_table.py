"""The committed 8-virtual-device ratio table must stay a trustworthy
regression guard: raw baselines are pinned to the framework's exact
program shapes (bench.py DeviceBench.raw_fn), so every ratio at >=4KB
must sit inside MULTIDEV_BAND — below is a dispatch/selection
regression, above means the baselines diverged again (round 3's bcast
row 'beat' raw by 86% because the baseline gathered n blocks to
deliver one)."""
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_committed_8dev_table_in_band():
    with open(os.path.join(REPO, "BENCH_SWEEP_8DEV.json")) as f:
        table = json.load(f)
    rows = table["results"]
    assert rows, "8-device table is empty"
    lo, hi = table["band"]   # written by bench.py multidev_child
    checked = 0
    for r in rows:
        if r.get("nbytes", 0) < 4096:
            continue   # latency-noise-bound tiny payloads
        assert lo <= r["ratio"] <= hi, (
            f"{r['coll']}/{r['nbytes']}: ratio {r['ratio']} outside "
            f"[{lo}, {hi}] — dispatch regression (low) or baseline "
            f"shape divergence (high)")
        assert r.get("in_band") is True, r
        checked += 1
    assert checked >= 5, f"only {checked} band-checked rows"
