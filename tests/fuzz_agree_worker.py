"""Fault-injection fuzz worker for the coordination-free ERA agreement.

Launched under tpurun by ``tests/test_ft_fuzz.py``.  Every rank derives
the SAME scenario plan from ``FUZZ_SEED`` (random kills with precise
protocol-phase triggers, false-suspicion injection, a concurrent
two-comm round), runs the rounds, and prints one ``FUZZ <key> <rank>
<value>`` line per completed agreement — the test asserts the ERA
uniformity property (all printed values for a key are equal) and
liveness (every planned survivor printed).

The precise kill triggers ride the shared ``ft/chaos`` kill points
planted in ``agreement._p2p_send`` (this file used to hand-roll its own
``_p2p_send`` interceptor + timer rig; each victim now just arms a
chaos schedule for its round — ``kill:site=agree_prepare,count=k`` /
``kill:site=agree_decision,count=k`` plus a ``kill:after=T`` watchdog):

* ``prepare_partial k`` — die before sending prepare frame #(k+1): some
  survivors hold the prepared value, others don't; the takeover root
  must adopt-before-recompute via query replies.
* ``commit_partial k`` — die before sending decision frame #(k+1).
  k=0 is the nastiest ERA window (root decided locally, committed
  nothing — between prepare-complete and commit); k=1 leaves exactly
  one survivor holding the committed value, which the takeover root
  must adopt via a 'decision' query reply.
* ``delay`` — the watchdog alone (mid-protocol at a random moment).

The watchdog always backstops every victim (a root-specific
trigger never fires on a rank that never roots), so every planned
victim really dies and the plan's alive-set bookkeeping stays true.
Reference corners: ``coll_ftagree_earlyreturning.c:34-36`` (ERA keeps
per-instance hash tables precisely for these takeover/late-query
paths).
"""
import os
import random
import threading
import time


def build_plan(seed: int, n: int, rounds: int):
    """Identical on every rank: per round, who dies (and how), who
    falsely suspects whom, which rounds run two comms concurrently.
    Importable by the pytest side to recompute expectations."""
    N = n
    rng = random.Random(seed)
    if seed == 0:
        # deterministic worst case: ROOT dies between prepare-complete
        # and commit (commit_partial 0) while the TAKEOVER root dies
        # before finishing its own prepare round (prepare_partial 1) —
        # the cascading-takeover window ERA's early-return tables exist
        # for; round 2 then agrees among the 3 survivors
        flags = [rng.getrandbits(8) | 1 for _ in range(N)]
        return [
            dict(victims={}, suspect=None, concurrent=True, flags=flags,
                 dead_after=frozenset()),
            dict(victims={0: ("commit_partial", 0, 1.2),
                          1: ("prepare_partial", 1, 1.4)},
                 suspect=None, concurrent=False, flags=flags,
                 dead_after=frozenset({0, 1})),
            dict(victims={}, suspect=None, concurrent=False, flags=flags,
                 dead_after=frozenset({0, 1})),
        ]
    if seed == 1:
        # designed: the ROOT dies while TWO agreement instances are in
        # flight on different comms (the concurrent round) — both
        # instances must converge uniformly through the takeover
        flags = [rng.getrandbits(8) | 1 for _ in range(N)]
        return [
            dict(victims={0: ("delay", 0, 0.25)}, suspect=None,
                 concurrent=True, flags=flags,
                 dead_after=frozenset({0})),
            dict(victims={}, suspect=None, concurrent=False,
                 flags=flags, dead_after=frozenset({0})),
        ]
    plan = []
    alive = set(range(N))
    for rd in range(rounds):
        flags = [rng.getrandbits(8) | 1 for _ in range(N)]
        victims, suspect, concurrent = {}, None, False
        style = rng.random()
        if rd == 0:
            concurrent = True         # everyone alive: two comms at once
        elif style < 0.5 and len(alive) > 3:
            k = min(rng.choice([1, 1, 2]), len(alive) - 2)
            # bias toward low ranks: root/takeover-root deaths are the
            # interesting corner (cascading takeover when both die)
            cand = sorted(alive)
            weights = [3 if r == cand[0] else 2 if r == cand[1] else 1
                       for r in cand]
            chosen = []
            for _ in range(k):
                pick = rng.choices([r for r in cand if r not in chosen],
                                   [w for r, w in zip(cand, weights)
                                    if r not in chosen])[0]
                chosen.append(pick)
            for v in chosen:
                mode = rng.choice(["delay", "prepare_partial",
                                   "commit_partial", "commit_partial"])
                victims[v] = (mode, rng.choice([0, 1, 2]),
                              0.6 + rng.random() * 0.9)
            alive -= set(victims)
        elif style < 0.75 and len(alive) > 3:
            suspector, target = rng.sample(sorted(alive), 2)
            suspect = (suspector, target)
            alive -= {target}          # evicted after the round
        plan.append(dict(victims=victims, suspect=suspect,
                         concurrent=concurrent, flags=flags,
                         dead_after=frozenset(range(N)) - frozenset(alive)))
    return plan


def main():
    import ompi_tpu
    from ompi_tpu.api.errhandler import ERRORS_RETURN
    from ompi_tpu.api.errors import ProcFailedError
    from ompi_tpu.ft import propagator
    from ompi_tpu.ft import state as ft_state

    plan = build_plan(int(os.environ["FUZZ_SEED"]),
                      int(os.environ["FUZZ_N"]),
                      int(os.environ["FUZZ_ROUNDS"]))
    w = ompi_tpu.init()
    w.set_errhandler(ERRORS_RETURN)
    me = w.rank
    d1 = w.dup()
    d2 = w.dup()
    d1.set_errhandler(ERRORS_RETURN)
    d2.set_errhandler(ERRORS_RETURN)

    from ompi_tpu.ft import chaos

    def arm_victim(mode, arg, delay):
        """Per-round chaos schedule: the protocol-phase trigger plus
        the wall-clock watchdog (behavior-identical to the old
        hand-rolled _p2p_send interceptor + Timer rig)."""
        parts = [f"kill:rank={me},after={delay}"]
        if mode == "prepare_partial":
            parts.append(f"kill:rank={me},site=agree_prepare,count={arg}")
        elif mode == "commit_partial":
            parts.append(
                f"kill:rank={me},site=agree_decision,count={arg}")
        chaos.install_spec(";".join(parts), rank=me)

    def agree_value(comm, flag):
        """One agreement; a uniform ProcFailedError carries the agreed
        flag (comm_agree.c group-fault sync), so it counts as the
        value."""
        try:
            return comm.agree(flag)
        except ProcFailedError as e:
            return e.flag

    def wait_all_failed(ranks, deadline):
        for r in sorted(ranks):
            while not ft_state.is_failed(r):
                if time.monotonic() > deadline:
                    print(f"FUZZTIMEOUT {me} waiting on failure of {r}",
                          flush=True)
                    os._exit(3)
                time.sleep(0.02)

    for rd, spec in enumerate(plan):
        my_flag = spec["flags"][me]
        if me in spec["victims"]:
            mode, arg, delay = spec["victims"][me]
            arm_victim(mode, arg, delay)
        if spec["suspect"] and spec["suspect"][0] == me:
            # false suspicion: announce a LIVE peer dead on the real
            # propagation carriers (event bus + p2p flood) mid-agreement
            propagator.report_failure(
                w.rte, w.world_rank(spec["suspect"][1]),
                origin="fuzz-false-suspicion")
        if spec["concurrent"]:
            results = {}

            def run(key, comm, flag):
                results[key] = agree_value(comm, flag)

            t1 = threading.Thread(target=run, args=(f"{rd}a", d1, my_flag))
            t2 = threading.Thread(target=run,
                                  args=(f"{rd}b", d2, (my_flag ^ 0xFF) | 1))
            t1.start()
            t2.start()
            t1.join(120)
            t2.join(120)
            for key, val in sorted(results.items()):
                print(f"FUZZ {key} {me} {val}", flush=True)
        else:
            val = agree_value(w, my_flag)
            print(f"FUZZ {rd} {me} {val}", flush=True)

        if spec["suspect"] and spec["suspect"][1] == me:
            print(f"EVICTED {me} round {rd}", flush=True)
            os._exit(0)
        if me in spec["victims"]:
            time.sleep(10)   # trigger never fired: let the watchdog (or
            os._exit(7)      # this) kill the victim before round+1
        # everyone planned-dead through this round must be locally known
        # dead before the next round starts (keeps root views convergent)
        wait_all_failed(spec["dead_after"], time.monotonic() + 60)
        if rd + 1 < len(plan):
            w.ack_failed()

    print(f"FUZZDONE {me}", flush=True)
    os._exit(0)


if __name__ == "__main__":
    main()
