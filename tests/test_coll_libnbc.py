"""Nonblocking-collective schedule tests (coll/libnbc equivalent): every i*
collective SPMD over the thread-per-rank harness, overlap (request stays
incomplete until progressed), multiple collectives in flight, and
selection wiring on multi-process-shaped communicators."""
import threading

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api import op as op_mod
from ompi_tpu.api.request import waitall
from ompi_tpu.mca.coll.libnbc import LibnbcModule

from test_coll_algorithms import spmd, _rank_data, _noncommutative_op, \
    _matrix_data, _fold_in_rank_order


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


@pytest.fixture(scope="module")
def world5(world):
    sub = world.create(world.group.incl([0, 1, 2, 3, 4]))
    assert sub is not None
    return sub


nbc = LibnbcModule()


@pytest.mark.parametrize("nranks", [8, 5])
def test_ibarrier(world, world5, nranks):
    comm = world if nranks == 8 else world5
    spmd(comm, lambda c, r: nbc.ibarrier(c).wait())


@pytest.mark.parametrize("nranks,root", [(8, 0), (8, 5), (5, 2)])
def test_ibcast(world, world5, nranks, root):
    comm = world if nranks == 8 else world5
    data = np.arange(300, dtype=np.float64)

    def body(c, r):
        req = nbc.ibcast(c, data if r == root else np.zeros_like(data), root)
        req.wait()
        return req.result

    out = spmd(comm, body)
    for r in range(nranks):
        np.testing.assert_array_equal(out[r], data)


@pytest.mark.parametrize("nranks", [8, 5])
def test_iallreduce(world, world5, nranks):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, 40, seed=30)

    def body(c, r):
        req = nbc.iallreduce(c, data[r])
        req.wait()
        return req.result

    out = spmd(comm, body)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-10)


def test_iallreduce_noncommutative(world):
    op = _noncommutative_op()
    data = _matrix_data(8, 8, seed=31)
    expect = _fold_in_rank_order(data, op)

    def body(c, r):
        req = nbc.iallreduce(c, data[r], op)
        req.wait()
        return req.result

    out = spmd(world, body)
    np.testing.assert_allclose(out[0], expect, rtol=1e-10)


@pytest.mark.parametrize("root", [0, 3])
def test_ireduce(world, root):
    data = _rank_data(8, 25, seed=32)

    def body(c, r):
        req = nbc.ireduce(c, data[r], op_mod.SUM, root)
        req.wait()
        return req.result

    out = spmd(world, body)
    np.testing.assert_allclose(out[root], data.sum(0), rtol=1e-10)
    assert all(out[r] is None for r in range(8) if r != root)


def test_ireduce_noncommutative(world5):
    op = _noncommutative_op()
    data = _matrix_data(5, 4, seed=33)
    expect = _fold_in_rank_order(data, op)

    def body(c, r):
        req = nbc.ireduce(c, data[r], op, 1)
        req.wait()
        return req.result

    out = spmd(world5, body)
    np.testing.assert_allclose(out[1], expect, rtol=1e-10)


@pytest.mark.parametrize("nranks", [8, 5])
def test_iallgather(world, world5, nranks):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, 7, seed=34)

    def body(c, r):
        req = nbc.iallgather(c, data[r])
        req.wait()
        return req.result

    out = spmd(comm, body)
    for r in range(nranks):
        np.testing.assert_allclose(np.asarray(out[r]), data)


@pytest.mark.parametrize("nranks", [8, 5])
def test_ialltoall(world, world5, nranks):
    comm = world if nranks == 8 else world5
    data = np.arange(nranks * nranks * 2).reshape(nranks, nranks, 2) \
        .astype(np.int64)

    def body(c, r):
        req = nbc.ialltoall(c, data[r])
        req.wait()
        return req.result

    out = spmd(comm, body)
    expect = np.swapaxes(data, 0, 1)
    for r in range(nranks):
        np.testing.assert_array_equal(np.asarray(out[r]), expect[r])


def test_igather_iscatter(world5):
    data = _rank_data(5, 3, seed=35)

    def gather_body(c, r):
        req = nbc.igather(c, data[r], 4)
        req.wait()
        return req.result

    out = spmd(world5, gather_body)
    np.testing.assert_allclose(np.asarray(out[4]), data)

    def scatter_body(c, r):
        req = nbc.iscatter(
            c, data if r == 4 else np.zeros(3, data.dtype), 4)
        req.wait()
        return req.result

    out = spmd(world5, scatter_body)
    for r in range(5):
        np.testing.assert_allclose(out[r], data[r])


@pytest.mark.parametrize("nranks", [8, 5])
def test_ireduce_scatter(world, world5, nranks):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, nranks * 3, seed=36)

    def body(c, r):
        req = nbc.ireduce_scatter(c, data[r])
        req.wait()
        return req.result

    out = spmd(comm, body)
    total = data.sum(0)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], total[r * 3:(r + 1) * 3],
                                   rtol=1e-10)


def test_iscan_iexscan(world):
    data = _rank_data(8, 10, seed=37)

    def scan_body(c, r):
        req = nbc.iscan(c, data[r])
        req.wait()
        return req.result

    out = spmd(world, scan_body)
    expect = np.cumsum(data, 0)
    for r in range(8):
        np.testing.assert_allclose(out[r], expect[r], rtol=1e-10)

    def exscan_body(c, r):
        req = nbc.iexscan(c, data[r])
        req.wait()
        return req.result

    out = spmd(world, exscan_body)
    assert np.all(out[0] == 0)
    for r in range(1, 8):
        np.testing.assert_allclose(out[r], expect[r - 1], rtol=1e-10)


def test_overlap_multiple_in_flight(world):
    """Several nonblocking collectives outstanding at once, completed out of
    issue order — the schedules must not cross-match."""
    data1 = _rank_data(8, 16, seed=38)
    data2 = _rank_data(8, 16, seed=39)
    data3 = np.arange(64, dtype=np.float64)

    def body(c, r):
        r1 = nbc.iallreduce(c, data1[r])
        r2 = nbc.iallreduce(c, data2[r], op_mod.MAX)
        r3 = nbc.ibcast(c, data3 if r == 2 else np.zeros_like(data3), 2)
        rb = nbc.ibarrier(c)
        waitall([r3, r1, rb, r2])
        return r1.result, r2.result, r3.result

    out = spmd(world, body)
    for r in range(8):
        s, m, b = out[r]
        np.testing.assert_allclose(s, data1.sum(0), rtol=1e-10)
        np.testing.assert_allclose(m, data2.max(0))
        np.testing.assert_array_equal(b, data3)


def test_selection_provides_nonblocking_slots(world5):
    """libnbc (25) must own the i* slots on non-device comms; tuned (30)
    the blocking ones.  world5 is carved from the device world, so emulate
    the multi-process shape by querying components directly."""
    from ompi_tpu.base import mca

    fw = mca.framework("coll")
    fw.open()
    comp = fw.components["libnbc"]

    class FakeRte:
        is_device_world = False

    class FakeComm:
        rte = FakeRte()
        size = 4

    res = comp.comm_query(FakeComm())
    assert res is not None
    prio, module = res
    assert prio == 25
    assert hasattr(module, "iallreduce") and hasattr(module, "ibarrier")
    assert not hasattr(module, "allreduce")   # blocking slots left to tuned
