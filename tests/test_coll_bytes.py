"""Wire-byte probes on compiled HLO: the binomial device gather/reduce
trees must move O(n·S)-class traffic, not the n²·S / 2n·S of the
all_gather- or allreduce-then-mask constructions they replaced
(``coll_base_gather.c`` / ``coll_base_reduce.c`` binomial algorithms).

The probe reads the actual compiled program: every collective-permute's
operand bytes times its source_target_pairs count is exactly the bytes
that cross links per execution — no timing noise, valid on the virtual
CPU mesh because it's a property of the program, not the clock.
"""
import re

import numpy as np
import pytest

import ompi_tpu

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "f64": 8, "s8": 1, "u8": 1, "pred": 1}


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


@pytest.fixture(scope="module")
def xla(world):
    from ompi_tpu.mca.coll.xla import XlaCollModule

    return next(m for m in world.coll_modules
                if isinstance(m, XlaCollModule))


def _wire_bytes(hlo: str) -> int:
    """Total link-crossing bytes per execution: Σ over collective-
    permutes of operand bytes × pair count."""
    total = 0
    for line in hlo.splitlines():
        if "collective-permute" not in line or \
                "source_target_pairs" not in line:
            continue
        if "-done" in line:
            continue   # async pair: count the -start (has the shape)
        shape = re.search(r"(\w+)\[([\d,]*)\]", line)
        pairs = re.search(r"source_target_pairs=\{(.*?)\}[,)]", line)
        if not shape or not pairs:
            continue
        dt = _DTYPE_BYTES.get(shape.group(1))
        if dt is None:
            continue
        dims = shape.group(2)
        elems = int(np.prod([int(d) for d in dims.split(",")])) \
            if dims else 1
        npairs = pairs.group(1).count("{")
        total += dt * elems * npairs
    return total


def _compiled_hlo(xla_mod, before_keys, arg) -> str:
    new = [k for k in xla_mod._cache if k not in before_keys]
    assert len(new) == 1, new
    fn = xla_mod._cache[new[0]][0]
    return fn.lower(arg).compile().as_text()


def test_gather_wire_bytes_binomial(world, xla):
    host = np.random.default_rng(0).standard_normal((8, 128)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.gather_array(dev, root=3))
    np.testing.assert_allclose(out[3], host, rtol=1e-6)  # still right
    hlo = _compiled_hlo(xla, before, dev)
    S = 128 * 4
    # binomial: k=1: 4 pairs x S, k=2: 2 x 2S, k=4: 1 x 4S = 12S total;
    # all_gather+mask moved n*(n-1)*S = 56S
    assert "all-gather" not in hlo
    wire = _wire_bytes(hlo)
    assert 0 < wire <= 14 * S, f"gather moves {wire} B vs 12S={12 * S}"


def test_reduce_wire_bytes_binomial(world, xla):
    host = np.random.default_rng(1).standard_normal((8, 128)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.reduce_array(dev, root=2))
    np.testing.assert_allclose(out[2], host.sum(0), rtol=1e-5)
    hlo = _compiled_hlo(xla, before, dev)
    S = 128 * 4
    # binomial reduce: (n-1) block sends = 7S; allreduce+mask rode the
    # full ring at ~2(n-1)S per device
    assert "all-reduce" not in hlo
    wire = _wire_bytes(hlo)
    assert 0 < wire <= 8 * S, f"reduce moves {wire} B vs 7S={7 * S}"
