"""Wire-byte probes on compiled HLO: the binomial device gather/reduce
trees must move O(n·S)-class traffic, not the n²·S / 2n·S of the
all_gather- or allreduce-then-mask constructions they replaced
(``coll_base_gather.c`` / ``coll_base_reduce.c`` binomial algorithms).

The probe reads the actual compiled program: every collective-permute's
operand bytes times its source_target_pairs count is exactly the bytes
that cross links per execution — no timing noise, valid on the virtual
CPU mesh because it's a property of the program, not the clock.
"""
import re

import numpy as np
import pytest

import ompi_tpu

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "f64": 8, "s8": 1, "u8": 1, "pred": 1}


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


@pytest.fixture(scope="module")
def xla(world):
    from ompi_tpu.mca.coll.xla import XlaCollModule

    return next(m for m in world.coll_modules
                if isinstance(m, XlaCollModule))


def _wire_bytes(hlo: str) -> int:
    """Total link-crossing bytes per execution: Σ over collective-
    permutes of operand bytes × pair count."""
    total = 0
    for line in hlo.splitlines():
        if "collective-permute" not in line or \
                "source_target_pairs" not in line:
            continue
        if "-done" in line:
            continue   # async pair: count the -start (has the shape)
        shape = re.search(r"(\w+)\[([\d,]*)\]", line)
        pairs = re.search(r"source_target_pairs=\{(.*?)\}[,)]", line)
        if not shape or not pairs:
            continue
        dt = _DTYPE_BYTES.get(shape.group(1))
        if dt is None:
            continue
        dims = shape.group(2)
        elems = int(np.prod([int(d) for d in dims.split(",")])) \
            if dims else 1
        npairs = pairs.group(1).count("{")
        total += dt * elems * npairs
    return total


def _compiled_hlo(xla_mod, before_keys, arg) -> str:
    new = [k for k in xla_mod._cache if k not in before_keys]
    assert len(new) == 1, new
    fn = xla_mod._cache[new[0]][0]
    return fn.lower(arg).compile().as_text()


def test_gather_wire_bytes_binomial(world, xla):
    host = np.random.default_rng(0).standard_normal((8, 128)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.gather_array(dev, root=3))
    np.testing.assert_allclose(out[3], host, rtol=1e-6)  # still right
    hlo = _compiled_hlo(xla, before, dev)
    S = 128 * 4
    # binomial: k=1: 4 pairs x S, k=2: 2 x 2S, k=4: 1 x 4S = 12S total;
    # all_gather+mask moved n*(n-1)*S = 56S
    assert "all-gather" not in hlo
    wire = _wire_bytes(hlo)
    assert 0 < wire <= 14 * S, f"gather moves {wire} B vs 12S={12 * S}"


def test_reduce_wire_bytes_binomial(world, xla):
    host = np.random.default_rng(1).standard_normal((8, 128)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.reduce_array(dev, root=2))
    np.testing.assert_allclose(out[2], host.sum(0), rtol=1e-5)
    hlo = _compiled_hlo(xla, before, dev)
    S = 128 * 4
    # binomial reduce: (n-1) block sends = 7S; allreduce+mask rode the
    # full ring at ~2(n-1)S per device
    assert "all-reduce" not in hlo
    wire = _wire_bytes(hlo)
    assert 0 < wire <= 8 * S, f"reduce moves {wire} B vs 7S={7 * S}"


def test_scatter_wire_bytes_binomial(world, xla):
    host = np.random.default_rng(2).standard_normal((8, 8, 128)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.scatter_array(dev, root=4))
    np.testing.assert_allclose(out, host[4], rtol=1e-6)
    hlo = _compiled_hlo(xla, before, dev)
    S = 128 * 4
    # binomial halving: k=4: 1x4S, k=2: 2x2S, k=1: 4x1S = 12S; the
    # all_to_all construction moved every rank's dead freight (56S)
    assert "all-to-all" not in hlo
    wire = _wire_bytes(hlo)
    assert 0 < wire <= 14 * S, f"scatter moves {wire} B vs 12S={12 * S}"


def test_bcast_large_scatter_allgather(world, xla):
    """Above bcast_sa_min_bytes the program must be the two ring phases
    (reduce-scatter + all-gather), not log2(n) serial full-S ppermute
    hops — and still correct from any root."""
    S = xla.bcast_sa_min_bytes // 4 + 1024   # f32 elems, above the bar
    host = np.random.default_rng(3).standard_normal((8, S)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.bcast_array(dev, root=6))
    np.testing.assert_allclose(out, np.broadcast_to(host[6], out.shape),
                               rtol=1e-6)
    hlo = _compiled_hlo(xla, before, dev)
    assert "collective-permute" not in hlo   # no tree hops
    assert "reduce-scatter" in hlo or "all-reduce-scatter" in hlo, \
        "scatter phase missing"
    assert "all-gather" in hlo, "allgather phase missing"


def test_bcast_small_stays_binomial(world, xla):
    host = np.random.default_rng(4).standard_normal((8, 64)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    before = set(xla._cache)
    out = np.asarray(world.bcast_array(dev, root=2))
    np.testing.assert_allclose(out, np.broadcast_to(host[2], out.shape),
                               rtol=1e-6)
    hlo = _compiled_hlo(xla, before, dev)
    assert "collective-permute" in hlo       # the tree
    assert "reduce-scatter" not in hlo
