"""Algorithm-library tests: every coll/base menu entry against numpy
references, run SPMD with one thread per rank over the in-process world
(the ``mpirun --oversubscribe`` harness of SURVEY §4), plus the tuned
decision ladder, force vars, and dynamic rule files."""
import threading
import traceback

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api import op as op_mod
from ompi_tpu.mca.coll import algorithms as algs


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


@pytest.fixture(scope="module")
def world5(world):
    sub = world.create(world.group.incl([0, 1, 2, 3, 4]))
    assert sub is not None
    return sub


@pytest.fixture(scope="module")
def world6(world):
    sub = world.create(world.group.incl([0, 1, 2, 3, 4, 5]))
    assert sub is not None
    return sub


def spmd(comm, fn, timeout=60):
    """Run fn(rank_facade, rank) SPMD-style, one thread per rank."""
    size = comm.size
    results = [None] * size
    errors = []

    def run(i):
        try:
            results[i] = fn(comm.as_rank(i), i)
        except Exception:
            errors.append((i, traceback.format_exc()))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    alive = [i for i, t in enumerate(threads) if t.is_alive()]
    assert not alive, f"SPMD deadlock: ranks {alive} still running"
    assert not errors, "\n".join(f"[rank {i}]\n{tb}" for i, tb in errors)
    return results


def _rank_data(size, n, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((size, n)).astype(dtype)


# ---------------------------------------------------------------------------
# allreduce


@pytest.mark.parametrize("alg", sorted(algs.ALLREDUCE))
@pytest.mark.parametrize("nelem", [1, 7, 1000])
def test_allreduce_sum(world, alg, nelem):
    data = _rank_data(8, nelem)
    out = spmd(world, lambda c, r: algs.ALLREDUCE[alg](c, data[r]))
    for r in range(8):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-10)


@pytest.mark.parametrize("alg", sorted(algs.ALLREDUCE))
def test_allreduce_odd_size(world5, alg):
    data = _rank_data(5, 64, seed=1)
    out = spmd(world5, lambda c, r: algs.ALLREDUCE[alg](c, data[r]))
    for r in range(5):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-10)


@pytest.mark.parametrize("alg", ["ring", "rabenseifner"])
def test_allreduce_max(world, alg):
    data = _rank_data(8, 33, seed=2)
    out = spmd(world, lambda c, r: algs.ALLREDUCE[alg](c, data[r], op_mod.MAX))
    np.testing.assert_allclose(out[0], data.max(0))


def _noncommutative_op():
    """2x2 matrix product over flat (4k,) buffers: associative (as MPI
    requires of user ops) but order-sensitive in every operand."""
    def fn(invec, inoutvec, datatype=None):
        a = invec.reshape(-1, 2, 2)
        b = inoutvec.reshape(-1, 2, 2)
        inoutvec[...] = np.matmul(a, b).reshape(inoutvec.shape)
    return op_mod.create(fn, commute=False)


def _matrix_data(nranks, nelem, seed=0):
    """Near-identity 2x2 matrices so long products stay well-conditioned."""
    rng = np.random.default_rng(seed)
    eye = np.tile(np.eye(2).reshape(-1), (nranks, nelem // 4))
    return eye + 0.1 * rng.standard_normal((nranks, nelem))


def _fold_in_rank_order(data, fn):
    acc = data[-1].copy()
    for i in range(data.shape[0] - 2, -1, -1):
        out = acc.copy()
        fn(data[i], out)
        acc = out
    return acc


@pytest.mark.parametrize("alg", ["nonoverlapping", "recursive_doubling",
                                 "linear"])
@pytest.mark.parametrize("nranks", [8, 5])
def test_allreduce_noncommutative_order(world, world5, alg, nranks):
    """Order-safe algorithms must fold operands in rank order."""
    comm = world if nranks == 8 else world5
    op = _noncommutative_op()
    data = _matrix_data(nranks, 8, seed=20)
    expect = _fold_in_rank_order(data, op)
    out = spmd(comm, lambda c, r: algs.ALLREDUCE[alg](c, data[r], op))
    for r in range(nranks):
        np.testing.assert_allclose(out[r], expect, rtol=1e-10)


# ---------------------------------------------------------------------------
# bcast


@pytest.mark.parametrize("alg", sorted(algs.BCAST))
@pytest.mark.parametrize("root", [0, 3])
@pytest.mark.parametrize("nelem", [5, 4096])
def test_bcast(world, alg, root, nelem):
    data = np.arange(nelem, dtype=np.float32) * 1.5
    out = spmd(world, lambda c, r: algs.BCAST[alg](
        c, data if r == root else np.zeros_like(data), root))
    for r in range(8):
        np.testing.assert_array_equal(out[r], data)


@pytest.mark.parametrize("alg", sorted(algs.BCAST))
def test_bcast_odd_size(world5, alg):
    data = np.arange(100, dtype=np.int64)
    out = spmd(world5, lambda c, r: algs.BCAST[alg](
        c, data if r == 2 else np.zeros_like(data), 2))
    for r in range(5):
        np.testing.assert_array_equal(out[r], data)


# ---------------------------------------------------------------------------
# reduce


@pytest.mark.parametrize("alg", sorted(algs.REDUCE))
@pytest.mark.parametrize("root", [0, 5])
def test_reduce(world, alg, root):
    data = _rank_data(8, 50, seed=3)
    out = spmd(world, lambda c, r: algs.REDUCE[alg](c, data[r], op_mod.SUM,
                                                    root))
    np.testing.assert_allclose(out[root], data.sum(0), rtol=1e-10)
    for r in range(8):
        if r != root:
            assert out[r] is None


@pytest.mark.parametrize("alg", ["pipeline", "linear"])
def test_reduce_noncommutative_order(world, alg):
    op = _noncommutative_op()
    data = _matrix_data(8, 4, seed=21)
    expect = _fold_in_rank_order(data, op)
    out = spmd(world, lambda c, r: algs.REDUCE[alg](c, data[r], op, 0))
    np.testing.assert_allclose(out[0], expect, rtol=1e-10)


def test_reduce_pipeline_multiseg(world5):
    """Segmented chain with several segments and a non-zero root."""
    data = _rank_data(5, 3000, seed=4)
    out = spmd(world5, lambda c, r: algs.reduce_pipeline(
        c, data[r], op_mod.SUM, root=3, segsize=4096))
    np.testing.assert_allclose(out[3], data.sum(0), rtol=1e-10)


# ---------------------------------------------------------------------------
# allgather


@pytest.mark.parametrize("alg", sorted(algs.ALLGATHER))
@pytest.mark.parametrize("nranks", [8, 5, 6])
def test_allgather(world, world5, world6, alg, nranks):
    comm = {8: world, 5: world5, 6: world6}[nranks]
    data = _rank_data(nranks, 9, seed=5)
    out = spmd(comm, lambda c, r: algs.ALLGATHER[alg](c, data[r]))
    for r in range(nranks):
        got = np.asarray(out[r]).reshape(nranks, 9)
        np.testing.assert_allclose(got, data)


# ---------------------------------------------------------------------------
# alltoall


@pytest.mark.parametrize("alg", sorted(algs.ALLTOALL))
@pytest.mark.parametrize("nranks", [8, 5])
def test_alltoall(world, world5, alg, nranks):
    comm = world if nranks == 8 else world5
    data = np.arange(nranks * nranks * 3).reshape(nranks, nranks, 3) \
        .astype(np.int64)
    out = spmd(comm, lambda c, r: algs.ALLTOALL[alg](c, data[r]))
    expect = np.swapaxes(data, 0, 1)   # out[r][s] = data[s][r]
    for r in range(nranks):
        np.testing.assert_array_equal(np.asarray(out[r]), expect[r])


# ---------------------------------------------------------------------------
# barrier


@pytest.mark.parametrize("alg", sorted(algs.BARRIER))
@pytest.mark.parametrize("nranks", [8, 5])
def test_barrier(world, world5, alg, nranks):
    comm = world if nranks == 8 else world5
    hits = []
    lock = threading.Lock()

    def body(c, r):
        algs.BARRIER[alg](c)
        with lock:
            hits.append(r)
        algs.BARRIER[alg](c)
        with lock:
            n = len(hits)
        # after the second barrier every rank must have logged the first
        assert n >= nranks
        algs.BARRIER[alg](c)

    spmd(comm, body)
    assert sorted(hits) == list(range(nranks))


# ---------------------------------------------------------------------------
# reduce_scatter


@pytest.mark.parametrize("alg", sorted(algs.REDUCE_SCATTER))
@pytest.mark.parametrize("nranks", [8, 5])
def test_reduce_scatter(world, world5, alg, nranks):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, nranks * 4, seed=6)
    out = spmd(comm, lambda c, r: algs.REDUCE_SCATTER[alg](c, data[r]))
    total = data.sum(0)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], total[r * 4:(r + 1) * 4],
                                   rtol=1e-10)


def test_reduce_scatter_uneven_counts(world):
    counts = [1, 2, 3, 4, 5, 6, 7, 8]
    n = sum(counts)
    data = _rank_data(8, n, seed=7)
    out = spmd(world, lambda c, r: algs.reduce_scatter_ring(
        c, data[r], recvcounts=counts))
    total = data.sum(0)
    off = 0
    for r in range(8):
        np.testing.assert_allclose(out[r], total[off:off + counts[r]],
                                   rtol=1e-10)
        off += counts[r]


# ---------------------------------------------------------------------------
# gather / scatter


@pytest.mark.parametrize("alg", sorted(algs.GATHER))
@pytest.mark.parametrize("nranks,root", [(8, 0), (8, 3), (5, 4)])
def test_gather(world, world5, alg, nranks, root):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, 6, seed=8)
    out = spmd(comm, lambda c, r: algs.GATHER[alg](c, data[r], root))
    got = np.asarray(out[root]).reshape(nranks, 6)
    np.testing.assert_allclose(got, data)
    for r in range(nranks):
        if r != root:
            assert out[r] is None


@pytest.mark.parametrize("alg", sorted(algs.SCATTER))
@pytest.mark.parametrize("nranks,root", [(8, 0), (8, 6), (5, 2)])
def test_scatter(world, world5, alg, nranks, root):
    comm = world if nranks == 8 else world5
    data = _rank_data(nranks, 4, seed=9)
    out = spmd(comm, lambda c, r: algs.SCATTER[alg](
        c, data if r == root else np.zeros(4, data.dtype), root))
    for r in range(nranks):
        np.testing.assert_allclose(np.asarray(out[r]), data[r])


# ---------------------------------------------------------------------------
# tuned decision layer


@pytest.fixture()
def tuned_module(world):
    from ompi_tpu.base import mca
    from ompi_tpu.mca.coll.tuned import TunedModule

    fw = mca.framework("coll")
    fw.open()
    comp = fw.components["tuned"]
    return TunedModule(comp), comp


def test_tuned_ladder_dispatch(world, tuned_module):
    mod, _ = tuned_module
    data = _rank_data(8, 100, seed=10)
    out = spmd(world, lambda c, r: mod.allreduce(c, data[r]))
    np.testing.assert_allclose(out[0], data.sum(0), rtol=1e-10)
    big = _rank_data(8, 200_000, seed=11)   # 1.6MB -> ring branch
    out = spmd(world, lambda c, r: mod.allreduce(c, big[r]))
    np.testing.assert_allclose(out[3], big.sum(0), rtol=1e-9)


def test_tuned_noncommutative_excluded(world, tuned_module):
    """Non-commutative ops must route to order-safe algorithms end to end."""
    mod, _ = tuned_module
    op = _noncommutative_op()
    data = _matrix_data(8, 2048, seed=22)
    expect = _fold_in_rank_order(data, op)
    out = spmd(world, lambda c, r: mod.allreduce(c, data[r], op))
    np.testing.assert_allclose(out[0], expect, rtol=1e-9)
    out = spmd(world, lambda c, r: mod.reduce_scatter(c, data[r], None, op))
    np.testing.assert_allclose(np.concatenate(out), expect, rtol=1e-9)


def test_tuned_force_var(tuned_module, fresh_registry):
    mod, comp = tuned_module
    fresh_registry.set("otpu_coll_tuned_allreduce_algorithm", "ring")
    assert mod._pick("allreduce", 8, 100, "recursive_doubling") == ("ring", 0)


def test_tuned_dynamic_rules(tuned_module, tmp_path, fresh_registry):
    mod, comp = tuned_module
    rules = tmp_path / "rules.conf"
    rules.write_text(
        "# comments are fine\n"
        "allreduce 8 4096 recursive_doubling\n"
        "allreduce 0 0 ring\n"
        "bcast 0 0 chain 65536\n")
    fresh_registry.set("otpu_coll_tuned_dynamic_rules_filename", str(rules))
    comp.open()
    try:
        assert mod._pick("allreduce", 4, 100, "x") == \
            ("recursive_doubling", 0)
        assert mod._pick("allreduce", 64, 100, "x") == ("ring", 0)  # size>8
        assert mod._pick("allreduce", 4, 1 << 20, "x") == ("ring", 0)
        # the rule's segsize column must reach the segmented algorithm
        assert mod._pick("bcast", 99, 1 << 22, "x") == ("chain", 65536)
        assert mod._pick("barrier", 8, 0, "tree") == ("tree", 0)  # no rule
    finally:
        comp.rules = []


def test_tuned_bad_rules_file_falls_back(tuned_module, tmp_path,
                                         fresh_registry):
    mod, comp = tuned_module
    bad = tmp_path / "bad.conf"
    bad.write_text("allreduce 8 4096 no_such_algorithm\n")
    fresh_registry.set("otpu_coll_tuned_dynamic_rules_filename", str(bad))
    comp.open()
    assert comp.rules == []
    assert mod._pick("allreduce", 8, 100, "ring") == ("ring", 0)
