"""Breadth components: coll/inter, coll/sync, hook/comm_method, mpisync."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_intercomm_collectives(tmp_path):
    """coll/inter: two-group semantics over a connect/accept bridge."""
    script = tmp_path / "inter.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.status import PROC_NULL, ROOT
        w = ompi_tpu.init()
        r = w.rank
        side = w.split(0 if r < 2 else 1)
        inter = (side.accept("ic-port") if r < 2
                 else side.connect("ic-port"))
        assert type(inter.c_coll['allreduce'].__self__).__name__ \\
            == 'InterCollModule'

        inter.barrier()

        # each group receives the OTHER group's sum
        out = inter.allreduce(np.array([float(r + 1)]))
        expect = 3.0 + 4.0 if r < 2 else 1.0 + 2.0   # other group's ranks+1
        assert out[0] == expect, (r, out)

        # allgather: the other group's rows
        g = inter.allgather(np.array([r], np.int64))
        expect_rows = [2, 3] if r < 2 else [0, 1]
        assert np.asarray(g).ravel().tolist() == expect_rows, g

        # rooted bcast from group A rank 1 into group B
        if r == 1:
            inter.bcast(np.array([9.25]), ROOT)
        elif r == 0:
            inter.bcast(np.zeros(1), PROC_NULL)
        else:
            got = inter.bcast(np.zeros(1), 1)   # root's rank in its group
            assert got[0] == 9.25, got

        # rooted reduce: group B's sum lands at group A rank 0
        if r == 0:
            red = inter.reduce(np.zeros(1), root=ROOT)
            assert red[0] == (2 + 1) + (3 + 1), red
        elif r == 1:
            inter.reduce(np.zeros(1), root=PROC_NULL)
        else:
            inter.reduce(np.array([float(r + 1)]), root=0)

        inter.barrier()
        print(f"inter OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("inter OK") == 4


def test_coll_sync_injects_barriers(tmp_path):
    script = tmp_path / "sync.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        fn = w.c_coll['bcast']
        assert getattr(fn, '__sync_wrapped__', False), 'sync not interposed'
        # storm of rooted collectives; sync's barriers keep queues bounded
        for i in range(25):
            out = w.bcast(np.array([float(i)]) if w.rank == 0
                          else np.zeros(1), root=0)
            assert out[0] == float(i)
        print("sync OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)],
                extra=("--mca", "coll_sync_barrier_after", "5"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("sync OK") == 2


def test_hook_comm_method_matrix(tmp_path):
    script = tmp_path / "hook.py"
    script.write_text("import ompi_tpu; ompi_tpu.init()\n")
    r = _tpurun(3, [sys.executable, str(script)],
                extra=("--mca", "hook_comm_method_display", "1"))
    assert r.returncode == 0, r.stdout + r.stderr
    # every rank printed its transport row; sm serves same-host peers
    assert r.stdout.count("[comm_method]") == 3
    assert "sm" in r.stdout


def test_era_tree_agreement_with_failure(tmp_path):
    """The ERA-shaped tree agreement (default algorithm) stays uniform
    when a participant dies mid-stream; the kv algorithm remains
    selectable."""
    script = tmp_path / "era.py"
    script.write_text(textwrap.dedent("""
        import os, time
        import numpy as np, ompi_tpu
        from ompi_tpu.api.errors import ProcFailedError
        from ompi_tpu.api.errhandler import ERRORS_RETURN
        w = ompi_tpu.init()
        w.set_errhandler(ERRORS_RETURN)  # ULFM apps opt out of abort
        r = w.rank
        assert w.agree(1) == 1          # clean round over the tree
        if r == 1:
            os._exit(1)                 # die before the next round
        deadline = time.time() + 30
        while time.time() < deadline and not w.get_failed().size:
            time.sleep(0.1)
        # next agreement: survivors agree uniformly and all observe the
        # unacknowledged failure
        try:
            w.agree(1)
            raise SystemExit("expected ProcFailedError")
        except ProcFailedError as exc:
            assert exc.flag == 1
        w.ack_failed()
        assert w.agree(1) == 1          # acknowledged: clean again
        print(f"era ft OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)], timeout=120,
                extra=("--enable-recovery",))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("era ft OK") == 3

    # the coordinator-decides algorithm remains selectable
    script2 = tmp_path / "kv.py"
    script2.write_text(textwrap.dedent("""
        import ompi_tpu
        w = ompi_tpu.init()
        assert w.agree(1) == 1
        print("kv agree OK")
    """))
    r2 = _tpurun(2, [sys.executable, str(script2)],
                 extra=("--mca", "coll_ftagree_algorithm", "kv"))
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert r2.stdout.count("kv agree OK") == 2


def test_mpisync_clock_offsets():
    r = _tpurun(3, [sys.executable, "-m", "ompi_tpu.tools.mpisync"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rank offset_us rtt_us" in r.stdout
    # rows for ranks 1 and 2 with numeric offsets
    lines = [l for l in r.stdout.splitlines() if l.startswith("[0] ")]
    # peer rows only: rank column != 0 (the reference-clock row)
    data = [l for l in lines
            if l.split()[1].isdigit() and l.split()[1] != "0"]
    assert len(data) == 2, r.stdout
