"""Telemetry-plane test worker: a steady loop of 4KB allreduces.

Two modes (env-selected):

- ``TW_SECS`` (default 4.0): run for a wall-clock window — the live
  otpu_top attach test needs a job that outlives several sampler
  intervals;
- ``TW_ITERS``: run exactly N rounds instead — the otpu_analyze
  straggler test needs a deterministic round count on every rank.
"""
import os
import time

import numpy as np

import ompi_tpu
from ompi_tpu.api import op
from ompi_tpu.ft import chaos

w = ompi_tpu.init()
x = np.ones(1024, np.float32)          # 4KB payload

iters = os.environ.get("TW_ITERS")
if iters is not None:
    for _ in range(int(iters)):
        if chaos.enabled:
            # the designed-straggler pacing point: 'delay:ms=8,rank=2,
            # site=step' makes rank 2 arrive late at every collective
            chaos.pace("step")
        w.allreduce(x, op.SUM)
else:
    # time-based mode with a COLLECTIVE exit decision: rank 0 owns the
    # deadline and the continue-flag allreduce (MIN) keeps every rank
    # doing the same number of rounds — per-rank deadlines would leave
    # finished ranks' peers blocked in a collective nobody else enters
    deadline = time.monotonic() + float(os.environ.get("TW_SECS", "4.0"))
    cont = np.ones(1, np.float32)
    while True:
        if w.rank == 0 and time.monotonic() >= deadline:
            cont = np.zeros(1, np.float32)
        flag = np.asarray(w.allreduce(cont, op.MIN))
        if float(flag[0]) < 0.5:
            break
        if chaos.enabled:
            chaos.pace("step")
        w.allreduce(x, op.SUM)
print(f"TELEMETRY WORKER DONE {w.rank}", flush=True)
ompi_tpu.finalize()
