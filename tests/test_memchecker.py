"""memchecker — buffer-ownership checking (valgrind-annotation analog)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_racy_write_to_inflight_send_buffer_caught(tmp_path):
    script = tmp_path / "mc.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        n = 1 << 18                       # rendezvous-sized
        if w.rank == 0:
            data = np.arange(n, dtype=np.float64)
            req = w.isend(data, 1, tag=7)
            try:
                data[0] = 999.0           # write while MPI owns the buffer
                raise SystemExit("memchecker missed the racy write")
            except ValueError:
                print("racy write caught")
            req.wait()
            data[0] = 999.0               # completed: writable again
        else:
            buf = np.zeros(n)
            w.recv(buf, 0, tag=7)
            assert buf[0] == 0.0 and buf[-1] == n - 1   # data uncorrupted
        w.barrier()
        print(f"mc OK rank {w.rank}")
    """))
    r = _tpurun(2, [sys.executable, str(script)],
                extra=("--mca", "memchecker_enable", "1"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "racy write caught" in r.stdout
    assert r.stdout.count("mc OK") == 2


def test_disabled_by_default(tmp_path):
    script = tmp_path / "mc_off.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        n = 1 << 18
        if w.rank == 0:
            data = np.arange(n, dtype=np.float64)
            req = w.isend(data, 1, tag=7)
            req.wait()
            data[0] = 1.0    # no guard when disabled
        else:
            buf = np.zeros(n)
            w.recv(buf, 0, tag=7)
        w.barrier()
        print("off OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("off OK") == 2
