"""otpu-trace: disabled-path no-op, span/histogram correctness under
concurrency, Chrome-JSON schema validity, and the tpurun gather/merge +
skew report on a real multiprocess run."""
import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from ompi_tpu.base.var import registry
from ompi_tpu.runtime import trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracer():
    """Enabled tracer with clean state; disabled + reset afterwards."""
    registry.set("otpu_trace_enable", True)
    trace.reset_for_testing()
    yield trace
    registry.set("otpu_trace_enable", False)
    trace.reset_for_testing()


class _FakeComm:
    cid = 42

    def __init__(self):
        self.c_coll = {}


def test_disabled_path_records_nothing():
    registry.set("otpu_trace_enable", False)
    trace.reset_for_testing()
    before = trace.recorded_count()
    trace.span("x", "coll", trace.now())
    trace.instant("y", "ft")
    assert trace.recorded_count() == before
    assert trace.enabled is False

    # the coll-table wrapper passes straight through and records nothing
    comm = _FakeComm()
    comm.c_coll["allreduce"] = lambda c, x: x * 2
    trace.wrap_coll_table(comm)
    out = comm.c_coll["allreduce"](comm, np.ones(4))
    assert np.all(out == 2)
    assert trace.histograms() == {}
    assert trace.recorded_count() == 0


def test_wrapper_records_span_and_histogram(tracer):
    comm = _FakeComm()
    comm.c_coll["allreduce"] = lambda c, x: x + 1
    trace.wrap_coll_table(comm)
    # double-wrap guard: wrapping again must not stack another layer
    wrapped = comm.c_coll["allreduce"]
    trace.wrap_coll_table(comm)
    assert comm.c_coll["allreduce"] is wrapped

    x = np.ones(1 << 12, np.float32)          # 16384 B -> "16k" bin
    for _ in range(5):
        comm.c_coll["allreduce"](comm, x)
    hists = trace.histograms()
    assert ("allreduce", "16k") in hists
    count, sum_us, min_us, max_us = hists[("allreduce", "16k")]
    assert count == 5
    assert 0 <= min_us <= max_us
    assert sum_us >= 5 * min_us
    # the same data is live through the MPI_T pvar surface
    pvs = {p.name: p for p in registry.all_pvars()}
    assert pvs["otpu_trace_hist_allreduce_16k_count"].read() == 5
    assert pvs["otpu_trace_hist_allreduce_16k_sum_us"].read() > 0
    # spans landed in the ring with the comm's cid
    spans = [e for e in trace.chrome_events() if e["name"] == "allreduce"]
    assert len(spans) == 5
    assert all(e["args"]["cid"] == 42 for e in spans)


def test_concurrent_recording_is_consistent(tracer):
    per_thread, nthreads = 500, 4

    def worker(i):
        for k in range(per_thread):
            t0 = trace.now()
            trace.span(f"op{i}", "coll", t0)
            trace.hist_record("allreduce", 1024, 1000)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # histogram updates are exact (locked)
    assert trace.histograms()[("allreduce", "1k")][0] == \
        per_thread * nthreads
    # every span got its own ring slot (atomic slot counter)
    assert trace.recorded_count() == per_thread * nthreads
    events = trace.chrome_events()
    assert len(events) == per_thread * nthreads


def test_hist_percentile_interpolates_log2_bins(tracer):
    # a known latency population: 90 fast (1us) + 10 slow (1ms) calls.
    # p50 must land in the fast bin, p99 in the slow bin — each within
    # its log2 bin (the estimator's contract), clamped to observed
    # min/max.
    for _ in range(90):
        trace.hist_record("serve_request", 256, 1_000)
    for _ in range(10):
        trace.hist_record("serve_request", 256, 1_000_000)
    p50 = trace.hist_percentile("serve_request", 0.5)
    p99 = trace.hist_percentile("serve_request", 0.99)
    assert 1.0 <= p50 <= 2.0, p50          # us; fast bin [512ns, 1024ns]+clamp
    assert 512.0 <= p99 <= 1048.0, p99     # us; slow bin [2^19, 2^20) ns
    assert p50 <= trace.hist_percentile("serve_request", 0.9) <= p99

    # single-bin population: clamping pins the estimate to observed range
    for _ in range(10):
        trace.hist_record("one_bin", 8, 700)
    assert trace.hist_percentile("one_bin", 0.99) == pytest.approx(
        0.7, abs=0.3)


def test_hist_percentile_merges_size_bins_and_filters(tracer):
    trace.hist_record("bcast", 64, 10_000)        # 64b size bin, 10us
    trace.hist_record("bcast", 1 << 20, 90_000)   # 1m size bin, 90us
    # per-size-bin query sees only its own cell
    assert trace.hist_percentile("bcast", 0.5, nbytes=64) < 20.0
    assert trace.hist_percentile("bcast", 0.5, nbytes=1 << 20) > 60.0
    # merged query spans both; an unknown coll reports 0
    merged = trace.hist_percentile("bcast", 0.99)
    assert merged >= 64.0
    assert trace.hist_percentile("nope", 0.5) == 0.0
    with pytest.raises(ValueError):
        trace.hist_percentile("bcast", 1.5)


def test_hist_reset_starts_fresh_population(tracer):
    for _ in range(50):
        trace.hist_record("serve_request", 64, 1_000_000)   # 1ms
    assert trace.hist_percentile("serve_request", 0.5) > 500.0
    trace.hist_reset("serve_request")
    assert trace.hist_percentile("serve_request", 0.5) == 0.0
    trace.hist_record("serve_request", 64, 1_000)           # 1us
    assert trace.hist_percentile("serve_request", 0.99) < 10.0
    # other collectives' cells survive the reset
    trace.hist_record("bcast", 64, 5_000)
    trace.hist_reset("serve_request")
    assert trace.hist_percentile("bcast", 0.5) > 0.0


def test_hist_percentile_pvars_via_read_path(tracer):
    for d in (1_000, 2_000, 4_000, 1_000_000):
        trace.hist_record("allreduce", 4096, d)
    by_name = {p.name: p for p in registry.all_pvars()}
    pv50 = by_name.get("otpu_trace_hist_allreduce_4k_p50_us")
    pv99 = by_name.get("otpu_trace_hist_allreduce_4k_p99_us")
    assert pv50 is not None and pv99 is not None
    v50, v99 = pv50.read(), pv99.read()
    assert 0 < v50 < v99 <= 1000.0
    assert v99 > 100.0      # pulled toward the 1ms outlier


def test_ring_overwrites_oldest(tracer):
    n = trace._ring_n
    for i in range(n + 100):
        trace.span(f"s{i}", "coll", trace.now())
    events = trace.chrome_events()
    assert len(events) == n
    payload = trace.chrome_payload(0)
    assert payload["metadata"]["events_overwritten"] == 100


def test_chrome_json_schema(tracer):
    t0 = trace.now()
    trace.span("allreduce", "coll", t0, args={"nbytes": 64})
    trace.instant("ft_detect", "ft", args={"rank": 1})
    payload = trace.chrome_payload(3, clock_offset_us=12.5)
    # must survive a JSON round-trip (what finalize writes to disk)
    payload = json.loads(json.dumps(payload))
    assert set(payload) == {"traceEvents", "metadata"}
    meta = payload["metadata"]
    assert meta["rank"] == 3
    assert meta["clock_offset_us"] == 12.5
    evs = payload["traceEvents"]
    assert len(evs) == 2
    for ev in evs:
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], float)
        assert ev["pid"] == 3
        assert isinstance(ev["tid"], int)
        assert ev["name"] and ev["cat"]
    x = [e for e in evs if e["ph"] == "X"][0]
    assert x["dur"] >= 0
    # events come out oldest-first
    assert evs[0]["ts"] <= evs[1]["ts"]


def _payload(rank, offset_us, spans):
    return {
        "traceEvents": [
            {"ph": "X", "name": name, "cat": "coll", "ts": ts,
             "dur": dur, "pid": rank, "tid": 1,
             "args": {"nbytes": nbytes}}
            for name, ts, dur, nbytes in spans],
        "metadata": {"rank": rank, "clock_offset_us": offset_us},
    }


def test_merge_handles_negative_offsets_and_zero_span_ranks():
    """Crash-bundle shapes: a rank whose clock ran BEHIND the coord's
    (negative offset) must align onto the same timebase, and a rank
    whose payload has zero spans must neither crash the merge/skew path
    nor erase the other ranks' matched rounds."""
    # rank 0 runs 500us behind the coord clock (offset is ours MINUS
    # the coord's, so it is negative); rank 1 runs 250us ahead
    p0 = _payload(0, -500.0, [("allreduce", -400.0, 50.0, 1024)])
    p1 = _payload(1, 250.0, [("allreduce", 350.0, 80.0, 1024)])
    p2 = _payload(2, 100.0, [])               # zero spans (died early)
    merged = trace.merge_timelines([p0, p1, p2])
    assert [e["ts"] for e in merged] == [100.0, 100.0]
    assert sorted(e["pid"] for e in merged) == [0, 1]

    report = trace.skew_report([p0, p1, p2])
    # the zero-span rank must NOT zero the survivors' rounds (the
    # pre-fix behavior: min over ALL ranks made every round unmatched)
    line = next(ln for ln in report.splitlines()
                if ln.startswith("allreduce"))
    cols = line.split()
    assert cols[2] == "1", line               # one matched round
    assert cols[5] == "1", line               # rank 1's 80us is slowest
    assert "absent" in line                   # the dead rank is noted
    assert "3 ranks" in report


def test_flow_events_survive_merge_and_export(tracer):
    trace.flow_start("pml_msg", (3, 0, 1, 9))
    trace.flow_finish("pml_msg", (3, 0, 1, 9))
    payload = trace.chrome_payload(1, clock_offset_us=-40.0)
    payload = json.loads(json.dumps(payload))
    merged = trace.merge_timelines([payload])
    flows = [e for e in merged if e["ph"] in ("s", "f")]
    assert len(flows) == 2
    assert all(e["id"] == "3.0.1.9" for e in flows)
    assert all(e["pid"] == 1 for e in flows)
    # alignment shifted the flow timestamps like any span's
    raw = [e for e in payload["traceEvents"] if e["ph"] in ("s", "f")]
    assert flows[0]["ts"] == raw[0]["ts"] + 40.0


def test_merge_aligns_clocks_and_skew_names_slowest():
    # rank 1's clock runs 1000us ahead of the coord clock; after merge
    # both ranks' allreduces line up at ts=100
    p0 = _payload(0, 0.0, [("allreduce", 100.0, 50.0, 1024)])
    p1 = _payload(1, 1000.0, [("allreduce", 1100.0, 400.0, 1024)])
    merged = trace.merge_timelines([p0, p1])
    assert [e["ts"] for e in merged] == [100.0, 100.0]
    assert sorted(e["pid"] for e in merged) == [0, 1]

    report = trace.skew_report([p0, p1])
    assert "allreduce" in report
    # rank 1's 400us invocation is the straggler (columns: name cid
    # rounds spread_mean spread_max slowest_rank)
    line = next(ln for ln in report.splitlines()
                if ln.startswith("allreduce"))
    assert line.split()[5] == "1"
    assert "p50_us" in report and "1k" in report


def test_boot_path_spans_in_merged_timeline(tmp_path):
    """The instance boot path is spanned — coord connect, jax
    distributed init slot, modex fence, and the whole instance_boot —
    so cross-rank merged timelines show STARTUP skew, not just
    steady-state collective skew."""
    script = tmp_path / "boot_traced.py"
    script.write_text(textwrap.dedent("""
        import ompi_tpu
        w = ompi_tpu.init()
        w.barrier()
        ompi_tpu.finalize()
    """))
    tdir = tmp_path / "traces"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
         "--mca", "trace_enable", "1", "--mca", "trace_dir", str(tdir),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    for rank in range(2):
        p = json.load(open(tdir / f"trace_rank{rank}.json"))
        boots = {e["name"] for e in p["traceEvents"]
                 if e["cat"] == "boot"}
        assert {"coord_connect", "jax_distributed_init", "modex_fence",
                "instance_boot"} <= boots, boots
        # the whole-boot span encloses the fence span
        span_of = {e["name"]: e for e in p["traceEvents"]
                   if e["cat"] == "boot"}
        whole, fence = span_of["instance_boot"], span_of["modex_fence"]
        assert whole["ts"] <= fence["ts"]
        assert whole["ts"] + whole["dur"] >= fence["ts"] + fence["dur"]
    merged = json.load(open(tdir / "trace_merged.json"))
    boot_pids = {e["pid"] for e in merged["traceEvents"]
                 if e.get("cat") == "boot"}
    assert boot_pids == {0, 1}


def test_tpurun_trace_gather_merge_and_skew(tmp_path):
    """4-rank end-to-end: per-rank Chrome JSON, merged timeline, skew
    report — the full gather path through the CoordServer."""
    script = tmp_path / "traced.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu, time
        w = ompi_tpu.init()
        for _ in range(4):
            w.allreduce(np.ones(4096, np.float32))
        if w.rank == w.size - 1:
            time.sleep(0.02)          # deliberate straggler
        w.barrier()
        ompi_tpu.finalize()
    """))
    tdir = tmp_path / "traces"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "4",
         "--mca", "trace_enable", "1", "--mca", "trace_dir", str(tdir),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr

    # per-rank Chrome traces
    for rank in range(4):
        p = json.load(open(tdir / f"trace_rank{rank}.json"))
        assert p["metadata"]["rank"] == rank
        colls = [e for e in p["traceEvents"] if e["cat"] == "coll"]
        assert any(e["name"] == "allreduce" for e in colls)
        assert all(e["pid"] == rank for e in p["traceEvents"])

    # merged timeline: all four pids, time-sorted
    merged = json.load(open(tdir / "trace_merged.json"))
    evs = merged["traceEvents"]
    assert sorted({e["pid"] for e in evs}) == [0, 1, 2, 3]
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)

    # skew report names a slowest rank per collective
    report = (tdir / "trace_skew.txt").read_text()
    assert "allreduce" in report and "slowest_rank" in report
    line = next(ln for ln in report.splitlines()
                if ln.startswith("allreduce"))
    assert int(line.split()[5]) in (0, 1, 2, 3)
