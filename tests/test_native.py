"""ompi_tpu.native — C++ twins must be bit-identical to the Python paths."""
import os

import numpy as np
import pytest

from ompi_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_pack_unpack_matches_numpy():
    from ompi_tpu.datatype import convertor as cv
    from ompi_tpu.datatype import core

    rng = np.random.default_rng(7)
    types = [
        core.vector(4, 2, 4, core.FLOAT64),
        core.indexed([1, 3, 2], [0, 5, 11], core.FLOAT32),
        core.subarray([6, 8], [3, 4], [1, 2], core.ORDER_C, core.FLOAT64),
        core.contiguous(16, core.INT32),
    ]
    for dt in types:
        for count in (1, 3, 7):
            mem = rng.standard_normal(8192).view(np.uint8).copy()
            cn = cv.Convertor(dt, count)
            cn.prepare(mem)
            cn._native = True
            cp = cv.Convertor(dt, count)
            cp.prepare(mem.copy())
            cp._native = False
            a, b = cn.pack(), cp.pack()
            assert np.array_equal(a, b), (dt.name, count)
            dn, dp = np.zeros(8192, np.uint8), np.zeros(8192, np.uint8)
            un = cv.Convertor(dt, count)
            un.prepare(dn)
            un._native = True
            up = cv.Convertor(dt, count)
            up.prepare(dp)
            up._native = False
            un.unpack(a)
            up.unpack(b)
            assert np.array_equal(dn, dp), (dt.name, count)


def test_partial_pack_resume_with_native():
    """Chunked pack with position resume stays identical across paths."""
    from ompi_tpu.datatype import convertor as cv
    from ompi_tpu.datatype import core

    dt = core.vector(8, 3, 5, core.FLOAT32)
    mem = np.arange(4096, dtype=np.uint8)
    for flag in (True, False):
        c = cv.Convertor(dt, 4)
        c.prepare(mem.copy())
        c._native = flag
        chunks = []
        while not c.finished:
            chunks.append(c.pack(37).tobytes())
        stream = b"".join(chunks)
        if flag:
            native_stream = stream
        else:
            assert stream == native_stream


def test_ring_native_roundtrip_and_wraparound():
    from multiprocessing import shared_memory

    from ompi_tpu.mca.btl.sm import _DATA_OFF, _Ring

    shm = shared_memory.SharedMemory(
        create=True, size=(1 << 14) + _DATA_OFF,
        name=f"otpu_ring_t{os.getpid()}")
    try:
        r = _Ring(shm, owner=True)
        assert r._addr is not None     # native path active
        for i in range(500):           # sizes force many wraparounds
            p = os.urandom((i * 53) % 2800 + 1)
            if not r.push(p):
                assert r.pop() is not None
                assert r.push(p)
            assert r.pop() == p
        msgs = [os.urandom(3000) for _ in range(4)]
        for m in msgs:
            assert r.push(m)
        for m in msgs:
            assert r.pop() == m
        assert r.pop() is None
    finally:
        shm.close()
        shm.unlink()


def test_python_and_native_rings_interoperate():
    """A Python-side writer must be readable by the native popper and
    vice versa (mixed jobs where one process lacks the library)."""
    from multiprocessing import shared_memory

    from ompi_tpu.mca.btl.sm import _DATA_OFF, _Ring

    shm = shared_memory.SharedMemory(
        create=True, size=(1 << 12) + _DATA_OFF,
        name=f"otpu_ring_x{os.getpid()}")
    try:
        nat = _Ring(shm, owner=True)
        pyr = _Ring(shm, owner=False)
        pyr._addr = None               # force the Python path
        assert nat._addr is not None
        nat.push(b"from-native")
        assert pyr.pop() == b"from-native"
        pyr.push(b"from-python")
        assert nat.pop() == b"from-python"
        # framed (header+payload) push/pop must interoperate the same way
        nat.push_frame(b"hdr-n", b"payload-from-native")
        frame = pyr.pop_frame()
        assert frame is not None and frame.tobytes().endswith(
            b"payload-from-native")
        pyr.push_frame(b"hdr-p", b"payload-from-python")
        frame = nat.pop_frame()
        assert frame is not None and frame.tobytes().endswith(
            b"payload-from-python")
    finally:
        shm.close()
        shm.unlink()
