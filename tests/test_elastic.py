"""parallel/elastic — train-through-failure.

* unit: the integer gradient field's partition-invariance (the property
  that makes degraded-width continuation bit-exact) and the
  checkpoint/restore/replay loop in one process;
* tpurun + chaos (the acceptance scenario): a 4-rank training job with
  a ``kill:rank=2,step=7`` schedule completes with parameters
  BIT-EXACT to a failure-free run restored from the same checkpoint
  step, respawning back to full width via ``dpm.spawn`` verified
  against the ``mpi://job/<id>`` pset, with the
  detect→agree→shrink→respawn→restore→resume spans in the merged
  trace timeline;
* shrink-only degraded-width continuation (no respawn).
"""
import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from ompi_tpu.parallel import elastic

REPO = Path(__file__).resolve().parent.parent


def test_grad_field_partition_invariant():
    """Any contiguous partition of the global batch sums to the same
    float64 bit pattern — integer summands, exact dyadic lr."""
    full = elastic.grad_field(3, 0, 32, 16)
    for width in (1, 2, 3, 4, 5, 7):
        parts = np.zeros(16, np.float64)
        for r in range(width):
            lo, hi = elastic.partition(r, width, 32)
            parts = parts + elastic.grad_field(3, lo, hi, 16)
        assert parts.tobytes() == full.tobytes(), width
    # partition() covers the batch exactly, no overlap
    seen = []
    for r in range(5):
        lo, hi = elastic.partition(r, 5, 32)
        seen.extend(range(lo, hi))
    assert seen == list(range(32))


def test_trainer_matches_reference_in_process(tmp_path, monkeypatch):
    """Single-rank ProcRte world (the trainer targets the multi-process
    model: host allreduce, not the device world's leading-axis
    convention): train/checkpoint/restore/replay is exact."""
    import ompi_tpu
    from ompi_tpu.rte.coord import CoordServer
    from ompi_tpu.runtime import init as rt

    srv = CoordServer(1)
    monkeypatch.setenv("OTPU_COORD", f"{srv.addr[0]}:{srv.addr[1]}")
    monkeypatch.setenv("OTPU_RANK", "0")
    monkeypatch.setenv("OTPU_NPROCS", "1")
    rt.reset_for_testing()
    try:
        w = ompi_tpu.init()
        tr = elastic.ElasticTrainer(w, ckpt_dir=str(tmp_path / "ck"),
                                    model_size=8, global_batch=12,
                                    ckpt_every=4)
        got = tr.train(9)
        ref = elastic.reference_run(np.zeros(8), 0, 9, 12)
        assert got.tobytes() == ref.tobytes()
        # restore from the latest checkpoint replays to the same params
        step = tr.latest_complete_step()
        assert step == 8
        tr._restore(step)
        assert tr.step == 8
        assert tr.train(9).tobytes() == ref.tobytes()
    finally:
        rt.reset_for_testing()
        srv.close()


_ELASTIC_JOB = textwrap.dedent("""
    import json, sys
    import ompi_tpu
    from ompi_tpu.parallel.elastic import ElasticTrainer

    w = ompi_tpu.init()
    tr = ElasticTrainer(w, ckpt_dir=sys.argv[1], model_size=12,
                        global_batch=24, ckpt_every=5,
                        respawn=(sys.argv[2] == "respawn"))
    tr.train(15)
    if tr.comm.rank == 0:
        print("ELASTIC " + json.dumps(tr.report()), flush=True)
    ompi_tpu.finalize()
""")


def _run_elastic(tmp_path, n, kill_spec, mode, extra_mca=(), timeout=300):
    script = tmp_path / "job.py"
    script.write_text(_ELASTIC_JOB)
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           "--enable-recovery",
           "--mca", "otpu_chaos_spec", kill_spec]
    for k, v in extra_mca:
        cmd += ["--mca", k, v]
    cmd += [sys.executable, str(script), str(ckpt), mode]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=timeout, cwd=REPO, env=env)
    line = next((ln for ln in r.stdout.splitlines() if "ELASTIC " in ln),
                None)
    assert line is not None, r.stdout + r.stderr
    return json.loads(line.split("ELASTIC ", 1)[1]), ckpt, r


def test_elastic_kill_respawn_bitexact(tmp_path):
    """The acceptance scenario: chaos kill schedule
    ``kill:rank=2,step=7``; recovery shrinks, respawns back to full
    width (replacements verified against the job pset), restores, and
    the final parameters are bit-exact to a failure-free run restored
    from the same checkpoint step; the merged timeline carries every
    recovery phase span."""
    tdir = tmp_path / "trace"
    rep, ckpt, r = _run_elastic(
        tmp_path, 4, "kill:rank=2,step=7", "respawn",
        extra_mca=(("otpu_trace_enable", "1"),
                   ("otpu_trace_dir", str(tdir))))
    assert rep["step"] == 15
    assert rep["world_size"] == 4, "never respawned to full width"
    recs = rep["recoveries"]
    # at least one recovery; a loaded host may see a benign second one
    # (a late pending request completing after resume).  The FIRST
    # recovery may have been entered via the peer's revocation BEFORE
    # the local failure mark landed, so rec["failed"] (the detect-time
    # snapshot) is <= {2}, not necessarily == [2].
    assert recs and set(recs[0]["failed"]) <= {2}
    assert recs[0]["detect_step"] == 7 and recs[0]["resume_step"] == 5
    assert "respawn_ms" in recs[0] and recs[0]["total_ms"] > 0
    # bit-exactness: the failure-free oracle restored from the SAME
    # checkpoint step the recovery used (the very files the job wrote)
    from ompi_tpu.parallel import checkpoint

    tree = checkpoint.load(str(ckpt / f"step{recs[0]['resume_step']:06d}"))
    assert int(np.asarray(tree["step"]).ravel()[0]) == 5
    ref = elastic.reference_run(np.asarray(tree["w"]),
                                recs[0]["resume_step"], 15, 24)
    assert rep["w"] == ref.tolist(), "parameter continuation diverged"
    # recovery state machine on the merged timeline
    merged = tdir / "trace_merged.json"
    assert merged.exists(), r.stdout + r.stderr
    names = {e.get("name") for e in
             json.loads(merged.read_text())["traceEvents"]}
    for span in ("elastic_detect", "elastic_agree", "elastic_shrink",
                 "elastic_respawn", "elastic_restore",
                 "elastic_resume"):
        assert span in names, (span, sorted(names))


def test_elastic_shrink_only_degraded_width(tmp_path):
    """No-respawn mode: the job continues at degraded width (3 → 2)
    and the continuation stays bit-exact — the global-batch gradient
    sum is width-invariant by construction."""
    rep, ckpt, _r = _run_elastic(tmp_path, 3, "kill:rank=1,step=6",
                                 "shrink")
    assert rep["step"] == 15
    assert rep["world_size"] == 2, "shrink-only run changed width"
    recs = rep["recoveries"]
    assert recs and set(recs[0]["failed"]) <= {1}
    assert all("respawn_ms" not in rec for rec in recs)
    from ompi_tpu.parallel import checkpoint

    tree = checkpoint.load(str(ckpt / f"step{recs[0]['resume_step']:06d}"))
    ref = elastic.reference_run(np.asarray(tree["w"]),
                                recs[0]["resume_step"], 15, 24)
    assert rep["w"] == ref.tolist()
