"""mca/part — MPI-4 partitioned communication (SURVEY §1/§2 part/persist
analog): Psend_init/Precv_init with Pready/Pready_range/Pready_list and
Parrived, aggregation onto fewer wire messages, mismatched send/recv
partition counts, mixed Startall, loud error paths, a seeded Pready-order
fuzz vs a numpy reference, the partitioned device collective (pcoll),
and the parallel_bucket_overlap trainer dryrun."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.request import start_all

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    from ompi_tpu.mca.part import part_framework

    part_framework().open()   # registers otpu_part_persist_* vars
    yield w
    rt.reset_for_testing()


@pytest.fixture
def min_partitions(world):
    """Set-and-restore handle on the aggregation var."""
    from ompi_tpu.base.var import registry

    var = registry.lookup("otpu_part_persist_min_partitions")
    old = var.value

    def setter(v):
        var.set(v)

    yield setter
    var.set(old)


def test_partitioned_pingpong_single_process(world):
    a, b = world.as_rank(0), world.as_rank(1)
    x = np.arange(24.0)
    y = np.zeros(24)
    s = a.psend_init(x, 4, dest=1, tag=11)
    r = b.precv_init(y, 4, source=0, tag=11)
    for epoch in range(3):                       # restartable
        x[:] = np.arange(24.0) * (epoch + 1)
        start_all([s, r])
        assert not r.complete_flag
        s.pready_range(0, 3)
        s.wait()
        r.wait()
        np.testing.assert_array_equal(y, x)
        assert all(r.parrived(p) for p in range(4))


def test_out_of_order_and_interleaved_pready(world):
    a, b = world.as_rank(2), world.as_rank(3)
    x = np.arange(32.0)
    y = np.zeros(32)
    s = a.psend_init(x, 8, dest=3, tag=12)
    r = b.precv_init(y, 8, source=2, tag=12)
    start_all([s, r])
    # interleave: ready a few, observe arrival, ready the rest reversed
    s.pready_list([5, 1])
    assert r.parrived(5) and r.parrived(1)
    assert not r.parrived(0)
    psize = 32 // 8
    np.testing.assert_array_equal(y[5 * psize:6 * psize],
                                  x[5 * psize:6 * psize])
    for p in (7, 6, 4, 3, 2, 0):
        s.pready(p)
    s.wait()
    r.wait()
    np.testing.assert_array_equal(y, x)


def test_mismatched_partition_counts(world):
    a, b = world.as_rank(0), world.as_rank(1)
    x = np.arange(48.0)
    # send 4 partitions / recv 3, then send 2 / recv 8 (same bytes)
    for sp, rp in ((4, 3), (2, 8), (6, 1)):
        y = np.zeros(48)
        s = a.psend_init(x, sp, dest=1, tag=13)
        r = b.precv_init(y, rp, source=0, tag=13)
        start_all([s, r])
        for p in np.random.RandomState(sp).permutation(sp):
            s.pready(int(p))
        s.wait()
        r.wait()
        np.testing.assert_array_equal(y, x)
        assert all(r.parrived(p) for p in range(rp))


def test_aggregation_reduces_wire_messages(world, min_partitions):
    from ompi_tpu.runtime import spc

    a, b = world.as_rank(4), world.as_rank(5)
    x = np.arange(64.0)
    y = np.zeros(64)
    min_partitions(4)
    s = a.psend_init(x, 8, dest=5, tag=14)
    r = b.precv_init(y, 8, source=4, tag=14)
    m0 = spc.read("part_msgs")
    start_all([s, r])
    for p in range(8):          # in-order: one run of 4 + forced rest
        s.pready(p)
    s.wait()
    r.wait()
    np.testing.assert_array_equal(y, x)
    assert spc.read("part_msgs") - m0 == 2
    # and Parrived still tracks under aggregated framing
    min_partitions(8)
    start_all([s, r])
    s.pready_range(0, 6)
    assert not r.parrived(0)    # whole run held below the threshold
    s.pready(7)                 # final pready force-flushes one message
    s.wait()
    r.wait()
    assert all(r.parrived(p) for p in range(8))


def test_startall_mixed_classic_and_partitioned(world):
    a, b = world.as_rank(6), world.as_rank(7)
    xp = np.arange(16.0)
    xc = np.full(4, 7.0)
    yp = np.zeros(16)
    yc = np.zeros(4)
    sp = a.psend_init(xp, 4, dest=7, tag=15)
    sc = a.send_init(xc, dest=7, tag=16)
    rp = b.precv_init(yp, 2, source=6, tag=15)
    rc = b.recv_init(yc, source=6, tag=16)
    start_all([sp, sc, rp, rc])
    sp.pready_list(range(4))
    from ompi_tpu.api.request import waitall

    waitall([sp, sc, rp, rc])
    np.testing.assert_array_equal(yp, xp)
    np.testing.assert_array_equal(yc, xc)


def test_error_paths(world):
    a, b = world.as_rank(0), world.as_rank(1)
    x = np.arange(8.0)
    y = np.zeros(8)
    s = a.psend_init(x, 4, dest=1, tag=17)
    r = b.precv_init(y, 4, source=0, tag=17)
    # Pready before start (inactive)
    with pytest.raises(MpiError) as exc:
        s.pready(0)
    assert exc.value.error_class is ErrorClass.ERR_REQUEST
    # Parrived before the first start
    with pytest.raises(MpiError) as exc:
        r.parrived(0)
    assert exc.value.error_class is ErrorClass.ERR_REQUEST
    start_all([s, r])
    # out-of-range partition indices, both sides
    with pytest.raises(MpiError) as exc:
        s.pready(4)
    assert exc.value.error_class is ErrorClass.ERR_ARG
    with pytest.raises(MpiError):
        s.pready(-1)
    with pytest.raises(MpiError) as exc:
        r.parrived(99)
    assert exc.value.error_class is ErrorClass.ERR_ARG
    # double-Pready of the same partition
    s.pready(2)
    with pytest.raises(MpiError) as exc:
        s.pready(2)
    assert exc.value.error_class is ErrorClass.ERR_ARG
    # Parrived on the send side / Pready on the recv side
    with pytest.raises(MpiError) as exc:
        s.parrived(0)
    assert exc.value.error_class is ErrorClass.ERR_REQUEST
    with pytest.raises(MpiError) as exc:
        r.pready(0)
    assert exc.value.error_class is ErrorClass.ERR_REQUEST
    # Pready/Parrived on a non-partitioned request
    req = a.send_init(x, dest=1, tag=18)
    with pytest.raises(MpiError):
        req.pready(0)
    with pytest.raises(MpiError):
        req.parrived(0)
    # drain the open epoch so no posted traffic dangles
    s.pready_list([0, 1, 3])
    s.wait()
    r.wait()
    # init-time validation: wildcards, bad counts, bad buffers
    from ompi_tpu.api.status import ANY_SOURCE, ANY_TAG

    with pytest.raises(MpiError):
        b.precv_init(y, 4, source=ANY_SOURCE, tag=1)
    with pytest.raises(MpiError):
        a.psend_init(x, 4, dest=1, tag=ANY_TAG)
    with pytest.raises(MpiError):
        a.psend_init(x, 3, dest=1, tag=1)      # 8 % 3 != 0
    with pytest.raises(MpiError):
        a.psend_init(x, 0, dest=1, tag=1)
    with pytest.raises(MpiError):
        a.psend_init([1.0, 2.0], 2, dest=1, tag=1)   # not an ndarray
    ro = np.arange(8.0)
    ro.setflags(write=False)
    with pytest.raises(MpiError):
        b.precv_init(ro, 4, source=0, tag=1)


def test_fuzz_random_pready_orders(world, min_partitions):
    """Seeded fuzz: random partition counts (mismatched send/recv),
    random Pready orders, random aggregation thresholds — every epoch
    validated against the numpy reference copy."""
    rng = np.random.RandomState(1234)
    a, b = world.as_rank(1), world.as_rank(2)
    for trial in range(12):
        sp = int(rng.randint(1, 9))
        rp = int(rng.randint(1, 9))
        unit = int(rng.randint(1, 5))
        count = sp * rp * unit
        x = rng.normal(size=count)
        y = np.zeros(count)
        min_partitions(int(rng.randint(1, 5)))
        s = a.psend_init(x, sp, dest=2, tag=20 + trial)
        r = b.precv_init(y, rp, source=1, tag=20 + trial)
        for _ in range(int(rng.randint(1, 3))):
            start_all([s, r])
            order = rng.permutation(sp)
            for p in order[:sp // 2]:
                s.pready(int(p))
            # poll some random Parrived mid-stream (must not disturb)
            for p in rng.randint(0, rp, size=3):
                r.parrived(int(p))
            for p in order[sp // 2:]:
                s.pready(int(p))
            s.wait()
            r.wait()
            np.testing.assert_array_equal(y, x)
            assert all(r.parrived(p) for p in range(rp))


def test_proc_null_partitioned(world):
    from ompi_tpu.api.status import PROC_NULL

    a = world.as_rank(0)
    x = np.arange(8.0)
    s = a.psend_init(x, 4, dest=PROC_NULL, tag=1)
    r = a.precv_init(np.zeros(8), 4, source=PROC_NULL, tag=1)
    start_all([s, r])
    r.wait()                      # completes immediately
    s.pready_range(0, 3)
    s.wait()
    assert r.parrived(0)


def test_partitioned_pingpong_multiprocess(tmp_path):
    script = tmp_path / "part_pp.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu

        w = ompi_tpu.init()
        parts = 8
        x = np.arange(64.0) + 100 * w.rank
        y = np.zeros(64)
        peer = 1 - w.rank
        if w.rank == 0:
            s = w.psend_init(x, parts, dest=1, tag=3)
            r = w.precv_init(y, 4, source=1, tag=4)   # mismatched counts
        else:
            r = w.precv_init(y, 4, source=0, tag=3)
            s = w.psend_init(x, parts, dest=0, tag=4)
        for epoch in range(2):
            x[:] = np.arange(64.0) + 100 * w.rank + epoch
            if w.rank == 0:
                s.start()
                for p in (5, 0, 7, 2, 1, 6, 3, 4):    # out of order
                    s.pready(p)
                s.wait()
                r.start(); r.wait()
            else:
                r.start(); r.wait()
                s.start()
                for p in range(parts):
                    s.pready(p)
                s.wait()
            want = np.arange(64.0) + 100 * (1 - w.rank) + epoch
            assert np.array_equal(y, want), (w.rank, epoch, y[:4])
            assert all(r.parrived(p) for p in range(4))
        print(f"PART OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.stdout.count("PART OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_partitioned_aggregated_multiprocess(tmp_path):
    """Aggregation var honored across processes; Parrived tracks under
    aggregated framing (several app partitions per wire message)."""
    script = tmp_path / "part_agg.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.runtime import spc

        w = ompi_tpu.init()
        x = np.arange(256.0)
        y = np.zeros(256)
        if w.rank == 0:
            s = w.psend_init(x, 16, dest=1, tag=2)
            m0 = spc.read("part_msgs")
            s.start()
            for p in range(16):
                s.pready(p)
            s.wait()
            sent = spc.read("part_msgs") - m0
            assert sent == 4, sent     # 16 partitions / min 4 -> 4 msgs
        else:
            r = w.precv_init(y, 8, source=0, tag=2)
            r.start()
            r.wait()
            assert np.array_equal(y, x)
            assert all(r.parrived(p) for p in range(8))
        print(f"AGG OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script,
                extra=("--mca", "part_persist_min_partitions", "4"))
    assert r.stdout.count("AGG OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_pallreduce_init_device_pcoll(world):
    """Partitioned persistent allreduce: each bucket bound once, released
    by Pready in production order, result per bucket."""
    n = world.size
    buckets = [np.full((n, 4), float(i + 1), np.float32)
               for i in range(3)]
    req = world.pallreduce_init(buckets)
    req.start()
    for i in (2, 1, 0):                     # late bucket first
        req.pready(i)
        # dispatch is async: Parrived flips once the device result lands
        for _ in range(2000):
            if req.parrived(i):
                break
        assert req.parrived(i)
    req.wait()
    for i in range(3):
        np.testing.assert_allclose(np.asarray(req.result[i]),
                                   (i + 1) * n)
    # restart with fresh data (device arrays are immutable)
    req.start([b * 2 for b in buckets])
    with pytest.raises(MpiError):
        req.pready(3)                       # out of range
    req.pready_range(0, 2)
    with pytest.raises(MpiError):
        req.pready(1)                       # double release
    req.wait()
    np.testing.assert_allclose(np.asarray(req.result[2]), 6 * n)


def test_pallreduce_failed_dispatch_does_not_wedge(world):
    """A pready whose dispatch raises (rebind with a bucket mismatching
    the bound template) must NOT release the bucket: the same error
    surfaces again on retry (not 'already released'), and the request
    stays freeable/restartable instead of wedging wait() forever."""
    n = world.size
    good = [np.ones((n, 4), np.float32)]
    req = world.pallreduce_init(good)
    # len ok, but the leading axis is not divisible by the mesh size,
    # so the bound program's sharded dispatch raises
    req.start([np.ones((n + 1, 4), np.float32)])
    with pytest.raises(Exception) as first:
        req.pready(0)
    assert "already released" not in str(first.value)
    with pytest.raises(Exception) as again:      # rollback: same error
        req.pready(0)
    assert "already released" not in str(again.value)
    req.free()
    req.start(good)
    req.pready(np.int64(0))                      # numpy index accepted
    req.wait()
    np.testing.assert_allclose(np.asarray(req.result[0]), float(n))


def test_pallreduce_matches_plain_allreduce(world):
    n = world.size
    rng = np.random.RandomState(7)
    buckets = [rng.normal(size=(n, 8)).astype(np.float32)
               for i in range(4)]
    req = world.pallreduce_init(buckets)
    req.start()
    req.pready_list(range(4))
    req.wait()
    for b, got in zip(buckets, req.result):
        # f32 reduction order differs between the bound device program
        # and the plain path — equal within a few ulp, not bitwise
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(world.allreduce(b)),
                                   rtol=1e-5)


def test_bucket_overlap_dryrun_bit_identical():
    """The acceptance pin: parallel_bucket_overlap produces bit-identical
    parameters to the non-overlapped trainer step (8-device virtual
    mesh, default and pp-active specs)."""
    import jax

    from ompi_tpu.parallel.dryrun import (parse_spec,
                                          run_bucket_overlap_check)

    run_bucket_overlap_check(jax.devices())
    run_bucket_overlap_check(jax.devices(),
                             parse_spec("dp=2,pp=2,sp=1,tp=2"))


def test_bucket_overlap_rejects_zero1():
    from ompi_tpu.base.var import registry

    import jax

    from ompi_tpu.parallel import train
    from ompi_tpu.parallel.dryrun import make_step_and_args

    bvar = registry.lookup("otpu_parallel_bucket_overlap")
    zvar = registry.lookup("otpu_parallel_zero1")
    old_b, old_z = bvar.value, zvar.value
    bvar.set(True)
    zvar.set(True)
    try:
        with pytest.raises(ValueError):
            make_step_and_args(jax.devices())
    finally:
        bvar.set(old_b)
        zvar.set(old_z)


def test_part_framework_discovered_by_otpu_info():
    """Satellite: the part framework (single default component) must be
    auto-discovered and its cvars visible under --all/--parsable."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_info", "--all",
         "--parsable"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    assert "mca part:persist (priority 20)" in r.stdout
    assert "mca var otpu_part_persist_min_partitions:1" in r.stdout


def test_part_spans_and_counters(world, min_partitions):
    """Observability satellite: pready spans + part_* SPC counters."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import spc, trace

    registry.set("otpu_trace_enable", True)
    trace.reset_for_testing()
    try:
        a, b = world.as_rank(0), world.as_rank(3)
        x = np.arange(16.0)
        y = np.zeros(16)
        c0 = spc.read("part_pready")
        s = a.psend_init(x, 4, dest=3, tag=19)
        r = b.precv_init(y, 4, source=0, tag=19)
        start_all([s, r])
        s.pready_range(0, 3)
        s.wait()
        r.wait()
        assert spc.read("part_pready") - c0 == 4
        assert spc.read("part_bytes") > 0
        names = {e[1] for e in trace._ring if e is not None}
        assert "pready" in names, names
        assert "part_arrive" in names, names
        assert any(k[0] == "pready" for k in trace.histograms()), \
            trace.histograms().keys()
    finally:
        registry.set("otpu_trace_enable", False)
        trace.reset_for_testing()
