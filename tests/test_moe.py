"""parallel/moe — expert parallelism over the ragged tier (ISSUE 17).

Acceptance coverage: gating is a pure deterministic function (same
seed + inputs ⇒ identical assignment across PYTHONHASHSEED-randomized
processes; dropped-token counts exactly reconcile with the capacity
factor), the expert-sharded host trainer is bit-exact against the
single-process oracle through checkpoint/restore AND a 2-process
tpurun, a chaos kill mid-train recovers elastically with the experts
re-sharded over the survivors, a designed-imbalance run's hot-expert
home rank bounds >= 90% of steps under ``otpu_analyze
--critical-path``, the device-tier expert FFN over the ('expert',)
mesh axis is bit-stable, the int8-quantized dispatch stays inside the
``otpu_quant_budget`` band through the REAL ragged device kernel, and
the fused coll/tuned DEVICE ladder cell matches its unfused fallback.
"""
import json
import math
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errors import MpiError
from ompi_tpu.parallel import moe
from ompi_tpu.parallel.elastic import partition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ gating (pure)

def test_gate_weights_dyadic_and_exact():
    for k in range(1, 6):
        w = moe.gate_weights(k)
        assert len(w) == k
        # dyadic rationals summing to EXACTLY 1.0 — combines stay
        # bit-exact no matter how the weighted rows are folded
        assert math.fsum(w) == 1.0 and sum(w) == 1.0
        assert all(x > 0 for x in w)
        assert list(w[1:]) == sorted(w[1:], reverse=True)
    assert moe.gate_weights(3) == (0.625, 0.25, 0.125)


def test_capacity_formula():
    assert moe.capacity_for(64, 8, 2, 1.25) == \
        math.ceil(1.25 * 64 * 2 / 8)
    assert moe.capacity_for(2, 8, 1, 0.01) == 1      # never below 1
    assert moe.capacity_for(48, 6, 2, 3.0) == 48


def test_plan_is_deterministic_and_total():
    a = moe.plan_step(5, 64, 8, 2, 1.25, seed=3)
    b = moe.plan_step(5, 64, 8, 2, 1.25, seed=3)
    assert a.to_json() == b.to_json()
    # every (token, slot) pair lands exactly once, kept or dropped
    assert len(a.kept) + len(a.dropped) == 64 * 2
    # loads ARE the per-expert kept counts, all within capacity
    counts = [0] * 8
    for asn in a.kept:
        assert asn.pos == counts[asn.expert]   # slots fill in order
        counts[asn.expert] += 1
    assert tuple(counts) == a.loads
    assert max(a.loads) <= a.capacity
    with pytest.raises(ValueError):
        moe.plan_step(0, 16, 4, 5, 1.25)


def test_drop_counts_reconcile_with_capacity_factor():
    """The satellite-3 accounting check: dropped == overflow demand.
    Demand is recomputed INDEPENDENTLY from the raw gate scores, so
    the plan's capacity loop is checked against the closed form
    ``sum_e max(0, demand_e - capacity)``."""
    T, E, k, cf = 96, 8, 2, 0.75
    plan = moe.plan_step(7, T, E, k, cf, seed=11)
    s = moe.gate_scores(7, T, E, 11)
    key = s * E + (E - 1 - np.arange(E, dtype=np.int64))[None, :]
    order = np.argsort(-key, axis=1, kind="stable")[:, :k]
    demand = np.bincount(order.ravel(), minlength=E)
    cap = moe.capacity_for(T, E, k, cf)
    assert plan.capacity == cap
    assert len(plan.dropped) == int(np.maximum(demand - cap, 0).sum())
    assert plan.loads == tuple(np.minimum(demand, cap).tolist())
    # a capacity factor of E/k * slack admits every assignment
    full = moe.plan_step(7, T, E, k, float(E), seed=11)
    assert not full.dropped and len(full.kept) == T * k


def test_hot_expert_skews_load():
    base = moe.plan_step(2, 128, 8, 2, 4.0, seed=0)
    hot = moe.plan_step(2, 128, 8, 2, 4.0, seed=0, hot_expert=5,
                        hot_boost=0.6)
    assert int(np.argmax(hot.loads)) == 5
    assert hot.imbalance() > base.imbalance()
    # the boosted token set is STEP-independent: the same rank stays
    # hot every step (what makes the critical-path blame stable)
    hot2 = moe.plan_step(3, 128, 8, 2, 4.0, seed=0, hot_expert=5,
                         hot_boost=0.6)
    assert int(np.argmax(hot2.loads)) == 5


def test_gating_identical_across_hash_seeds():
    """Satellite 3: same seed + inputs ⇒ byte-identical assignment in
    processes with randomized PYTHONHASHSEED."""
    prog = ("from ompi_tpu.parallel import moe; "
            "print(moe.plan_step(3, 96, 8, 2, 1.25, seed=11, "
            "hot_expert=5, hot_boost=0.3).to_json())")
    outs = []
    for hs in ("0", "4242", "random"):
        env = dict(os.environ, PYTHONHASHSEED=hs)
        r = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, timeout=120,
                           cwd=REPO)
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout.strip())
    assert outs[0] and outs[0] == outs[1] == outs[2]
    assert outs[0] == moe.plan_step(3, 96, 8, 2, 1.25, seed=11,
                                    hot_expert=5,
                                    hot_boost=0.3).to_json()


def test_reference_run_is_expert_sharding_invariant():
    """The oracle folds every kept row in plan order; dyadic weights +
    integer grads make the result independent of HOW experts are
    grouped — the property the re-shard acceptance leans on."""
    w = moe.reference_moe_run(np.zeros(32), 0, 6, tokens=16,
                              n_experts=4, expert_dim=8, seed=5)
    again = moe.reference_moe_run(np.zeros(32), 0, 6, tokens=16,
                                  n_experts=4, expert_dim=8, seed=5)
    assert w.tobytes() == again.tobytes()
    assert np.isfinite(w).all() and np.abs(w).sum() > 0


# ------------------------------------------- host trainer (in-process)

def test_moe_trainer_matches_reference_in_process(tmp_path, monkeypatch):
    """Single-rank ProcRte world: expert-parallel train / checkpoint /
    restore / replay is bit-exact against the oracle, and the SPC +
    report dispatch accounting reconciles with the plans."""
    from ompi_tpu.rte.coord import CoordServer
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import spc

    srv = CoordServer(1)
    monkeypatch.setenv("OTPU_COORD", f"{srv.addr[0]}:{srv.addr[1]}")
    monkeypatch.setenv("OTPU_RANK", "0")
    monkeypatch.setenv("OTPU_NPROCS", "1")
    rt.reset_for_testing()
    try:
        w = ompi_tpu.init()
        spc0 = spc.read("moe_dispatch_tokens")
        tr = moe.MoeTrainer(w, str(tmp_path / "ck"), n_experts=6,
                            expert_dim=8, tokens_per_step=24,
                            top_k=2, capacity_factor=0.9,
                            ckpt_every=4, seed=3)
        got = tr.train(9)
        ref = moe.reference_moe_run(np.zeros(48), 0, 9, tokens=24,
                                    n_experts=6, expert_dim=8,
                                    capacity_factor=0.9, seed=3)
        assert got.tobytes() == ref.tobytes()
        # accounting: dispatched/dropped are exactly the plan totals
        kept = dropped = 0
        for s in range(9):
            p = moe.plan_step(s, 24, 6, 2, 0.9, seed=3)
            kept += len(p.kept)
            dropped += len(p.dropped)
        rep = tr.report()
        assert rep["dispatched"] == kept
        assert rep["dropped"] == dropped and dropped > 0
        assert rep["experts"] == [0, 6]
        assert rep["imbalance_max"] >= 1.0
        assert spc.read("moe_dispatch_tokens") - spc0 == kept
        assert moe._TELEM["steps"] >= 9
        # restore from the expert-boundary checkpoint and replay
        step = tr.latest_complete_step()
        assert step == 8
        tr._restore(step)
        assert tr.step == 8
        assert tr.train(9).tobytes() == ref.tobytes()
        # drop_policy=error: the same overflow is a loud ERR_TRUNCATE
        tr2 = moe.MoeTrainer(w, str(tmp_path / "ck2"), n_experts=6,
                             expert_dim=8, tokens_per_step=24,
                             capacity_factor=0.9, drop_policy="error",
                             seed=3)
        with pytest.raises(MpiError):
            tr2.train(9)
    finally:
        rt.reset_for_testing()
        srv.close()


def test_trainer_rejects_bogus_drop_policy():
    with pytest.raises(MpiError):
        moe.MoeTrainer(None, "unused", drop_policy="bogus")


# --------------------------------------------- multi-process (tpurun)

_MOE_JOB = textwrap.dedent("""
    import json, sys
    import ompi_tpu
    from ompi_tpu.parallel.moe import MoeTrainer

    w = ompi_tpu.init()
    conf = json.loads(sys.argv[2])
    steps = conf.pop("steps")
    tr = MoeTrainer(w, sys.argv[1], **conf)
    tr.train(steps)
    rep = tr.report()
    print("MOERANK %d " % w.rank + json.dumps(
        {"dispatched": rep["dispatched"],
         "dropped": rep["dropped"]}), flush=True)
    if w.rank == 0:
        print("MOE " + json.dumps(rep), flush=True)
    ompi_tpu.finalize()
""")


def _tpurun_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("OTPU_RANK", "OTPU_NPROCS", "OTPU_COORD"):
        env.pop(k, None)
    return env


def test_mp_moe_train_bit_exact_and_reconciled(tmp_path):
    """The 2-process acceptance run: expert-parallel training over the
    ragged host collectives lands bit-exact on the oracle, and the
    per-rank dispatch/drop counters sum to the global plan totals."""
    script = tmp_path / "job.py"
    script.write_text(_MOE_JOB)
    conf = {"steps": 10, "n_experts": 6, "expert_dim": 8,
            "tokens_per_step": 24, "capacity_factor": 0.9,
            "ckpt_every": 4, "seed": 3}
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
           sys.executable, str(script), str(tmp_path / "ckpt"),
           json.dumps(conf)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=_tpurun_env())
    line = next((ln for ln in r.stdout.splitlines()
                 if "MOE " in ln and "MOERANK" not in ln), None)
    assert line is not None, r.stdout + r.stderr
    rep = json.loads(line.split("MOE ", 1)[1])
    assert rep["world_size"] == 2 and rep["recoveries"] == []
    ref = moe.reference_moe_run(np.zeros(48), 0, 10, tokens=24,
                                n_experts=6, expert_dim=8,
                                capacity_factor=0.9, seed=3)
    assert np.array(rep["w"], np.float64).tobytes() == ref.tobytes()
    # cross-rank reconciliation: token ranges partition the batch, so
    # per-rank counters must SUM to the global plan totals
    per_rank = [json.loads(ln.split("MOERANK ", 1)[1].split(" ", 1)[1])
                for ln in r.stdout.splitlines()
                if "MOERANK " in ln]
    assert len(per_rank) == 2
    kept = dropped = 0
    for s in range(10):
        p = moe.plan_step(s, 24, 6, 2, 0.9, seed=3)
        kept += len(p.kept)
        dropped += len(p.dropped)
    assert sum(d["dispatched"] for d in per_rank) == kept
    assert sum(d["dropped"] for d in per_rank) == dropped


def test_moe_chaos_kill_reshards_over_survivors(tmp_path):
    """The elastic acceptance: kill an expert-heavy rank mid-train;
    recovery shrinks, the survivors re-shard the expert table among
    themselves (ownership is recomputed from the live comm — no extra
    code path), and the finished run is bit-exact to the oracle."""
    conf = {"steps": 12, "ckpt_dir": str(tmp_path / "ckpt"),
            "n_experts": 6, "expert_dim": 8, "tokens_per_step": 24,
            "ckpt_every": 4, "seed": 3}
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--enable-recovery",
           "--mca", "otpu_chaos_spec", "kill:rank=2,step=5",
           sys.executable, "-m", "ompi_tpu.parallel.moe",
           json.dumps(conf)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=_tpurun_env())
    line = next((ln for ln in r.stdout.splitlines()
                 if "MOE " in ln), None)
    assert line is not None, r.stdout + r.stderr
    rep = json.loads(line.split("MOE ", 1)[1])
    assert rep["world_size"] == 2, rep
    assert len(rep["recoveries"]) == 1
    rec = rep["recoveries"][0]
    assert rec["failed"] == [2]
    assert "shrink_ms" in rec and "restore_ms" in rec
    # rank 0's expert slice under the SHRUNKEN world: re-sharded from
    # the 3-way split [0,2) to the 2-way split [0,3)
    assert rep["experts"] == list(partition(0, 2, 6)) == [0, 3]
    ref = moe.reference_moe_run(np.zeros(48), 0, 12, tokens=24,
                                n_experts=6, expert_dim=8, seed=3)
    assert np.array(rep["w"], np.float64).tobytes() == ref.tobytes()


def test_moe_critical_path_blames_hot_expert_rank(tmp_path):
    """The observability acceptance: a designed-imbalanced run
    (hot_expert=5 homes on rank 2 of 3; pacing makes received load
    wall-clock) must have ``otpu_analyze --critical-path`` name the
    hot expert's home rank as bounding >= 90% of steps."""
    from ompi_tpu.tools import otpu_analyze as oa

    tdir = tmp_path / "trace"
    conf = {"steps": 12, "ckpt_dir": str(tmp_path / "ckpt"),
            "n_experts": 6, "expert_dim": 8, "tokens_per_step": 48,
            "capacity_factor": 3.0, "hot_expert": 5, "hot_boost": 0.8,
            "compute_us_per_token": 2000, "ckpt_every": 50, "seed": 0}
    assert partition(2, 3, 6) == (4, 6)      # expert 5 homes on rank 2
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
           "--mca", "otpu_trace_enable", "1",
           "--mca", "otpu_trace_dir", str(tdir),
           sys.executable, "-m", "ompi_tpu.parallel.moe",
           json.dumps(conf)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300, cwd=REPO, env=_tpurun_env())
    assert any("MOE " in ln for ln in r.stdout.splitlines()), \
        r.stdout + r.stderr
    events, profiles, meta = oa.load_run([str(tdir)])
    rep = oa.analyze(events, profiles=profiles, meta=meta,
                     critical_path=True)
    cp = rep["critical_path"]
    assert len(cp["steps"]) >= 10, cp
    assert cp["bound_by"]["rank"] == 2, cp["bound_by"]
    assert cp["bound_by"]["fraction"] >= 0.90, cp["bound_by"]


# --------------------------------------- device tier ('expert' axis)

def test_device_moe_dryrun_bit_stable():
    """The expert-sharded FFN over the ('expert',) mesh axis composed
    with dp: compiles under shard_map (check_vma), descends, and two
    fresh builds produce byte-identical loss curves."""
    import jax

    if len(jax.devices()) != 8:
        pytest.skip("needs 8 virtual devices")
    losses = moe.run_moe_training_step(steps=3)
    assert len(losses) == 3
    assert losses[-1] < losses[0]


def test_moe_param_specs_shard_experts_only():
    from jax.sharding import PartitionSpec as P

    spec = moe.MeshSpec(dp=2, ep=4)
    specs = moe.moe_param_specs(P, spec)
    assert specs["wr"] == P(None, None)
    assert specs["we1"] == P("expert", None, None)
    assert specs["we2"] == P("expert", None, None)
    # ep=1 collapses to fully-replicated (no 'expert' axis in the mesh)
    flat = moe.moe_param_specs(P, moe.MeshSpec(dp=2))
    assert flat["we1"] == P(None, None, None)


# ----------------------------------------- quantized dispatch (PR 15)

def test_dispatch_codec_roundtrip_band():
    rng = np.random.default_rng(9)
    x = rng.standard_normal((3, 5, 512)).astype(np.float32)
    y = np.asarray(moe.encode_dispatch_int8(x))
    assert y.shape == (3, 5, 512 // 4 + 128)
    back = np.asarray(moe.decode_dispatch_int8(y, 512))
    # per-128-block absmax/127 scales: error <= scale/2 per element
    blocks = x.reshape(3, 5, 4, 128)
    bound = (np.abs(blocks).max(axis=-1, keepdims=True) / 127.0) \
        * 0.5 + 1e-7
    assert (np.abs((back.reshape(3, 5, 4, 128) - blocks)) <=
            bound).all()
    with pytest.raises(ValueError):
        moe.encode_dispatch_int8(np.zeros((2, 100), np.float32))


def test_quant_dispatch_tolerance_acceptance():
    """Int8 dispatch through the REAL ragged device kernel stays
    inside the int8 accuracy band (the PR 15 contract on the
    alltoallv slot)."""
    rep = moe.run_quant_dispatch_check(nranks=4, sizes=(1 << 14,))
    assert rep and all(r <= 1.0 / 127 for r in rep.values()), rep


def test_dispatch_tokens_budget_gated():
    """``dispatch_tokens`` engages the int8 codec ONLY under an
    explicit ``otpu_quant_budget`` admitting it, decodes within band,
    and falls back to raw f32 for widths the packer cannot block."""
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import spc

    rt.reset_for_testing()
    w = ompi_tpu.init()
    try:
        if w.size != 8:
            pytest.skip("needs 8 virtual devices")
        n, R, W = 8, 4, 512
        rng = np.random.default_rng(21)
        x = rng.standard_normal((n, n, R, W)).astype(np.float32)
        counts = rng.integers(0, R + 1, (n, n)).astype(np.int32)
        counts[2] = 0           # a rank that sends nothing
        counts[:, 6] = 0        # a rank that receives nothing
        outs, codec = moe.dispatch_tokens(w, x, counts)
        assert codec is None    # no budget, no codec
        np.testing.assert_array_equal(
            np.asarray(outs[0][3]), x[3, 0, :int(counts[3, 0])])
        w.info.set("otpu_quant_budget", "0.02")
        enc0 = spc.read("quant_encodes")
        outs, codec = moe.dispatch_tokens(w, x, counts)
        assert codec == "int8"
        assert spc.read("quant_encodes") - enc0 == n * n
        atol = float(np.abs(x).max()) / 127.0
        for i in range(n):
            for j in range(n):
                c = int(counts[j][i])
                blk = np.asarray(outs[i][j])
                assert blk.shape == (c, W)
                np.testing.assert_allclose(blk, x[j, i, :c],
                                           atol=atol)
        assert all(np.asarray(b).shape[0] == 0 for b in outs[6])
        # width not blockable by the 128-lane packer: raw fallback
        thin = rng.standard_normal((n, n, R, 128)).astype(np.float32)
        _outs, codec = moe.dispatch_tokens(w, thin, counts)
        assert codec is None
    finally:
        w.info.delete("otpu_quant_budget")
        rt.reset_for_testing()


# ------------------------------------- fused device ladder (coll/tuned)

def test_expert_ffn_fused_matches_unfused():
    """The coll/tuned DEVICE ladder: the fused matmul+allreduce cell
    and the unfused einsum contraction agree, and the one force-var
    governs the device tier ('off' disables the cells)."""
    import jax
    from ompi_tpu.base.var import registry
    from ompi_tpu.mca.coll import tuned

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 virtual devices")
    from jax.sharding import Mesh

    mesh = Mesh(np.array(devs[:4]), ("expert",))
    rng = np.random.default_rng(3)
    a = rng.standard_normal((4, 8, 16)).astype(np.float32)
    b = rng.standard_normal((4, 16, 8)).astype(np.float32)
    assert tuned.device_cell("matmul_allreduce") is not None
    fused = np.asarray(moe.expert_ffn_fused(a, b, mesh))
    try:
        registry.set("otpu_coll_tuned_fused_cells", "off")
        assert tuned.device_cell("matmul_allreduce") is None
        unfused = np.asarray(moe.expert_ffn_fused(a, b, mesh))
        # forcing the OTHER cell also disables this one
        registry.set("otpu_coll_tuned_fused_cells",
                     "matmul_reduce_scatter")
        assert tuned.device_cell("matmul_allreduce") is None
        assert tuned.device_cell("matmul_reduce_scatter") is not None
    finally:
        registry.set("otpu_coll_tuned_fused_cells", "")
    ref = np.einsum("nmk,nko->mo", a, b)
    np.testing.assert_allclose(fused, ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(unfused, ref, rtol=2e-4, atol=2e-4)
    with pytest.raises(KeyError):
        tuned.device_cell("bogus_cell")


# ------------------------------------------ expert-sharded serving

@pytest.fixture(scope="module")
def world():
    from ompi_tpu.mca.part import part_framework
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    part_framework().open()
    yield w
    rt.reset_for_testing()


def test_blocking_probe_raises_on_peer_failure(world):
    """The FT hole the MoE dispatch exposed: coll/basic's alltoallv
    probes each peer before sizing the recv, and a BLOCKING probe is
    not a posted request — ``_peer_failed`` cannot complete it in
    error, so without a liveness poll in the pml loop the survivors of
    a chaos kill spin in ``progress()`` forever.  ULFM semantics: a
    probe naming a failed source raises ERR_PROC_FAILED."""
    from ompi_tpu.api.errors import ProcFailedError
    from ompi_tpu.ft import state as ft_state

    c0 = world.as_rank(0)
    res = {}

    def _probe():
        try:
            c0.probe(source=7, tag=333)      # nobody ever sends this
        except MpiError as exc:
            res["exc"] = exc

    th = threading.Thread(target=_probe, daemon=True)
    th.start()
    time.sleep(0.2)                  # the probe is inside its spin loop
    w7 = c0.group.world_rank(7)
    ft_state.mark_failed(w7)
    try:
        th.join(timeout=30)
        assert not th.is_alive(), "blocking probe hung past peer death"
        assert isinstance(res.get("exc"), ProcFailedError), res
    finally:
        ft_state._failed.discard(w7)  # don't poison the module world


def test_router_expert_affinity_and_prefix_priority(world):
    """Routing order on an expert-sharded pool: prefix-cache hit wins
    (a hit skips the prefill outright), else the request's expert home
    rank, else least-loaded; rebind re-shards the table."""
    from ompi_tpu.serving import prefix_cache
    from ompi_tpu.serving.router import Router
    from ompi_tpu.serving.scheduler import ServeRequest

    reg = prefix_cache.PrefixRegistry()
    router = Router(world.as_rank(0), workers=[1, 2, 3],
                    prefix_registry=reg, experts=6)
    table = router.expert_table()
    assert sorted(table) == list(range(6))
    assert set(table.values()) == {1, 2, 3}
    # expert_of is pure content hashing — no Python hash() anywhere
    # (one full prefix block long, so the registry can hold its hash)
    prompt = [(5 * i + 3) % 97 for i in range(prefix_cache.block_size())]
    req = ServeRequest(len(prompt), 4, rid=101, prompt=prompt)
    e = router.expert_of(req)
    assert e == router.expert_of(req)
    pre, dec, extra = router._stage_split()
    router._assign(req, dec, extra, pre)
    assert req.worker == table[e]
    # a registered prefix on a DIFFERENT worker beats the expert home
    other = next(w for w in (1, 2, 3) if w != table[e])
    hashes = prefix_cache.block_hashes(prompt)
    reg.insert(hashes, other, generation=1)
    req2 = ServeRequest(len(prompt), 4, rid=102, prompt=prompt)
    router._assign(req2, dec, extra, pre)
    assert req2.worker == other
    # rebind to a shrunken pool: the table re-covers ALL experts over
    # the survivors (contiguous partition slices, the trainer's rule)
    router.rebind(world.as_rank(0), [1, 2])
    t2 = router.expert_table()
    assert sorted(t2) == list(range(6))
    assert set(t2.values()) == {1, 2}


def test_fleet_expert_sharded_pool_end_to_end(world):
    """Fleet pool with ``experts=``: fresh admissions land on their
    expert's home worker, completions are bit-exact, and stats publish
    the expert → worker table."""
    import threading

    from ompi_tpu.serving import FleetController, PoolSpec, ShardWorker
    from ompi_tpu.serving.worker import toy_token

    workers = [ShardWorker(world.as_rank(r), router=0) for r in (1, 2)]
    threads = [threading.Thread(target=wk.serve, daemon=True)
               for wk in workers]
    for t in threads:
        t.start()
    fleet = FleetController(world.as_rank(0), pools=[
        PoolSpec("m_moe", [1, 2], max_batch=4, max_batch_tokens=4096,
                 experts=4)])
    router = fleet.routers["m_moe"]
    table = router.expert_table()
    assert sorted(table) == [0, 1, 2, 3]
    assert set(table.values()) == {1, 2}
    prompts = [[i, 3 * i + 1, 7] for i in range(8)]
    reqs = [fleet.submit("t0", "m_moe", prompt_len=len(p),
                         max_new_tokens=2, prompt=p, rid=200 + i)
            for i, p in enumerate(prompts)]
    homes = {r.rid: table[router.expert_of(r)] for r in reqs}
    deadline = time.monotonic() + 60
    while len(fleet.completed()) < len(reqs):
        fleet.tick()
        assert time.monotonic() < deadline, "fleet did not drain"
        time.sleep(0.002)
    st = fleet.stats()
    fleet.shutdown()
    for t in threads:
        t.join(timeout=10)
    for req in fleet.completed():
        assert req.worker == homes[req.rid], (req.rid, req.worker)
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]
    assert st["pools"]["m_moe"]["experts"] == \
        {str(e): w for e, w in table.items()}


# ------------------------------------------------- bench pins (--moe)

def test_moe_bench_pins_fresh():
    """The committed `bench.py --moe` sweep rows stay inside the pinned
    bands: throughputs get the wide CI-host noise band (the serving-pin
    discipline), but the load-imbalance factor is a pure function of
    the seeded gating plan, so it must match the pin EXACTLY — a drift
    there is a gating change, not noise."""
    with open(os.path.join(REPO, "tests", "bench_pins.json")) as f:
        pins = json.load(f)["moe"]
    with open(os.path.join(REPO, "BENCH_SWEEP.json")) as f:
        sweep = json.load(f)
    rows = {r["coll"]: r for r in sweep.get("results", [])
            if str(r.get("coll", "")).startswith("moe_")}
    assert set(rows) == {"moe_host_n2", "moe_dense_n2"}, sorted(rows)
    for row in rows.values():
        assert row.get("ok"), row
    assert rows["moe_host_n2"]["imbalance"] == pins["imbalance"]
    assert rows["moe_host_n2"]["dropped"] == 0
    assert rows["moe_host_n2"]["tokens_per_s"] >= \
        0.25 * pins["host_tokens_per_s"]
    assert rows["moe_dense_n2"]["tokens_per_s"] >= \
        0.25 * pins["dense_tokens_per_s"]
