"""Send modes (ssend/bsend/rsend) + persistent p2p requests.

Reference: ``ompi/mpi/c/{ssend,bsend,rsend,send_init,recv_init}.c`` and
the pml's per-mode protocol choice (MCA_PML_BASE_SEND_SYNCHRONOUS etc.,
``pml_ob1_isend.c``).
"""
import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api import buffer as bsend_buf
from ompi_tpu.api.errors import MpiError
from ompi_tpu.api.request import startall, waitall


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()
    bsend_buf.reset_for_testing()


class TestSsend:
    def test_issend_completes_only_on_match(self, world):
        s, r = world.as_rank(0), world.as_rank(1)
        req = s.issend(np.array([3.14]), dest=1, tag=9)
        # progress a while: must NOT complete before the recv is posted
        from ompi_tpu.runtime.progress import progress

        for _ in range(50):
            progress()
        assert not req.complete_flag
        buf = np.zeros(1)
        rr = r.irecv(buf, source=0, tag=9)
        req.wait()
        rr.wait()
        assert buf[0] == 3.14

    def test_blocking_ssend(self, world):
        s, r = world.as_rank(2), world.as_rank(3)
        buf = np.zeros(2)
        rr = r.irecv(buf, source=2, tag=4)
        s.ssend(np.array([1.0, 2.0]), dest=3, tag=4)
        rr.wait()
        assert buf.tolist() == [1.0, 2.0]


class TestBsend:
    def test_requires_attach(self, world):
        bsend_buf.reset_for_testing()
        with pytest.raises(MpiError):
            world.as_rank(0).bsend(np.array([1.0]), dest=1, tag=1)

    def test_bsend_roundtrip_and_capacity(self, world):
        bsend_buf.attach(1 << 16)
        try:
            s, r = world.as_rank(4), world.as_rank(5)
            msg = np.arange(16.0)
            s.bsend(msg, dest=5, tag=7)
            msg[:] = -1           # caller may clobber after return
            buf = np.zeros(16)
            r.recv(buf, source=4, tag=7)
            assert buf.tolist() == list(range(16))
            # exhausting the buffer raises ERR_BUFFER
            with pytest.raises(MpiError):
                s.bsend(np.zeros(1 << 16, np.uint8), dest=5, tag=8)
        finally:
            bsend_buf.detach()

    def test_detach_returns_buffer(self, world):
        arr = np.zeros(4096, np.uint8)
        bsend_buf.attach(arr)
        assert bsend_buf.detach() is arr


class TestPersistent:
    def test_send_recv_init_restartable(self, world):
        s, r = world.as_rank(6), world.as_rank(7)
        src = np.zeros(1)
        dst = np.zeros(1)
        sreq = s.send_init(src, dest=7, tag=11)
        rreq = r.recv_init(dst, source=6, tag=11)
        for i in range(3):
            src[0] = 10.0 + i
            startall([sreq, rreq])
            waitall([sreq, rreq])
            assert dst[0] == 10.0 + i
        # inactive between starts: wait on inactive is an error-free no-op
        # but start-while-active raises
        startall([rreq])
        with pytest.raises(MpiError):
            rreq.start()
        src[0] = 99.0
        sreq.start()
        waitall([sreq, rreq])
        assert dst[0] == 99.0

    def test_ssend_init(self, world):
        s, r = world.as_rank(0), world.as_rank(2)
        dst = np.zeros(1)
        sreq = s.ssend_init(np.array([5.0]), dest=2, tag=21)
        sreq.start()
        from ompi_tpu.runtime.progress import progress

        for _ in range(50):
            progress()
        assert not sreq.complete_flag     # sync: needs the match
        rr = r.irecv(dst, source=0, tag=21)
        sreq.wait()
        rr.wait()
        assert dst[0] == 5.0
