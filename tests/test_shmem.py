"""OpenSHMEM-style PGAS layer: symmetric heap, put/get, atomics, scoll."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_symmetric_heap_allocator():
    """memheap invariant: collective allocs give identical offsets, and
    free+coalesce reclaims the space."""
    from ompi_tpu.shmem import _Shmem

    heap = _Shmem.__new__(_Shmem)
    heap.heap_bytes = 1 << 12
    heap.free_list = [(0, 1 << 12)]
    a = heap.alloc(100)
    b = heap.alloc(200)
    assert a != b and a % 16 == 0 and b % 16 == 0
    heap.release(a, 100)
    heap.release(b, 200)
    c = heap.alloc(1 << 12 - 1)   # coalesced space serves a big block
    assert c == 0


def test_pgas_ring_example():
    r = _tpurun(4, [sys.executable, str(REPO / "examples" / "pgas_ring.py")])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pgas ring OK: 4 PEs, counter 10" in r.stdout


def test_shmem_put_get_atomics_colls(tmp_path):
    script = tmp_path / "shmem_all.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu.shmem as shmem
        shmem.init()
        me, n = shmem.my_pe(), shmem.n_pes()

        x = shmem.array(4, np.float64)
        x.local[:] = me * 10.0
        shmem.barrier_all()

        # get from right neighbor
        got = shmem.get(x, 4, (me + 1) % n)
        assert got.tolist() == [((me + 1) % n) * 10.0] * 4, got
        shmem.barrier_all()   # everyone done reading before anyone writes

        # put into left neighbor's second element
        shmem.p(x, 500.0 + me, (me - 1) % n, index=1)
        shmem.barrier_all()
        assert x.local[1] == 500.0 + (me + 1) % n, x.local

        # typed atomics on a shared int64 counter at PE 0
        c = shmem.array(1, np.int64)
        c.local[0] = 0
        shmem.barrier_all()
        old = shmem.atomic_fetch_add(c, 1, 0)
        assert 0 <= old < n
        shmem.barrier_all()
        if me == 0:
            assert c.local[0] == n, c.local

        # compare-and-swap: exactly one PE wins the election slot
        e = shmem.array(1, np.int64)
        e.local[0] = -1
        shmem.barrier_all()
        prev = shmem.atomic_compare_swap(e, -1, me, 0)
        shmem.barrier_all()
        winner = int(shmem.g(e, 0))
        assert 0 <= winner < n
        got_it = (prev == -1)
        wins = np.asarray(shmem._get().world.allgather(
            np.array([1 if got_it else 0], np.int64)))
        assert wins.sum() == 1, wins

        # scoll: reductions + collect
        y = shmem.array(2, np.float64)
        y.local[:] = [me + 1.0, me * 2.0]
        shmem.sum_to_all(y)
        assert y.local[0] == n * (n + 1) / 2
        z = shmem.array(1, np.int64)
        z.local[0] = me * me
        coll = shmem.collect(z)
        assert coll.tolist() == [i * i for i in range(n)], coll
        # broadcast
        b = shmem.array(3, np.float64)
        b.local[:] = me
        shmem.broadcast(b, root=2)
        assert b.local.tolist() == [2.0, 2.0, 2.0]

        shmem.barrier_all()
        print(f"shmem OK pe {me}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("shmem OK") == 4


def test_shmem_sync_locks_strided(tmp_path):
    """wait_until/test, distributed locks, iput/iget, nbi, alltoall,
    bitwise/prod reductions (shmem_lock.c / shmem_iput / wait_until)."""
    script = tmp_path / "shmem_sync.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu.shmem as shmem
        shmem.init()
        me, n = shmem.my_pe(), shmem.n_pes()

        # wait_until: PE0 signals each peer's flag word in turn
        f = shmem.array(1, np.int64)
        f.local[0] = 0
        shmem.barrier_all()
        if me == 0:
            for pe in range(1, n):
                shmem.p(f, pe * 7, pe)
            shmem.quiet()
        else:
            shmem.wait_until(f, shmem.CMP_EQ, me * 7)
            assert not shmem.test(f, shmem.CMP_NE, me * 7)

        # distributed lock protects a read-modify-write on PE 0
        lock = shmem.array(1, np.int64)
        tot = shmem.array(1, np.int64)
        lock.local[0] = 0
        tot.local[0] = 0
        shmem.barrier_all()
        for _ in range(3):
            shmem.set_lock(lock)
            v = int(shmem.g(tot, 0))
            shmem.p(tot, v + 1, 0)
            shmem.quiet()
            shmem.clear_lock(lock)
        shmem.barrier_all()
        if me == 0:
            assert tot.local[0] == 3 * n, tot.local
            # free lock: try-acquire succeeds; a second try fails until
            # the holder clears it
            assert shmem.test_lock(lock) is True
            assert shmem.test_lock(lock) is False
            shmem.clear_lock(lock)
        shmem.barrier_all()

        # strided iput/iget: write every 2nd slot of the right neighbor
        s = shmem.array(8, np.float64)
        s.local[:] = -1.0
        shmem.barrier_all()
        shmem.iput(s, np.array([me, me, me, me], float), tst=2, sst=1,
                   count=4, pe=(me + 1) % n)
        shmem.barrier_all()
        left = (me - 1) % n
        assert s.local[::2].tolist() == [left] * 4, s.local
        back = shmem.iget(s, tst=1, sst=2, count=4, pe=me)
        assert back.tolist() == [left] * 4

        # nbi put completes by quiet
        q = shmem.array(1, np.float64)
        q.local[0] = 0
        shmem.barrier_all()
        shmem.put_nbi(q, np.array([me + 1.0]), (me + 1) % n)
        shmem.quiet()
        shmem.barrier_all()
        assert q.local[0] == ((me - 1) % n) + 1.0

        # alltoall + prod/bitwise reductions
        a = shmem.array(n, np.int64)
        a.local[:] = [me * n + j for j in range(n)]
        out = shmem.alltoall(a)
        assert out.tolist() == [j * n + me for j in range(n)], out
        pr = shmem.array(1, np.int64)
        pr.local[0] = me + 1
        shmem.prod_to_all(pr)
        import math
        assert pr.local[0] == math.factorial(n)
        bw = shmem.array(1, np.int64)
        bw.local[0] = 1 << me
        shmem.or_to_all(bw)
        assert bw.local[0] == (1 << n) - 1

        shmem.barrier_all()
        print(f"shmem sync OK pe {me}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("shmem sync OK") == 4


def test_shmem_contexts_bitwise_accessibility(tmp_path):
    """shmem_ctx_* ordering domains, bitwise/set atomics, strided
    alltoalls, pe/addr accessibility, calloc/align/realloc
    (oshmem/include/shmem.h.in:180-207 families)."""
    script = tmp_path / "shmem_new.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu.shmem as shmem

        shmem.init()
        me, n = shmem.my_pe(), shmem.n_pes()

        # -- contexts: independent issue streams, implicit quiet on destroy
        flags = shmem.calloc(1, np.int64)
        shmem.barrier_all()
        ctx = shmem.ctx_create(shmem.Ctx.PRIVATE)
        ctx.atomic_add(flags, 1, pe=0)
        ctx.quiet()
        shmem.barrier_all()
        if me == 0:
            assert flags.local[0] == n, flags.local
        shmem.ctx_destroy(ctx)
        try:
            ctx.put(flags, 1, 0)
            raise SystemExit("destroyed ctx accepted an op")
        except Exception:
            pass
        # default context is always usable
        shmem.CTX_DEFAULT.fence()

        # -- bitwise + set atomics
        bits = shmem.calloc(1, np.int64)
        shmem.barrier_all()
        shmem.atomic_or(bits, 1 << me, pe=0)
        shmem.quiet()
        shmem.barrier_all()
        if me == 0:
            assert bits.local[0] == (1 << n) - 1, bits.local
        shmem.barrier_all()
        old = shmem.atomic_fetch_and(bits, ~(1 << me), pe=0)
        assert old >= 0
        shmem.barrier_all()
        if me == 0:
            assert bits.local[0] == 0, bits.local
        shmem.barrier_all()   # readers finish before the next mutation
        shmem.atomic_set(bits, 7, pe=0)
        shmem.barrier_all()
        if me == 0:
            assert bits.local[0] == 7
        shmem.barrier_all()
        x = shmem.calloc(1, np.int64)
        shmem.barrier_all()
        shmem.atomic_xor(x, me + 1, pe=(me + 1) % n)
        shmem.quiet()
        shmem.barrier_all()
        assert x.local[0] == ((me - 1) % n) + 1, x.local

        # -- strided alltoalls (spec: src index sst*(j*ne+k))
        ne, sst, dst = 2, 2, 3
        a = shmem.array(dst * n * ne, np.int64)
        a.local[:] = -1
        a.local[: sst * n * ne : sst] = [
            me * 100 + v for v in range(n * ne)]
        shmem.barrier_all()
        got = shmem.alltoalls(a, dst=dst, sst=sst, nelems=ne)
        want = []
        for j in range(n):
            want += [j * 100 + me * ne, j * 100 + me * ne + 1]
        assert got.tolist() == want, (got.tolist(), want)
        assert a.local[: dst * n * ne : dst].tolist() == want

        # -- accessibility + ptr
        assert shmem.pe_accessible(me) and shmem.pe_accessible(0)
        assert not shmem.pe_accessible(n) and not shmem.pe_accessible(-1)
        assert shmem.addr_accessible(a, (me + 1) % n)
        ptr = shmem.shmem_ptr(a, me)
        assert ptr is not None and ptr[0] == a.local[0]

        # -- allocation variants
        c = shmem.calloc(8, np.float32)
        assert c.local.tolist() == [0.0] * 8
        al = shmem.align(256, 4, np.float64)
        assert al.offset % 256 == 0
        al.local[:] = me
        r = shmem.realloc(al, 8)
        assert r.count == 8 and r.local[:4].tolist() == [me] * 4

        print(f"SHMEM NEW OK {me}", flush=True)
        shmem.finalize()
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.stdout.count("SHMEM NEW OK") == 4, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_shmem_global_exit(tmp_path):
    """shmem_global_exit terminates every PE with the given status."""
    script = tmp_path / "gexit.py"
    script.write_text(textwrap.dedent("""
        import time
        import ompi_tpu.shmem as shmem

        shmem.init()
        shmem.barrier_all()
        if shmem.my_pe() == 1:
            shmem.global_exit(3)
        time.sleep(30)   # never reached on any PE if global_exit works
        print("SURVIVED", flush=True)
    """))
    r = _tpurun(3, [sys.executable, str(script)], timeout=60)
    assert "SURVIVED" not in r.stdout, r.stdout + r.stderr
    assert r.returncode != 0


def test_shmem_active_set_barrier_sync_info(tmp_path):
    """shmem_barrier/sync over a (PE_start, logPE_stride, PE_size)
    active set + the info/version and deprecated cache no-op surface."""
    script = tmp_path / "aset.py"
    script.write_text("""
import numpy as np
import ompi_tpu.shmem as sh

sh.init()
me, n = sh.my_pe(), sh.n_pes()
assert sh.info_get_version()[0] >= 1
assert "shmem" in sh.info_get_name()
sh.set_cache_inv(); sh.udcflush(); sh.clear_cache_line_inv(0)

flag = sh.array(4, np.int64)
flag.local[:] = 0
# active set = even PEs (stride 2^1): they barrier among themselves
# while odd PEs only make the collective split calls
evens = list(range(0, n, 2))
if me in evens:
    sh.p(flag, me + 1, me, index=me)
    sh.barrier(0, 1, len(evens))     # quiet + subset barrier
    sh.barrier(0, 1, len(evens))     # repeat: cached comm, no re-split
    # after the subset barrier every even PE sees every even PE's put
    for pe in evens:
        got = sh.g(flag, pe, index=pe)
        assert got == pe + 1, (me, pe, got)
else:
    pass   # odd PEs NEVER call: create_group is non-collective over
           # the world — the OpenSHMEM active-set contract
sh.sync_all()
sh.sync(0, 0, n)                     # whole-world active set
sh.barrier()                         # default = all PEs
sh.finalize()
print("aset ok", flush=True)
""")
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("aset ok") == 4
