"""ompi_tpu/serving/fleet — the multi-tenant serving platform.

Coverage layers:

* fair-share admission (pure scheduler): weighted round-robin across
  tenants with the checkable no-starvation invariant — a burst tenant
  cannot starve a light one, weights are respected, per-tenant FIFO
  holds;
* autoscaler policy units (fake fleet, no comm): PER-POOL cooldown
  (the regression: pool A absorbing its scale-up must not block pool
  B's needed spawn) and the per-pool max-workers cap;
* the fleet in-process end to end (router + worker threads over
  ``as_rank``): two pools, two tenants, prefix-cache hits actually
  skipping prefill, per-tenant percentile isolation, idle retirement
  into the reserve and a p99-SLO (telemetry-driven) re-enlist recorded
  in the otpu-trace ring;
* multiprocess under tpurun: THE chaos-armed soak — sustained mixed
  Poisson load across 2 models/tenants with a worker chaos-killed
  mid-load, zero dropped requests, prefix hit-rate > 0 with a
  measurable prefill-count delta, and at least one autoscale decision
  driven by a telemetry sample (p99 from the coord-KV sample, NOT
  queue depth) spawning a real replacement via ``dpm.spawn`` into the
  pool pset (bounded tier-1 run; the full-length version rides the
  ``slow`` lane).
"""
import os
import subprocess
import sys
import textwrap
import threading

import pytest

import ompi_tpu
from ompi_tpu.api.errors import MpiError
from ompi_tpu.serving.scheduler import (ContinuousBatchScheduler,
                                        ServeRequest)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), script_args=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script), *script_args]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


# ----------------------------------------------------- fair-share admission

def test_fair_share_burst_cannot_starve():
    """One tenant floods 50 requests; the other trickles 5.  The light
    tenant's requests must land within the first WRR cycles (never
    starve), the weights must shape the admitted mix, and the
    scheduler's own cross-tenant invariant must hold every tick."""
    s = ContinuousBatchScheduler(max_batch=2, max_batch_tokens=10000,
                                 tenants={"burst": 3, "light": 1})
    for _ in range(50):
        s.submit(ServeRequest(5, 5, tenant="burst"))
    for _ in range(5):
        s.submit(ServeRequest(5, 5, tenant="light"))
    admitted = []
    for _ in range(200):
        a, _e = s.tick()
        admitted.extend(r.tenant for r in a)
        s.check_invariants()
        for r in s.running():
            s.mark_done(r)
        if not s.depth() and not s.running():
            break
    assert not s.depth() and not s.running()
    assert "light" in admitted[:8], admitted[:8]
    head = admitted[:20]
    # 3:1 weights while both backlogged (light exhausts after 5)
    assert head.count("burst") == 15 and head.count("light") == 5, head


def test_fair_share_per_tenant_fifo_and_dynamic_tenant():
    s = ContinuousBatchScheduler(max_batch=4, max_batch_tokens=10000,
                                 tenants={"a": 1})
    r1 = s.submit(ServeRequest(4, 4, tenant="a"))
    # a tenant first seen at submit time joins with weight 1
    r2 = s.submit(ServeRequest(4, 4, tenant="newcomer"))
    r3 = s.submit(ServeRequest(4, 4, tenant="a"))
    a, _ = s.tick()
    s.check_invariants()
    assert {r.rid for r in a} == {r1.rid, r2.rid, r3.rid}
    assert s.tenant_depths() == {"": 0, "a": 0, "newcomer": 0}
    # per-tenant FIFO: within tenant a, r1 admitted before r3
    ia = [r.rid for r in a if r.tenant == "a"]
    assert ia == [r1.rid, r3.rid]


def test_fair_share_invariant_trips_on_violation():
    """The invariant checker must actually detect starvation — feed a
    poisoned admission log and expect the assertion."""
    s = ContinuousBatchScheduler(max_batch=2, max_batch_tokens=10000,
                                 tenants={"a": 1, "b": 1})
    with s._slock:
        for _ in range(10):     # "a" admitted 10x while b backlogged
            s._admit_log.append(("a", ("b",)))
    with pytest.raises(AssertionError, match="passed over"):
        s.check_invariants()


def test_tenant_weight_must_be_positive():
    with pytest.raises(MpiError):
        ContinuousBatchScheduler(tenants={"a": 0})


# ------------------------------------------------- autoscaler policy units

class _FakeSched:
    def __init__(self):
        self.queued = 0

    def stats(self):
        return {"queued": self.queued, "running": 0}

    def depth(self):
        return self.queued


class _FakeRouter:
    def __init__(self, workers):
        self.workers = list(workers)
        self.sched = _FakeSched()
        self.registry = None


class _FakeRte:
    client = None


class _FakeComm:
    rte = _FakeRte()


class _FakeFleet:
    """Just enough fleet for FleetAutoscaler: routers, capacity hooks,
    decision log."""

    def __init__(self):
        self.routers = {"a": _FakeRouter([1]), "b": _FakeRouter([2])}
        self.comm = _FakeComm()
        self.enlisted = []
        self.retired = []
        self.decisions = []

    def enlist(self, pool):
        self.enlisted.append(pool)
        self.routers[pool].workers.append(99)
        return 99

    def spawn_into(self, pool, n=1):
        return []

    def retire(self, pool):
        self.retired.append(pool)
        w = self.routers[pool].workers.pop()
        return w

    def note_decision(self, d):
        self.decisions.append(d)


def test_autoscale_cooldown_is_per_pool():
    """THE regression: with pool A cooling after its scale-up, pool
    B's burst must still trigger B's spawn — a single global cooldown
    timer would block it."""
    from ompi_tpu.serving.fleet import FleetAutoscaler

    fleet = _FakeFleet()
    a = FleetAutoscaler(fleet, depth_high=0, patience=1, cooldown=10,
                        poll_ticks=1, slo_p99_ms=0.0,
                        watch_stale=False, idle_patience=10**9)
    fleet.routers["a"].sched.queued = 5          # only A is deep
    a.step()
    assert fleet.enlisted == ["a"]
    assert a._cooling["a"] == 10, "A must now cool down"
    fleet.routers["a"].sched.queued = 0
    fleet.routers["b"].sched.queued = 5          # B gets deep LATER
    a.step()
    assert fleet.enlisted == ["a", "b"], \
        "pool A's cooldown blocked pool B's needed scale-up"
    # and A, still cooling, does not double-scale even if deep again
    fleet.routers["a"].sched.queued = 9
    a.step()
    assert fleet.enlisted == ["a", "b"]


def test_autoscale_max_workers_cap_is_per_pool():
    from ompi_tpu.serving.fleet import FleetAutoscaler

    fleet = _FakeFleet()
    a = FleetAutoscaler(fleet, depth_high=0, patience=1, cooldown=0,
                        poll_ticks=1, slo_p99_ms=0.0,
                        watch_stale=False, idle_patience=10**9,
                        max_workers={"a": 1, "b": 3})
    fleet.routers["a"].sched.queued = 5
    fleet.routers["b"].sched.queued = 5
    a.step()
    assert fleet.enlisted == ["b"], \
        "pool A is at its cap; only B may scale"


def test_autoscale_idle_retirement():
    from ompi_tpu.serving.fleet import FleetAutoscaler

    fleet = _FakeFleet()
    fleet.routers["a"].workers = [1, 5]
    a = FleetAutoscaler(fleet, depth_high=None, poll_ticks=1,
                        slo_p99_ms=0.0, watch_stale=False,
                        idle_patience=3, cooldown=4, min_workers=1)
    for _ in range(3):
        a.step()
    assert fleet.retired == ["a"], "idle pool A should drain one rank"
    # pool B sits at min_workers: never retired below the floor
    for _ in range(10):
        a.step()
    assert fleet.retired.count("b") == 0
    assert a.stats()["downs"] == 1


# ------------------------------------------------------------ in-process env

@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    from ompi_tpu.mca.part import part_framework

    part_framework().open()
    yield w
    rt.reset_for_testing()


def _run_workers(workers):
    threads = [threading.Thread(target=wk.serve, daemon=True)
               for wk in workers]
    for t in threads:
        t.start()
    return threads


def test_fleet_two_pools_two_tenants_end_to_end(world):
    """Two model pools + two weighted tenants under mixed Poisson
    load: every token bit-exact, per-tenant percentiles isolated,
    prefix-cache hits measurably skipping prefill."""
    from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                                  PoolSpec, ShardWorker)
    from ompi_tpu.serving.worker import toy_token

    workers = [ShardWorker(world.as_rank(r), router=0)
               for r in (1, 2, 3, 4)]
    threads = _run_workers(workers)
    fleet = FleetController(world.as_rank(0), pools=[
        PoolSpec("m_a", [1, 2], max_batch=4, max_batch_tokens=4096),
        PoolSpec("m_b", [3, 4], max_batch=4, max_batch_tokens=4096),
    ], tenants={"ten_a": 2, "ten_b": 1})
    drv = MixedPoissonDriver({
        "ten_a": dict(model="m_a", rate_rps=600, n_requests=16,
                      prompt_lens=(4, 24), decode_lens=(2, 8),
                      prefixes=2, prefix_len=32),
        "ten_b": dict(model="m_b", rate_rps=400, n_requests=12,
                      prompt_lens=(4, 24), decode_lens=(2, 8),
                      prefixes=1, prefix_len=16),
    }, seed=7)
    rep = drv.run(fleet, max_wall_s=90, check_invariants=True)
    fleet.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert rep["requests"] == 28
    for req in fleet.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]
    # per-tenant report: separate populations, sane estimator bands
    for name in ("ten_a", "ten_b"):
        tr = rep["tenants"][name]
        assert tr["requests"] == (16 if name == "ten_a" else 12)
        assert tr["p50_ms"] > 0 and tr["p99_ms"] > 0
        assert tr["p99_ms"] <= tr["p99_exact_ms"] * 2.0 + 1.0
        assert tr["p99_exact_ms"] <= tr["p99_ms"] * 2.0 + 1.0
    # prefix-cache evidence: hits happened AND skipped prefill passes
    assert rep["prefix_hits"] > 0
    assert rep["prefills"] + rep["prefix_hits"] >= 28
    assert rep["prefills"] < 28, \
        "every request prefilled — the cache skipped nothing"
    st = fleet.stats()
    assert st["pools"]["m_a"]["prefix"]["hits"] > 0
    assert st["pools"]["m_a"]["workers"] == 2


def test_fleet_per_tenant_hist_reset_isolation(world):
    """Per-tenant percentile populations must not merge across runs:
    poison the tenant family with an absurd sample, re-run, and the
    reported p99 must reflect only the fresh run."""
    from ompi_tpu.runtime import trace
    from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                                  PoolSpec, ShardWorker)
    from ompi_tpu.serving.router import TENANT_HIST_PREFIX

    workers = [ShardWorker(world.as_rank(r), router=0) for r in (1,)]
    threads = _run_workers(workers)
    fleet = FleetController(world.as_rank(0),
                            pools=[PoolSpec("m_x", [1])],
                            tenants={"t0": 1})
    # poison: one 100-second sample in t0's family
    trace.hist_record(TENANT_HIST_PREFIX + "t0", 32, int(100e9))
    drv = MixedPoissonDriver({
        "t0": dict(model="m_x", rate_rps=500, n_requests=8,
                   prompt_lens=(4, 8), decode_lens=(2, 4))}, seed=2)
    rep = drv.run(fleet, max_wall_s=60)
    fleet.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert rep["tenants"]["t0"]["p99_ms"] < 50_000, \
        "poisoned pre-run sample leaked into the tenant's percentiles"


def test_fleet_autoscaler_telemetry_decision_in_trace(world):
    """Idle retirement parks a rank in the reserve; a p99-SLO breach —
    read from a telemetry SAMPLE, not queue depth — re-enlists it, and
    the decision lands in the otpu-trace ring naming the signal."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import trace
    from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                                  PoolSpec, ShardWorker)

    workers = [ShardWorker(world.as_rank(r), router=0)
               for r in (1, 2, 3)]
    threads = _run_workers(workers)
    fleet = FleetController(
        world.as_rank(0),
        pools=[PoolSpec("m_a", [1, 2], max_batch=4,
                        max_batch_tokens=4096),
               PoolSpec("m_b", [3], max_batch=4,
                        max_batch_tokens=4096)],
        tenants={"ten_a": 1},
        autoscale=dict(poll_ticks=2, idle_patience=3, cooldown=4,
                       slo_p99_ms=0.0001, min_workers=1,
                       watch_stale=False))
    # idle ticks: pool A drains one rank into the reserve
    for _ in range(30):
        fleet.tick()
    assert fleet.stats()["reserve"] >= 1
    assert len(fleet.routers["m_a"].workers) == 1
    # loaded run under an absurd SLO: the p99 signal must re-enlist
    was = trace.enabled
    if not was:
        registry.set("otpu_trace_enable", True)
    try:
        drv = MixedPoissonDriver({
            "ten_a": dict(model="m_a", rate_rps=2000, n_requests=30,
                          prompt_lens=(8, 16), decode_lens=(4, 8))},
            seed=1)
        drv.run(fleet, max_wall_s=60)
        ups = [d for d in fleet.stats()["decisions"]
               if d["dir"] == "up"]
        assert any(d["signal"] == "p99" for d in ups), ups
        ring = [e[6] for e in trace._ring if e is not None
                and e[1] == "fleet_scale"]
        assert any(d.get("signal") == "p99" and d.get("dir") == "up"
                   for d in ring), \
            "no telemetry-driven decision in the trace ring"
        assert len(fleet.routers["m_a"].workers) == 2, \
            "the reserve rank was not re-enlisted"
    finally:
        fleet.shutdown()
        for t in threads:
            t.join(timeout=10)
        if not was:
            registry.set("otpu_trace_enable", False)


def test_fleet_rejects_bad_pools(world):
    from ompi_tpu.serving import FleetController, PoolSpec

    with pytest.raises(MpiError, match="shares workers"):
        FleetController(world.as_rank(0),
                        pools=[PoolSpec("a", [1, 2]),
                               PoolSpec("b", [2, 3])])
    with pytest.raises(MpiError, match="at least one pool"):
        FleetController(world.as_rank(0), pools=[])
    with pytest.raises(MpiError, match="at least one worker"):
        PoolSpec("a", [])
    with pytest.raises(MpiError, match="given together"):
        PoolSpec("a", [1, 2], prefill=[1])
    fleet = FleetController(world.as_rank(0),
                            pools=[PoolSpec("a", [1])])
    with pytest.raises(MpiError, match="no serving pool"):
        fleet.submit("t", "nope", prompt_len=4, max_new_tokens=2)


def test_fleet_stages_pool_sized_independently(world):
    """A disaggregated pool with 1 prefill feeding 2 decode ranks:
    the prefill rank holds one slab pairing per decode peer and every
    token still verifies."""
    from ompi_tpu.serving import (FleetController, PoolSpec,
                                  ShardWorker)
    from ompi_tpu.serving.worker import toy_token

    pre = ShardWorker(world.as_rank(1), router=0, role="prefill",
                      peer=[2, 3], slots=4, kv_elems=32)
    dec1 = ShardWorker(world.as_rank(2), router=0, role="decode",
                       peer=1, slots=4, kv_elems=32)
    dec2 = ShardWorker(world.as_rank(3), router=0, role="decode",
                       peer=1, slots=4, kv_elems=32)
    threads = _run_workers([pre, dec1, dec2])
    fleet = FleetController(world.as_rank(0), pools=[
        PoolSpec("m_s", [1, 2, 3], prefill=[1], decode=[2, 3],
                 max_batch=2, max_batch_tokens=4096, slots=4,
                 decode_chunk=2, kv_elems=32)])
    for i in range(8):
        fleet.submit("", "m_s", prompt_len=4 + i, max_new_tokens=3)
    done = fleet.serve_until_drained(max_ticks=5000)
    fleet.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 8
    assert {q.worker for q in done} == {2, 3}, \
        "both decode ranks must take work"
    for req in done:
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


def test_retire_is_stage_aware(world):
    """Scale-down must never wedge a stage pool: colocated extras
    leave first, and the last prefill / last decode rank is
    untouchable even when the pool still has several workers."""
    from ompi_tpu.serving import FleetController, PoolSpec

    fleet = FleetController(world.as_rank(0), pools=[
        PoolSpec("m_s", [1, 2, 3], prefill=[1], decode=[2])])
    assert fleet.retire("m_s") == 3, "the colocated extra goes first"
    assert fleet.retire("m_s") is None, \
        "the last prefill/decode ranks must be protected"
    assert fleet.routers["m_s"].workers == [1, 2]
    # a wider decode pool may shrink — newest decode rank first
    fleet2 = FleetController(world.as_rank(0), pools=[
        PoolSpec("m_t", [4, 5, 6], prefill=[4], decode=[5, 6])])
    assert fleet2.retire("m_t") == 6
    assert fleet2.retire("m_t") is None


def test_mixed_driver_drives_bare_router(world):
    """MixedPoissonDriver's documented bare-Router mode: same driver,
    no fleet controller."""
    from ompi_tpu.serving import (MixedPoissonDriver, Router,
                                  ShardWorker)
    from ompi_tpu.serving.worker import toy_token

    wk = ShardWorker(world.as_rank(7), router=0)
    threads = _run_workers([wk])
    router = Router(world.as_rank(0), workers=[7], decode_chunk=4)
    rep = MixedPoissonDriver({
        "solo": dict(model="", rate_rps=500, n_requests=6,
                     prompt_lens=(4, 8), decode_lens=(2, 4))},
        seed=9).run(router, max_wall_s=60)
    router.shutdown()
    for t in threads:
        t.join(timeout=10)
    assert rep["requests"] == 6
    assert rep["tenants"]["solo"]["requests"] == 6
    for req in router.completed():
        assert req.tokens == [toy_token(req.rid, i)
                              for i in range(req.max_new_tokens)]


# ------------------------------------------------------------- multiprocess

_SOAK = """
import sys

import ompi_tpu
from ompi_tpu.runtime import trace
from ompi_tpu.serving import (FleetController, MixedPoissonDriver,
                              ShardWorker)
from ompi_tpu.serving.worker import toy_token

N_A, N_B = int(sys.argv[1]), int(sys.argv[2])
w = ompi_tpu.init()
if w.rank == 0:
    # pools resolve from the tpurun --pool psets (no explicit specs)
    fleet = FleetController(
        w, tenants={"ten_a": 2, "ten_b": 1},
        spawn_argv=[sys.executable, "-m", "ompi_tpu.serving.worker"],
        autoscale=dict(poll_ticks=2, depth_high=None, cooldown=25,
                       slo_p99_ms=2.0, max_workers=3,
                       idle_patience=10**9))
    assert fleet.pool_workers() == {"m_a": [1, 2], "m_b": [3, 4]}, \\
        fleet.pool_workers()
    drv = MixedPoissonDriver({
        "ten_a": dict(model="m_a", rate_rps=300, n_requests=N_A,
                      prompt_lens=(4, 16), decode_lens=(4, 10),
                      prefixes=2, prefix_len=32),
        "ten_b": dict(model="m_b", rate_rps=200, n_requests=N_B,
                      prompt_lens=(4, 16), decode_lens=(4, 10),
                      prefixes=1, prefix_len=16),
    }, seed=3)
    rep = drv.run(fleet, max_wall_s=150)
    total = N_A + N_B
    # zero dropped: every admitted request completed, bit-exactly
    assert rep["requests"] == total, (rep["requests"], total)
    assert len({q.rid for q in fleet.completed()}) == total
    for q in fleet.completed():
        assert q.tokens == [toy_token(q.rid, i)
                            for i in range(q.max_new_tokens)], q
    assert rep["requeued"] > 0, "victim died, nothing requeued"
    # prefix cache: hits happened and measurably skipped prefills
    assert rep["prefix_hits"] > 0, rep
    assert rep["prefills"] < total, rep
    # at least one autoscale decision came from a TELEMETRY sample
    # (p99 / stale_rank), not queue depth — and reached the trace ring
    ring = [e[6] for e in trace._ring if e is not None
            and e[1] == "fleet_scale"]
    assert any(d.get("dir") == "up"
               and d.get("signal") in ("p99", "stale_rank")
               for d in ring), ring
    st = fleet.stats()
    assert st["autoscale"]["ups"] >= 1
    fleet.shutdown()
    import json
    print("SOAK OK " + json.dumps(
        {"requeued": rep["requeued"], "hits": rep["prefix_hits"],
         "prefills": rep["prefills"],
         "ups": st["autoscale"]["ups"]}), flush=True)
else:
    if w.rank == 2:
        from ompi_tpu.ft import chaos
        chaos.install_spec("kill:rank=2,site=serve_work,count=2")
    ShardWorker(w, router=0).serve()
    print(f"WORKER {w.rank} DONE", flush=True)
"""


def _soak(tmp_path, n_a, n_b, timeout):
    script = tmp_path / "fleet_soak.py"
    script.write_text(_SOAK)
    return _tpurun(
        5, script,
        extra=("--enable-recovery", "--pool", "m_a:1,2",
               "--pool", "m_b:3,4",
               "--mca", "otpu_telemetry_interval_ms", "50"),
        script_args=(str(n_a), str(n_b)),
        timeout=timeout)


def test_fleet_chaos_soak_bounded(tmp_path):
    """THE acceptance scenario (bounded): mixed two-tenant Poisson
    load over two --pool pools while a worker is chaos-killed
    mid-load; zero dropped requests, prefix hit-rate > 0 with a
    prefill-count delta, and a telemetry-driven (p99) scale decision
    spawning a replacement via dpm.spawn into the pool."""
    r = _soak(tmp_path, 24, 16, timeout=300)
    assert "SOAK OK" in r.stdout, r.stdout + r.stderr


@pytest.mark.slow
def test_fleet_chaos_soak_full(tmp_path):
    """The full-length soak: same invariants, 4x the load."""
    r = _soak(tmp_path, 96, 64, timeout=480)
    assert "SOAK OK" in r.stdout, r.stdout + r.stderr


def test_tpurun_pool_psets_resolve(tmp_path):
    """--pool publishes mpi://serving/pool/<model> and
    pool_specs_from_psets resolves the tables from it."""
    script = tmp_path / "pools.py"
    script.write_text(textwrap.dedent("""
        import ompi_tpu
        from ompi_tpu.serving import pool_specs_from_psets

        w = ompi_tpu.init()
        specs = {s.name: s.workers for s in pool_specs_from_psets(w)}
        assert specs == {"left": [1], "right": [2, 3]}, specs
        print(f"POOLS OK {w.rank}", flush=True)
    """))
    r = _tpurun(4, script, extra=("--pool", "left:1",
                                  "--pool", "right:2-3"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("POOLS OK") == 4


def test_otpu_info_serving_surface():
    """otpu_info --serving lists the registry-enumerated serving vars
    (and works under --parsable, matching --telemetry/--profile)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_info", "--serving"],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr
    for var in ("otpu_serving_prefix_block", "otpu_serving_slo_p99_ms",
                "otpu_serving_scale_cooldown", "otpu_serving_slo_window_s",
                "otpu_trace_requests"):
        assert var in out.stdout, var
    # the otpu-req surfaces: SLO telemetry key and the registry-
    # enumerated request/SLO SPC counters
    assert "serving telemetry key slo" in out.stdout
    for ctr in ("req_traced", "req_stages", "slo_goodput",
                "slo_breaches"):
        assert f"serving counter {ctr}" in out.stdout, ctr
    # the front-door surfaces: admission vars, the speculative window,
    # the frontdoor telemetry key, and the shed/preempt/spec counters
    for var in ("otpu_serving_fd_queue_cap", "otpu_serving_fd_rate_rps",
                "otpu_serving_fd_hold_ticks", "otpu_serving_spec_k"):
        assert var in out.stdout, var
    assert "serving telemetry key frontdoor" in out.stdout
    for ctr in ("serve_shed", "serve_preempt", "serve_spec_accepts",
                "serve_spec_rejects"):
        assert f"serving counter {ctr}" in out.stdout, ctr
    par = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_info", "--serving",
         "--parsable"],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=env)
    assert par.returncode == 0
    assert any(ln.startswith("serving var otpu_serving_prefix_block:")
               for ln in par.stdout.splitlines()), par.stdout

# ---------------------------------------- coord recovery budget (the flake)

def test_coord_recovery_budget_resolution():
    """The documented fleet-soak flake fix: RPCs inside
    ``recovery_scope()`` take the recovery retry/timeout budget
    (``otpu_coord_recovery_retry_max`` / ``_rpc_timeout``), scopes
    nest, the budget never SHORTENS a raised steady-state ladder, and
    everything reverts when the outermost scope exits."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.rte import coord

    c = coord.CoordClient.__new__(coord.CoordClient)
    c._retry_max = 2
    c._recovery_depth = 0
    c._rpc_timeout = 1.5
    assert c._effective_retry_max() == 2
    assert c._effective_rpc_timeout() == 1.5
    with c.recovery_scope():
        assert c._effective_retry_max() == 24       # the var default
        with c.recovery_scope():                    # scopes nest
            assert c._effective_retry_max() == 24
        assert c._effective_retry_max() == 24       # outer still open
        # recovery never shortens a caller-raised steady-state ladder
        c._retry_max = 100
        assert c._effective_retry_max() == 100
        # the rpc timeout inherits steady state until the var is set
        assert c._effective_rpc_timeout() == 1.5
        registry.set("otpu_coord_recovery_rpc_timeout", 9.0)
        try:
            assert c._effective_rpc_timeout() == 9.0
        finally:
            registry.set("otpu_coord_recovery_rpc_timeout", 0.0)
    assert c._recovery_depth == 0
    assert c._effective_retry_max() == 100
    assert c._effective_rpc_timeout() == 1.5


def test_coord_recovery_scope_survives_reconnect_burst():
    """Behavioral pin against a hostile server: with the steady-state
    ladder (retries=1) a burst of connection kills exhausts the budget
    and raises; the SAME burst inside ``recovery_scope()`` is absorbed
    by the recovery budget and the RPC completes."""
    import socket

    from ompi_tpu.rte import coord

    kills = {"n": 0}
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    addr = srv.getsockname()

    def _conn(conn):
        try:
            while True:
                req = coord._recv_frame(conn)
                if kills["n"] > 0:
                    # swallow the request, reset the connection — the
                    # client sees a ConnectionError and walks its
                    # reconnect ladder
                    kills["n"] -= 1
                    conn.close()
                    return
                coord._send_frame(conn, {"ok": True, "value": None,
                                         "_rid": req.get("_rid")})
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _accept():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=_accept, daemon=True).start()
    try:
        c = coord.CoordClient(addr=addr, retries=1)
        c.put(0, "warm", 1)                  # the happy path works
        kills["n"] = 3
        with pytest.raises((ConnectionError, OSError)):
            c.put(0, "k", 2)                 # steady-state ladder: 1
        kills["n"] = 3                       # retry, then exhausted
        with c.recovery_scope():
            c.put(0, "k", 3)                 # recovery budget: 24
        assert kills["n"] == 0, "recovery path never hit the server"
        c.put(0, "after", 4)                 # steady state restored
        assert c._recovery_depth == 0
    finally:
        srv.close()


def test_agreement_wraps_coord_in_recovery_scope():
    """agree_kv's coord traffic rides the client's recovery scope when
    one exists — and degrades to a no-op context for bare test fakes
    (the shrink path must not demand the full client surface)."""
    import contextlib

    from ompi_tpu.ft import agreement

    class _Client:
        entered = 0

        @contextlib.contextmanager
        def recovery_scope(self):
            _Client.entered += 1
            try:
                yield self
            finally:
                _Client.entered -= 1

    cl = _Client()
    with agreement._recovery_scope(cl):
        assert _Client.entered == 1
    assert _Client.entered == 0
    # a fake without the method gets nullcontext, not AttributeError
    with agreement._recovery_scope(object()):
        pass
