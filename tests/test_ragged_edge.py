"""Ragged-collective edge cases ahead of MoE dispatch traffic: zero-count
contributions, ranks receiving nothing, empty slabs, and single-member
communicators must round-trip without the caller special-casing —
fuzzed count matrices over the host (``comm.alltoallv``) and device
(``*v_array`` / ``ops.pallas_collectives``) paths."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import ompi_tpu

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) != 8:
        pytest.skip("needs 8 virtual devices")
    return Mesh(np.array(devs), ("x",))


def _check_a2av(mesh, x, counts):
    from ompi_tpu.ops import pallas_collectives as pc

    out = np.asarray(pc.all_to_all_v(x, counts, mesh, "x"))
    assert out.shape == x.shape
    n = x.shape[0]
    for i in range(n):
        for j in range(n):
            c = int(counts[i, j])
            np.testing.assert_array_equal(out[j, i, :c], x[i, j, :c],
                                          err_msg=f"pair {i}->{j}")


def test_device_a2av_fuzzed_count_matrices(mesh):
    """Seeded fuzz over count matrices with forced degenerate rows and
    columns: a rank that contributes nothing (all-zero row) and a rank
    that receives nothing (all-zero column) must round-trip like any
    other raggedness — no special-casing at the call site."""
    n, R, W = 8, 11, 128
    rng = np.random.default_rng(1234)
    for trial in range(4):
        x = rng.standard_normal((n, n, R, W)).astype(np.float32)
        counts = rng.integers(0, R + 1, (n, n)).astype(np.int32)
        counts[int(rng.integers(n))] = 0        # sends nothing
        counts[:, int(rng.integers(n))] = 0     # receives nothing
        _check_a2av(mesh, x, counts)


def test_device_a2av_all_zero_counts(mesh):
    n, R, W = 8, 5, 128
    x = np.random.default_rng(0).standard_normal(
        (n, n, R, W)).astype(np.float32)
    _check_a2av(mesh, x, np.zeros((n, n), np.int32))


def test_device_a2av_empty_slab(mesh):
    """R == 0: every count clamps to zero valid rows and the exchange
    degenerates to a shape-preserving no-op (regression: building a
    zero-row kernel used to fail in interpret-mode DMA discharge)."""
    from ompi_tpu.ops import pallas_collectives as pc

    n, W = 8, 128
    x = np.zeros((n, n, 0, W), np.float32)
    out = np.asarray(pc.all_to_all_v(x, np.zeros((n, n), np.int32),
                                     mesh, "x"))
    assert out.shape == (n, n, 0, W)
    # malformed counts still surface on the degenerate path
    with pytest.raises(ValueError, match="counts"):
        pc.all_to_all_v(x, np.zeros((n,), np.int32), mesh, "x")


def test_device_agv_fuzzed_counts_and_empty_slab(mesh):
    from ompi_tpu.ops import pallas_collectives as pc

    n, R, W = 8, 9, 128
    rng = np.random.default_rng(99)
    for trial in range(4):
        x = rng.standard_normal((n, R, W)).astype(np.float32)
        counts = rng.integers(0, R + 1, n).astype(np.int32)
        counts[int(rng.integers(n))] = 0        # contributes nothing
        out = np.asarray(pc.all_gather_v(x, counts, mesh, "x"))
        for i in range(n):
            c = int(counts[i])
            np.testing.assert_array_equal(out[i, :c], x[i, :c])
    # R == 0 slab (regression: zero-row kernel build)
    empty = np.zeros((n, 0, W), np.float32)
    out = np.asarray(pc.all_gather_v(empty, np.zeros(n, np.int32),
                                     mesh, "x"))
    assert out.shape == (n, 0, W)
    with pytest.raises(ValueError, match="counts"):
        pc.all_gather_v(empty, np.zeros((n, 2), np.int32), mesh, "x")


def test_device_single_member_mesh_roundtrip():
    """n == 1 communicator: ragged exchange is the identity, including
    on an empty slab."""
    import jax
    from jax.sharding import Mesh

    from ompi_tpu.ops import pallas_collectives as pc

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("x",))
    x = np.arange(3 * 128, dtype=np.float32).reshape(1, 1, 3, 128)
    out = np.asarray(pc.all_to_all_v(x, np.array([[2]], np.int32),
                                     mesh1, "x"))
    np.testing.assert_array_equal(out[0, 0, :2], x[0, 0, :2])
    g = np.asarray(pc.all_gather_v(x[0], np.array([2], np.int32),
                                   mesh1, "x"))
    np.testing.assert_array_equal(g[0, :2], x[0, 0, :2])
    e = np.asarray(pc.all_to_all_v(np.zeros((1, 1, 0, 128), np.float32),
                                   np.zeros((1, 1), np.int32),
                                   mesh1, "x"))
    assert e.shape == (1, 1, 0, 128)


def test_component_alltoallv_array_zero_rows_and_cols():
    """The in-process device-comm path (``comm.alltoallv_array``)
    returns correctly-typed zero-length views for zero-count cells."""
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    try:
        if w.size != 8:
            pytest.skip("needs 8 virtual devices")
        n, R, W = 8, 6, 128
        rng = np.random.default_rng(7)
        host = rng.standard_normal((n, n, R, W)).astype(np.float32)
        counts = rng.integers(0, R + 1, (n, n))
        counts[3] = 0       # rank 3 sends nothing
        counts[:, 5] = 0    # rank 5 receives nothing
        outs = w.alltoallv_array(host, counts)
        for i in range(n):
            for j in range(n):
                blk = np.asarray(outs[i][j])
                c = int(counts[j][i])
                assert blk.shape[0] == c, (i, j)
                np.testing.assert_array_equal(blk, host[j, i, :c])
        assert all(np.asarray(b).shape[0] == 0 for b in outs[5])
    finally:
        rt.reset_for_testing()


def test_host_alltoallv_self_comm_zero_and_empty():
    """Single-member host communicator (coll/self): alltoallv returns
    the send buffer unchanged, including a zero-length one."""
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    ompi_tpu.init()
    try:
        s = ompi_tpu.COMM_SELF
        blk = np.arange(4, dtype=np.float32)
        out = s.alltoallv([blk])
        np.testing.assert_array_equal(np.asarray(out[0]), blk)
        out0 = s.alltoallv([np.zeros(0, np.float32)])
        assert np.asarray(out0[0]).shape == (0,)
    finally:
        rt.reset_for_testing()


def test_mp_host_alltoallv_zero_count_cells(tmp_path):
    """Multi-process host path (btl wire + probe/recv): forced
    zero-count cells — one rank sends nothing to anyone, another
    receives nothing from anyone — round-trip typed and exact."""
    script = tmp_path / "a2av_zero.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu

        ompi_tpu.init()
        w = ompi_tpu.COMM_WORLD
        me, n = w.rank, w.size
        rng = np.random.default_rng(11)          # same plan on every rank
        base = rng.standard_normal((n, n, 24))
        cnts = rng.integers(0, 24, (n, n))
        cnts[1] = 0        # rank 1 sends nothing
        cnts[:, 2] = 0     # rank 2 receives nothing
        send = [base[me, j, : cnts[me][j]].astype(np.float32)
                for j in range(n)]
        got = w.alltoallv(send)
        for src in range(n):
            blk = np.asarray(got[src])
            assert blk.dtype == np.float32, (src, blk.dtype)
            assert blk.shape[0] == cnts[src][me], (src, blk.shape)
            assert np.allclose(blk, base[src, me, : cnts[src][me]]
                               .astype(np.float32)), src
        if me == 2:
            assert all(np.asarray(b).shape[0] == 0 for b in got)
        # allgatherv with a zero contribution from rank 0
        gcnt = [0 if r == 0 else 5 for r in range(n)]
        gout = w.allgatherv(base[me, 0, : gcnt[me]].astype(np.float32))
        for r in range(n):
            g = np.asarray(gout[r]).view(np.float32)
            assert g.shape[0] == gcnt[r], (r, g.shape)
            assert np.allclose(g, base[r, 0, : gcnt[r]]
                               .astype(np.float32)), r
        w.barrier()
        if me == 0:
            print("RAGGED ZERO OK")
        ompi_tpu.finalize()
    """))
    env = dict(os.environ, JAX_PLATFORMS="cpu", OTPU_SANITIZE="1")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "3",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=180, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-1500:]
    assert "RAGGED ZERO OK" in r.stdout
