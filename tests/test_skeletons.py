"""Teaching/skeleton components: btl/template and coll/demo.

Reference model: ``opal/mca/btl/template`` + ``ompi/mca/coll/demo`` —
buildable fakes exercising the framework plumbing (SURVEY §4's
"skeleton components serve as buildable fakes for framework testing").
"""
import numpy as np
import pytest

from ompi_tpu.base.var import registry


@pytest.fixture
def fresh_runtime():
    from ompi_tpu.base import mca
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    mca.reset_for_testing()
    yield
    rt.reset_for_testing()
    mca.reset_for_testing()


def test_template_btl_disabled_by_default(fresh_runtime):
    from ompi_tpu.base import mca

    fw = mca.framework("btl", multi_select=True)
    fw.open()
    names = [c.name for c in fw.available]
    assert "template" not in names      # open() returns False unless enabled
    assert "self" in names


def test_template_btl_enabled_loopback(fresh_runtime):
    from ompi_tpu.base import mca
    from ompi_tpu.mca.btl.base import Frag
    from ompi_tpu.mca.btl.template import COMPONENT as tpl

    fw = mca.framework("btl", multi_select=True)
    fw.discover()
    registry.set("otpu_btl_template_enable", True)
    try:
        fw.open()
        assert tpl in fw.available

        class FakeRte:
            my_world_rank = 0
            is_device_world = False

        got = []
        tpl.set_recv_callback(got.append)
        tpl.setup(FakeRte())
        ep = tpl.reachable(0, FakeRte())
        assert ep is not None and tpl.reachable(1, FakeRte()) is None
        frag = Frag(0, 0, 0, 7, 0, 0, b"hi")
        tpl.send(ep, frag)
        assert got == []                # nothing until progress runs
        assert tpl.progress() == 1
        assert got and got[0].tag == 7
        tpl.close()
    finally:
        registry.set("otpu_btl_template_enable", False)


def test_coll_demo_interposes(fresh_runtime):
    import ompi_tpu
    from ompi_tpu.base import mca

    fw = mca.framework("coll", multi_select=True)
    fw.discover()
    fw.components["demo"].register_vars(fw)   # vars exist before open
    registry.set("otpu_coll_demo_priority", 100)
    try:
        w = ompi_tpu.init()
        # demo's comm_enable re-pointed the vtable slots at wrappers
        assert getattr(w.c_coll["allreduce"], "_demo_wrapped", False)
        # still correct through the wrapper (device world: rank-stacked)
        out = np.asarray(w.allreduce(np.ones((w.size, 1))))
        assert float(np.ravel(out)[0]) == w.size
    finally:
        registry.set("otpu_coll_demo_priority", -1)


def test_coll_demo_absent_by_default(fresh_runtime):
    import ompi_tpu

    w = ompi_tpu.init()
    assert not getattr(w.c_coll["allreduce"], "_demo_wrapped", False)


def test_template_pml_disabled_by_default(fresh_runtime):
    from ompi_tpu.base import mca

    fw = mca.framework("pml")
    fw.open()
    names = [c.name for c in fw.available]
    assert "template" not in names      # opt-in only, like pml/example
    assert "ob1" in names
    assert fw.select().name == "ob1"    # never outranks the real pml


def test_template_pml_enabled_loopback(fresh_runtime):
    from ompi_tpu.base import mca
    from ompi_tpu.mca.pml.template import COMPONENT as tpl

    fw = mca.framework("pml")
    fw.discover()
    registry.set("otpu_pml_template_enable", True)
    try:
        fw.open()
        assert tpl in fw.available

        class FakeComm:
            cid = 0
            rank = 0

        pml = tpl.get_module(rte=None)
        comm = FakeComm()
        pml.add_comm(comm)
        data = np.arange(6, dtype=np.float32)
        pml.send(comm, data, dest=0, tag=9)
        out = np.zeros(6, np.float32)
        st = pml.recv(comm, out, source=-1, tag=-1)   # wildcards match
        assert (st.source, st.tag) == (0, 9)
        np.testing.assert_array_equal(out, data)
        with pytest.raises(RuntimeError):
            pml.isend(comm, data, dest=1, tag=0)      # loopback only
        pml.finalize()
    finally:
        registry.set("otpu_pml_template_enable", False)
