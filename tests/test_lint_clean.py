"""The self-clean CI gate: otpu-lint over the whole package must report
zero non-baselined violations, inside the tier-1 time budget.

The baseline (``lint_suppressions.txt`` at the repo root) may only carry
justified, per-entry-commented exceptions — and only ones that still
fire: unused entries fail the gate, so the file can only shrink.
"""
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BASELINE = REPO / "lint_suppressions.txt"


def test_package_is_lint_clean_in_budget():
    """In-process gate: every pass (the PR 6 five + the otpu-verify
    interprocedural three) over every package file, < 20s — the shared
    AST cache keeps eight passes at one parse per file, and the shared
    call graph keeps the interprocedural passes at one resolve per
    call.  On a blown budget the per-pass breakdown names the slow
    pass."""
    from ompi_tpu import analysis

    sup = analysis.Suppressions.load(str(BASELINE))
    t0 = time.monotonic()
    res = analysis.lint([str(REPO / "ompi_tpu")], suppressions=sup)
    elapsed = time.monotonic() - t0
    assert res.passes == 8
    assert res.files > 100          # the whole package, not a subtree
    assert not res.errors, [f.format() for f in res.errors]
    assert not res.findings, "\n".join(f.format() for f in res.findings)
    assert not sup.unused(), [
        f"{BASELINE}:{e.line_no} suppresses nothing — remove it"
        for e in sup.unused()]
    assert elapsed < 20.0, (
        f"lint took {elapsed:.1f}s (budget 20s) — per-pass breakdown:\n"
        + res.format_timings())
    # the breakdown itself is always well-formed (one row per pass)
    assert len(res.timings) == res.passes
    assert all(t >= 0 for _n, t in res.timings)


def test_baseline_entries_are_justified():
    """Every baseline entry carries a comment: either trailing on the
    line or in the comment block immediately above it."""
    lines = BASELINE.read_text().splitlines()
    for i, raw in enumerate(lines):
        code = raw.split("#", 1)[0].strip()
        if not code:
            continue
        has_trailing = "#" in raw
        has_block_above = i > 0 and lines[i - 1].strip().startswith("#")
        assert has_trailing or has_block_above, (
            f"{BASELINE}:{i + 1}: suppression {code!r} has no "
            "justification comment")


def test_acceptance_command_exits_zero():
    """The exact acceptance-criteria invocation, from the repo root."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint", "ompi_tpu/"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stdout
