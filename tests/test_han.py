"""coll/han — hierarchical two-level collectives.

Host path: tpurun --fake-nodes partitions ranks into emulated nodes so the
low/up sub-comm composition is exercised on one host (the reference tests
han under ``mpirun --oversubscribe`` the same way).  Device path: the
('dcn', 'ici') 2-D mesh composition on the 8-device CPU mesh
(VERDICT round-1 item #3: 2x4 split).
"""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_han_symmetric_two_nodes(tmp_path):
    """4 ranks on 2 fake nodes: han selects and every composition is
    correct, including the reduce_scatter/allreduce/allgather fast path."""
    script = tmp_path / "han_sym.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        r = w.rank
        mod = w.c_coll['allreduce'].__self__
        assert type(mod).__name__ == 'HanModule', type(mod).__name__

        # symmetric fast path: 8 elems / low size 2 divides evenly
        out = w.allreduce(np.arange(8, dtype=np.float64) + r)
        assert np.allclose(out, 4 * np.arange(8) + 6.0), out
        # leader path: odd length not divisible by low size
        out = w.allreduce(np.ones(7) * (r + 1))
        assert np.allclose(out, 10.0), out
        # MAX reduction through the hierarchy
        out = w.allreduce(np.array([float(r)]), ompi_tpu.MAX)
        assert out[0] == 3.0, out

        # bcast from a NON-leader root (rank 1 lives on node 0)
        b = w.bcast(np.array([42.5]) if r == 1 else np.zeros(1), root=1)
        assert b[0] == 42.5
        # bcast from node 1's leader (rank 2)
        b = w.bcast(np.array([7.0, 8.0]) if r == 2 else np.zeros(2), root=2)
        assert b.tolist() == [7.0, 8.0]

        # reduce to a non-leader root on node 1 (rank 3)
        red = w.reduce(np.array([float(r + 1)]), root=3)
        if r == 3:
            assert red[0] == 10.0, red
        else:
            assert red is None

        g = w.allgather(np.array([r * 10], np.int64))
        assert np.asarray(g).ravel().tolist() == [0, 10, 20, 30]

        w.barrier()

        gat = w.gather(np.array([r, r * r], np.int64), root=3)
        if r == 3:
            assert gat.tolist() == [[0, 0], [1, 1], [2, 4], [3, 9]], gat
        else:
            assert gat is None

        stack = np.arange(8, dtype=np.float32).reshape(4, 2) * 100
        sc = w.scatter(stack if r == 1 else np.zeros(2, np.float32), root=1)
        assert sc.tolist() == [r * 2 * 100.0, (r * 2 + 1) * 100.0], sc

        assert w.agree(1) == 1  # served by coll/ftagree, not han

        # slots han doesn't provide fall through to tuned on the same comm
        a2a = w.alltoall(np.arange(4, dtype=np.int64) + 100 * r)
        assert a2a.ravel().tolist() == [r, 100 + r, 200 + r, 300 + r]

        # a split spanning both nodes with 1 rank each: han declines, the
        # tuned ladder owns it
        sub = w.split(0 if r in (0, 3) else 1)
        assert type(sub.c_coll['allreduce'].__self__).__name__ != 'HanModule'
        assert sub.allreduce(np.array([1.0]))[0] == 2.0
        print(f"han symmetric OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)],
                extra=("--fake-nodes", "2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("han symmetric OK") == 4


def test_han_asymmetric_nodes(tmp_path):
    """5 ranks over 2 fake nodes (3+2): the leader-based compositions
    handle unequal node sizes."""
    script = tmp_path / "han_asym.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        r = w.rank
        mod = w.c_coll['allreduce'].__self__
        assert type(mod).__name__ == 'HanModule', type(mod).__name__
        out = w.allreduce(np.full(6, float(r)))
        assert np.allclose(out, 10.0), out
        b = w.bcast(np.array([3.25]) if r == 4 else np.zeros(1), root=4)
        assert b[0] == 3.25
        g = w.allgather(np.array([r + 1], np.int64))
        assert np.asarray(g).ravel().tolist() == [1, 2, 3, 4, 5]
        gat = w.gather(np.array([float(r)]), root=2)
        if r == 2:
            assert gat.ravel().tolist() == [0.0, 1.0, 2.0, 3.0, 4.0]
        sc = w.scatter(np.arange(5., dtype=np.float64).reshape(5, 1) * 3
                       if r == 0 else np.zeros(1), root=0)
        assert sc[0] == r * 3.0
        w.barrier()
        print(f"han asymmetric OK rank {r}")
    """))
    r = _tpurun(5, [sys.executable, str(script)],
                extra=("--fake-nodes", "2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("han asymmetric OK") == 5


def test_han_single_node_declines(tmp_path):
    """Without --fake-nodes every rank shares one node: han must NOT
    select (the reference disqualifies itself the same way)."""
    script = tmp_path / "no_han.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        assert type(w.c_coll['allreduce'].__self__).__name__ != 'HanModule'
        assert w.allreduce(np.ones(1))[0] == 2.0
        print("no-han OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("no-han OK") == 2


def test_device_hierarchical_allreduce():
    """2x4 ('dcn', 'ici') mesh on the 8-device CPU backend: the two-level
    trace-time composition equals a flat global reduction."""
    import jax

    from ompi_tpu.mca.coll.han import XlaHierarchicalColl

    devs = jax.devices()[:8]
    h = XlaHierarchicalColl(devs, n_up=2, n_low=4)

    # divisible inner dim: psum_scatter/psum/all_gather path
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    out = np.asarray(h.allreduce(x))
    assert out.shape == (16,)
    assert np.allclose(out, x.sum(0))

    # non-divisible (1-elem rows): plain two-axis psum path
    y = np.linspace(0, 1, 8, dtype=np.float32).reshape(8)
    out = np.asarray(h.allreduce(y))
    assert np.allclose(out, y.sum())


def test_device_hierarchical_reduce_scatter():
    import jax

    from ompi_tpu.mca.coll.han import XlaHierarchicalColl

    devs = jax.devices()[:8]
    h = XlaHierarchicalColl(devs, n_up=2, n_low=4)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 4)).astype(np.float32)
    out = np.asarray(h.reduce_scatter(x))
    assert out.shape == (8, 4)
    expect = x.sum(0)  # (8, 4): row i belongs to device i
    assert np.allclose(out, expect, atol=1e-5)
