"""Core object tests: group/op/info/status/attributes/request (SURVEY §2.2)."""
import numpy as np
import pytest

from ompi_tpu.api import op as op_mod
from ompi_tpu.api.errors import ErrorClass, MpiError
from ompi_tpu.api.group import GROUP_EMPTY, IDENT, SIMILAR, UNEQUAL, Group
from ompi_tpu.api.info import Info
from ompi_tpu.api.request import (
    CompletedRequest,
    GeneralizedRequest,
    Request,
    waitall,
    waitany,
)
from ompi_tpu.api.request import testall as req_testall
from ompi_tpu.api.request import testany as req_testany
from ompi_tpu.api.status import UNDEFINED, Status
from ompi_tpu.api import attributes as attr
from ompi_tpu.datatype import FLOAT_INT, FLOAT32, contiguous


# -- Group ---------------------------------------------------------------

def test_group_basics():
    g = Group([4, 2, 7])
    assert g.size == 3
    assert g.rank_of(7) == 2
    assert g.rank_of(5) == UNDEFINED
    assert g.world_rank(0) == 4


def test_group_set_ops():
    a, b = Group([0, 1, 2, 3]), Group([2, 3, 4])
    assert a.union(b).world_ranks == (0, 1, 2, 3, 4)
    assert a.intersection(b).world_ranks == (2, 3)
    assert a.difference(b).world_ranks == (0, 1)
    assert a.incl([3, 1]).world_ranks == (3, 1)
    assert a.excl([0, 2]).world_ranks == (1, 3)


def test_group_ranges():
    g = Group(list(range(10)))
    assert g.range_incl([(0, 8, 2)]).world_ranks == (0, 2, 4, 6, 8)
    assert g.range_excl([(0, 8, 2)]).world_ranks == (1, 3, 5, 7, 9)


def test_group_translate_compare():
    a, b = Group([5, 6, 7]), Group([7, 6, 5])
    assert a.translate_ranks([0, 2], b) == [2, 0]
    assert a.compare(b) == SIMILAR
    assert a.compare(Group([5, 6, 7])) == IDENT
    assert a.compare(Group([5, 6])) == UNEQUAL
    assert GROUP_EMPTY.size == 0


def test_group_duplicate_ranks_rejected():
    with pytest.raises(MpiError):
        Group([1, 1])


# -- Op ------------------------------------------------------------------

def test_builtin_ops():
    a = np.array([1, 5, 3], np.int64)
    b = np.array([4, 2, 6], np.int64)
    assert list(op_mod.SUM.reduce_arrays(a, b)) == [5, 7, 9]
    assert list(op_mod.MAX.reduce_arrays(a, b)) == [4, 5, 6]
    assert list(op_mod.MIN.reduce_arrays(a, b)) == [1, 2, 3]
    assert list(op_mod.PROD.reduce_arrays(a, b)) == [4, 10, 18]
    assert list(op_mod.BXOR.reduce_arrays(a, b)) == [5, 7, 5]
    assert list(op_mod.LAND.reduce_arrays(np.array([1, 0]), np.array([1, 1]))) \
        == [1, 0]


def test_maxloc_minloc():
    dt = np.dtype([("v", np.float32), ("i", np.int32)], align=True)
    a = np.array([(3.0, 0), (1.0, 0)], dtype=dt)
    b = np.array([(3.0, 1), (2.0, 1)], dtype=dt)
    r = op_mod.MAXLOC.reduce_arrays(a, b)
    assert r["v"].tolist() == [3.0, 2.0]
    assert r["i"].tolist() == [0, 1]  # tie → lower index
    r2 = op_mod.MINLOC.reduce_arrays(a, b)
    assert r2["v"].tolist() == [3.0, 1.0]
    assert r2["i"].tolist() == [0, 0]


def test_user_op_and_commutativity():
    def fn(invec, inoutvec, dt):
        inoutvec[...] = invec * 2 + inoutvec

    op = op_mod.create(fn, commute=False)
    assert not op.commute
    out = op.reduce_arrays(np.array([1, 2]), np.array([10, 20]))
    assert out.tolist() == [12, 24]


def test_jax_fold_rejects_unloweratable():
    with pytest.raises(MpiError):
        op_mod.jax_fold(op_mod.MAXLOC)


# -- Info / Status / attributes -----------------------------------------

def test_info():
    i = Info()
    i.set("key", "val")
    assert i.get("key") == "val"
    assert i.get_nkeys() == 1
    assert i.get_nthkey(0) == "key"
    d = i.dup()
    i.delete("key")
    assert d.get("key") == "val"
    with pytest.raises(KeyError):
        i.delete("missing")


def test_status_count_semantics():
    dt = contiguous(4, FLOAT32)
    st = Status(_nbytes=32)
    assert st.get_count(dt) == 2
    st2 = Status(_nbytes=30)
    assert st2.get_count(dt) == UNDEFINED
    assert st2.get_elements(dt) == 7


class _Obj(attr.AttributeHost):
    def __repr__(self):
        return "_Obj"


def test_attributes_copy_delete():
    deleted = []
    kv = attr.keyval_create(
        copy_fn=lambda o, k, e, v: (True, v + 1),
        delete_fn=lambda o, k, v, e: deleted.append(v))
    a, b = _Obj(), _Obj()
    a.attr_put(kv, 41)
    assert a.attr_get(kv) == (True, 41)
    a._attrs_copy_to(b)
    assert b.attr_get(kv) == (True, 42)
    a.attr_delete(kv)
    assert deleted == [41]
    assert a.attr_get(kv) == (False, None)
    attr.keyval_free(kv)
    with pytest.raises(KeyError):
        b.attr_put(kv, 0)


# -- Request -------------------------------------------------------------

def test_request_complete_and_wait():
    r = Request()
    assert not r.complete_flag
    r.complete()
    assert r.wait() is r.status
    done, st = r.test()
    assert done


def test_request_error_propagates():
    r = Request()
    r.complete(MpiError(ErrorClass.ERR_TRUNCATE, "too big"))
    with pytest.raises(MpiError) as ei:
        r.wait()
    assert ei.value.error_class is ErrorClass.ERR_TRUNCATE


def test_request_callbacks_fire_once():
    seen = []
    r = Request()
    r.on_complete(lambda req: seen.append(1))
    r.complete()
    r.on_complete(lambda req: seen.append(2))  # late registration fires now
    r.complete()  # idempotent
    assert seen == [1, 2]


def test_waitall_testany():
    rs = [CompletedRequest(), CompletedRequest()]
    assert len(waitall(rs)) == 2
    ok, idx, st = req_testany(rs)
    assert ok and idx == 0
    ok, stats = req_testall(rs)
    assert ok and len(stats) == 2
    i, st = waitany(rs)
    assert i == 0


def test_generalized_request():
    r = GeneralizedRequest(query_fn=lambda st: st.set_elements(FLOAT32, 3))
    assert not r.complete_flag
    r.grequest_complete()
    st = r.wait()
    assert st.get_count(FLOAT32) == 3


class TestThreadAndInterlib:
    """MPI_Init_thread / Query_thread / Is_thread_main + the interlib
    refcount guard (``ompi/interlib/interlib.c``)."""

    def test_init_thread_provided(self):
        import ompi_tpu
        from ompi_tpu.runtime import init as rt

        rt.reset_for_testing()
        try:
            w, provided = ompi_tpu.init_thread(ompi_tpu.THREAD_MULTIPLE)
            assert provided == ompi_tpu.THREAD_MULTIPLE
            assert w.size >= 1
            assert ompi_tpu.query_thread() == ompi_tpu.THREAD_MULTIPLE
            assert ompi_tpu.is_thread_main()
        finally:
            rt.reset_for_testing()

    def test_interlib_blocks_finalize(self):
        import ompi_tpu
        from ompi_tpu.runtime import init as rt
        from ompi_tpu.runtime import interlib

        rt.reset_for_testing()
        try:
            ompi_tpu.init()
            interlib.register(interlib.THREAD_SERIALIZED)
            ompi_tpu.finalize()
            assert ompi_tpu.initialized()      # library still registered
            assert interlib.deregister() == 0
            ompi_tpu.finalize()
            assert ompi_tpu.finalized()
        finally:
            rt.reset_for_testing()


class TestEnvironmentInquiry:
    """MPI environment functions (wtime/version/processor-name/error
    classes) + comm compare/idup (``ompi/mpi/c/*.c`` small families)."""

    def test_wtime_and_friends(self):
        from ompi_tpu.api import env

        t0 = env.wtime()
        assert env.wtime() >= t0
        assert 0 < env.wtick() < 1
        assert env.get_processor_name()
        assert env.get_version() == (4, 0)
        assert "ompi_tpu" in env.get_library_version()
        buf = env.alloc_mem(128)
        assert buf.nbytes == 128
        env.free_mem(buf)

    def test_user_error_classes(self):
        from ompi_tpu.api import errors

        cls = errors.add_error_class()
        code = errors.add_error_code(cls, "my failure mode")
        errors.add_error_string(cls, "my class")
        assert errors.error_string(cls) == "my class"
        assert errors.error_string(code) == "my failure mode"
        assert errors.error_class_of(code) == cls
        assert errors.error_string(errors.ErrorClass.ERR_TRUNCATE) \
            == "ERR_TRUNCATE"

    def test_comm_compare_and_idup(self):
        import ompi_tpu
        from ompi_tpu.runtime import init as rt

        rt.reset_for_testing()
        try:
            w = ompi_tpu.init()
            assert w.compare(w) == w.IDENT
            d = w.dup()
            assert w.compare(d) == w.CONGRUENT
            if w.size > 1:
                sub = w.create_group(
                    ompi_tpu.Group(list(w.group.world_ranks[:1])))
                if sub is not None:
                    assert w.compare(sub) == w.UNEQUAL
            c2, req = w.idup()
            req.wait()
            assert w.compare(c2) == w.CONGRUENT
            c2.free()
            d.free()
        finally:
            rt.reset_for_testing()
