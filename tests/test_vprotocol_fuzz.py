"""Seeded replay fuzz for vprotocol/pessimist (channel event clocks).

Each seed drives a randomized piecewise-deterministic exchange program
(tests/fuzz_replay_worker.py): per-round single- or dual-comm sends
with seed-chosen comms/tags and plan-chosen consumption order, and a
seed-derived kill point for rank 1 (after its sends, or between its two
recvs of a dual round).  Phase A crashes mid-program under full
sender-based logging; phase B replays every rank from the logs and must
reproduce the failure-free recurrence (numpy simulation) to 1e-12 —
any payload mis-pairing across the interleaved channels corrupts the
asymmetric fold immediately.
"""
import importlib.util
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "fuzz_replay_worker.py"

ROUNDS = 6
SEEDS = [3, 14, 27, 42]


def _mod():
    spec = importlib.util.spec_from_file_location("fuzz_replay_worker",
                                                  WORKER)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def _run(env_extra, mca=(), timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    env.update(env_extra)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
           "--enable-recovery"]
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [sys.executable, str(WORKER)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_replay_reproduces_recurrence(seed, tmp_path):
    m = _mod()
    _, kill_round, kill_pos = m.build_plan(seed, ROUNDS)
    logdir = tmp_path / "logs"

    # phase A: crash at the seed-derived point under full logging
    ra = _run({"VPF_SEED": str(seed), "VPF_ROUNDS": str(ROUNDS),
               "VPF_NITER": str(kill_round + 1), "VPF_DIE": "1",
               "VPF_OUT": str(tmp_path / "a")},
              mca=[("vprotocol_pessimist_log", str(logdir)),
                   ("vprotocol_pessimist_log_payloads", "1"),
                   ("ft_detector", "true"),
                   ("ft_detector_period", "0.2"),
                   ("ft_detector_timeout", "1.5")])
    assert not (tmp_path / "a.1.npy").exists(), (
        f"seed {seed}: rank 1 survived its {kill_pos} kill at round "
        f"{kill_round}\n{ra.stdout}{ra.stderr}")

    # phase B: full program, every rank replayed from the logs
    rb = _run({"VPF_SEED": str(seed), "VPF_ROUNDS": str(ROUNDS),
               "VPF_NITER": str(ROUNDS), "VPF_DIE": "0",
               "VPF_OUT": str(tmp_path / "b")},
              mca=[("vprotocol_pessimist_replay", str(logdir))])
    assert rb.returncode == 0, (seed, rb.stdout + rb.stderr)
    assert rb.stdout.count("VPF DONE") == 2, (seed, rb.stdout)

    want = m.simulate(seed, ROUNDS, ROUNDS)
    for r in range(2):
        got = np.load(tmp_path / f"b.{r}.npy")
        np.testing.assert_allclose(got, want[r], rtol=1e-12, err_msg=(
            f"seed {seed} rank {r}: replay diverged from the "
            f"failure-free recurrence (kill was {kill_pos}@"
            f"{kill_round})"))
