"""coll/quant — block-scale quantized collectives and KV slabs.

Covers the acceptance list of ISSUE 15: codec round-trip units (block
boundaries, scale edge cases, cross-process determinism), the
(dtype, size, accuracy_budget) ladder (quant only under an EXPLICIT
budget, never for non-commutative ops, force-vars win), the device
tier (budget-armed comm routes to the pallas encode/dequant-accumulate
programs), the wire tier (>=2x fewer bytes at 4MB over loopback tcp
with the tolerance check passing; corrupt quant frames fail as loudly
as crc32 ones, chaos-armed), the serving KV tier (decode within band,
codec change -> stale hints fall back to full prefill), the tolerance
harness itself, the CPU AOT compile of the codec kernels (the
re-earnable device contract), otpu_info --quant, and the committed
bench-row pins.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import traceback
import zlib
from pathlib import Path

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api import op as op_mod
from ompi_tpu.mca.coll import quant

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


def spmd(comm, fn, timeout=60):
    """One thread per rank over the in-process world (the
    test_coll_algorithms harness)."""
    size = comm.size
    results = [None] * size
    errors = []

    def run(i):
        try:
            results[i] = fn(comm.as_rank(i), i)
        except Exception:
            errors.append((i, traceback.format_exc()))

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not errors, errors[0]
    assert not any(t.is_alive() for t in threads), "spmd rank hung"
    return results


def _mp_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return env


# ----------------------------------------------------------- codec units

def test_codec_roundtrip_bands_and_boundaries():
    rng = np.random.default_rng(0)
    for n in (1, 5, 127, 128, 129, 257, 1000, 4096):
        x = (rng.standard_normal(n) * 10).astype(np.float32)
        for codec in quant.CODECS:
            enc = quant.encode_f32(x, codec, 128)
            assert enc.dtype == np.uint8
            assert enc.nbytes == quant.encoded_nbytes(n, codec, 128)
            dec = quant.decode_f32(enc, codec, n, 128)
            rel = np.abs(dec - x).max() / np.abs(x).max()
            assert rel <= quant.CODEC_BANDS[codec] + 1e-9, \
                (n, codec, rel)


def test_codec_scale_edge_cases():
    # all-zero block: scale 0, exact zeros back
    z = np.zeros(300, np.float32)
    assert np.array_equal(
        quant.decode_f32(quant.encode_f32(z, "int8"), "int8", 300), z)
    # huge magnitudes (near f32 max) and denormal-scale tinies survive
    for fill in (3e38, 1e-30, -2.5e7):
        x = np.full(257, fill, np.float32)
        d = quant.decode_f32(quant.encode_f32(x, "int8"), "int8", 257)
        np.testing.assert_allclose(d, x, rtol=0.01)
    # mixed-magnitude block: the small element's error is bounded by
    # the BLOCK max (the block-scale contract), not its own magnitude
    x = np.array([1e6] + [1.0] * 127, np.float32)
    d = quant.decode_f32(quant.encode_f32(x, "int8", 128), "int8",
                         128, 128)
    assert abs(d[1] - 1.0) <= 0.5 * 1e6 / 127 + 1e-3
    # NaN payloads SURVIVE the bf16 truncation (the naive rounding add
    # carries into the exponent and flushes payload NaNs to +/-0.0 —
    # silently defeating overflow detection), and infinities hold
    pats = np.array([0x7FFFFFFF, 0xFFFFFFFF, 0x7FFF8000, 0x7FC00000,
                     0x7F800000, 0xFF800000], np.uint32)
    d = quant.decode_f32(quant.encode_f32(pats.view(np.float32),
                                          "bf16"), "bf16", pats.size)
    assert np.isnan(d[:4]).all(), d
    assert np.isposinf(d[4]) and np.isneginf(d[5])
    # a truncated payload is a loud error, never a silent misparse
    enc = quant.encode_f32(np.ones(256, np.float32), "int8")
    with pytest.raises(ValueError, match="does not match"):
        quant.decode_f32(enc[:-1], "int8", 256)


def test_codec_cross_process_determinism(tmp_path):
    """Identical input encodes to identical bytes in a fresh process
    with randomized hashing — the property the KV prefix cache and the
    wire receive parse rely on."""
    body = (
        "import numpy as np, zlib\n"
        "from ompi_tpu.mca.coll import quant\n"
        "x = np.random.default_rng(42).standard_normal(5000)"
        ".astype(np.float32)\n"
        "print(zlib.crc32(quant.encode_f32(x, 'int8', 128).tobytes()),"
        " zlib.crc32(quant.encode_f32(x, 'bf16').tobytes()))\n")
    x = np.random.default_rng(42).standard_normal(5000).astype(
        np.float32)
    here = (zlib.crc32(quant.encode_f32(x, "int8", 128).tobytes()),
            zlib.crc32(quant.encode_f32(x, "bf16").tobytes()))
    env = dict(_mp_env(), PYTHONHASHSEED="random")
    out = subprocess.run([sys.executable, "-c", body], env=env,
                         capture_output=True, text=True, timeout=120,
                         cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    got = tuple(int(v) for v in out.stdout.split())
    assert got == here, "codec bytes differ across processes"


# ------------------------------------------------------- decision ladder

def test_decide_rule_key():
    f32, big = np.float32, 1 << 20
    assert quant.decide("allreduce", f32, big, 0.01) == "int8"
    assert quant.decide("allreduce", f32, big,
                        quant.CODEC_BANDS["int8"]) == "int8"
    assert quant.decide("allreduce", f32, big, 0.005) == "bf16"
    assert quant.decide("allreduce", f32, big, 0.001) is None
    assert quant.decide("allreduce", f32, big, None) is None
    assert quant.decide("allreduce", f32, big, 0.0) is None
    # exact dtypes and non-f32 floats are excluded
    assert quant.decide("allreduce", np.int32, big, 0.01) is None
    assert quant.decide("allreduce", np.float64, big, 0.01) is None
    # non-commutative reductions are excluded (the tuned gate)
    assert quant.decide("allreduce", f32, big, 0.01,
                        commute=False) is None
    # below the size floor the encode never earns its cost
    assert quant.decide("allreduce", f32, 1024, 0.01) is None
    # only the implemented collectives
    assert quant.decide("bcast", f32, big, 0.01) is None
    assert quant.decide("allgather", f32, big, 0.01) == "int8"


def test_budget_info_key_parsing(world, capsys):
    c = world.dup()
    assert quant.budget_of(c) is None
    c.info.set("otpu_quant_budget", "0.01")
    assert quant.budget_of(c) == 0.01
    assert quant.pick(c, "allreduce", np.float32, 1 << 20,
                      op_mod.SUM) == "int8"
    # malformed budget: loud show_help, quant stays OFF
    c.info.set("otpu_quant_budget", "not-a-float")
    assert quant.budget_of(c) is None
    assert "does not parse" in capsys.readouterr().err


# ---------------------------------------------------- tuned (host) tier

def _rank_data(n, elems, seed):
    return np.stack([np.random.default_rng([seed, r])
                     .standard_normal(elems)
                     for r in range(n)]).astype(np.float32)


@pytest.fixture()
def tuned_module(world):
    from ompi_tpu.base import mca
    from ompi_tpu.mca.coll.tuned import TunedModule

    fw = mca.framework("coll")
    fw.open()
    comp = fw.components["tuned"]
    return TunedModule(comp), comp


def test_tuned_quant_only_under_budget(world, tuned_module):
    from ompi_tpu.runtime import spc

    mod, _ = tuned_module
    spc.init()
    data = _rank_data(8, 64 * 1024, seed=21)   # 256KB f32
    exact = data.astype(np.float64).sum(0)

    # no budget: the exact ladder path, zero codec activity
    enc0 = spc.read("quant_encodes")
    out = spmd(world, lambda c, r: mod.allreduce(c, data[r]))
    assert np.abs(out[0] - exact).max() / np.abs(exact).max() < 1e-5
    assert spc.read("quant_encodes") == enc0, \
        "quantized WITHOUT an accuracy budget"

    world.info.set("otpu_quant_budget", "0.02")
    try:
        out = spmd(world, lambda c, r: mod.allreduce(c, data[r]))
        rel = np.abs(out[0] - exact).max() / np.abs(exact).max()
        assert 1e-7 < rel <= quant.CODEC_BANDS["int8"] * 1.2, rel
        assert spc.read("quant_encodes") > enc0
        # every rank folds in rank order: results bit-identical
        for r in range(1, 8):
            assert np.array_equal(out[0], out[r])
        # allgather arm: each block decodes within band at every rank
        g = spmd(world, lambda c, r: mod.allgather(c, data[r][:32768]))
        relg = np.abs(g[0] - data[:, :32768]).max() / np.abs(data).max()
        assert 1e-7 < relg <= quant.CODEC_BANDS["int8"]
    finally:
        world.info.delete("otpu_quant_budget")


def test_tuned_quant_never_noncommutative(world, tuned_module):
    from ompi_tpu.runtime import spc

    mod, _ = tuned_module
    spc.init()

    def first_half(invec, inoutvec, datatype=None):
        half = len(inoutvec) // 2
        inoutvec[:half] = invec[:half]
        inoutvec[half:] += invec[half:]

    ncop = op_mod.create(first_half, commute=False)
    data = _rank_data(8, 64 * 1024, seed=22)
    world.info.set("otpu_quant_budget", "0.02")
    try:
        enc0 = spc.read("quant_encodes")
        out = spmd(world, lambda c, r: mod.allreduce(c, data[r], ncop))
        assert spc.read("quant_encodes") == enc0, \
            "non-commutative op was quantized"
        # order-safe fold: acc = data[r] (op) acc, r descending (the
        # recursive-doubling grouping differs associatively, so a few
        # f32 ulps of slack — far below any codec band)
        exact = data[7].copy()
        for r in range(6, -1, -1):
            exact = ncop.reduce_arrays(data[r], exact)
        np.testing.assert_allclose(out[0], exact, rtol=1e-4, atol=1e-5)
    finally:
        world.info.delete("otpu_quant_budget")


def test_tuned_force_var_beats_quant(world, tuned_module,
                                     fresh_registry):
    from ompi_tpu.runtime import spc

    mod, _ = tuned_module
    spc.init()
    fresh_registry.set("otpu_coll_tuned_allreduce_algorithm", "ring")
    data = _rank_data(8, 64 * 1024, seed=23)
    world.info.set("otpu_quant_budget", "0.02")
    try:
        enc0 = spc.read("quant_encodes")
        out = spmd(world, lambda c, r: mod.allreduce(c, data[r]))
        assert spc.read("quant_encodes") == enc0, \
            "force-var override was quantized away"
        exact = data.astype(np.float64).sum(0)
        assert np.abs(out[0] - exact).max() / np.abs(exact).max() < 1e-5
    finally:
        world.info.delete("otpu_quant_budget")


def test_tolerance_harness_on_tuned_quant(world, tuned_module):
    """The dryrun tolerance-band check driving the REAL quant ladder
    path (the satellite: run_tolerance_check used in tier-1 quant
    tests) — and its loud failure names the (coll, size, dtype) cell."""
    from ompi_tpu.parallel.dryrun import run_tolerance_check

    mod, _ = tuned_module
    world.info.set("otpu_quant_budget", "0.02")
    try:
        def approx(stack):
            out = spmd(world,
                       lambda c, r: mod.allreduce(c, stack[r]))
            return out[0]

        report = run_tolerance_check(
            "allreduce_quant", approx, nranks=8,
            sizes=(32 * 1024,), band=quant.CODEC_BANDS["int8"])
        assert report["allreduce_quant/32768/float32"] > 1e-7
    finally:
        world.info.delete("otpu_quant_budget")
    # the loud path: an impossible band names the failing cell
    with pytest.raises(RuntimeError) as ei:
        run_tolerance_check(
            "quant_rt",
            lambda stack: quant.decode_f32(
                quant.encode_f32(stack.sum(0), "int8"), "int8",
                stack.shape[1]),
            sizes=(2048,), band=1e-9)
    assert "(quant_rt, 2048, float32)" in str(ei.value)


# --------------------------------------------------------- device tier

def test_device_quant_allreduce_and_allgather(world):
    xla = next(m for m in world.coll_modules
               if type(m).__name__ == "XlaCollModule")
    host = _rank_data(8, 65536, seed=31)
    exact = host.astype(np.float64).sum(0)

    # no budget: bit-exact-grade device path
    dev = xla.make_world_array(host)
    out = np.asarray(world.allreduce_array(dev))
    assert np.abs(out - exact).max() / np.abs(exact).max() < 1e-5

    q = world.dup()
    q.info.set("otpu_quant_budget", "0.02")
    xla_q = next(m for m in q.coll_modules
                 if type(m).__name__ == "XlaCollModule")
    dev_q = xla_q.make_world_array(host)
    out_q = np.asarray(q.allreduce_array(dev_q))
    rel = np.abs(out_q - exact).max() / np.abs(exact).max()
    assert 1e-7 < rel <= quant.CODEC_BANDS["int8"] * 1.2, rel
    # compiled program cache: the second call is the same program
    assert np.array_equal(out_q, np.asarray(q.allreduce_array(dev_q)))
    # quant allgather decodes within the single-encode band
    ag = np.asarray(q.allgather_array(dev_q))
    relg = np.abs(ag - host).max() / np.abs(host).max()
    assert 1e-7 < relg <= 0.5 / 127 * 1.5, relg
    # MAX is not a psum reduction: it must take the exact path
    mx = np.asarray(q.allreduce_array(dev_q, op_mod.MAX))
    np.testing.assert_allclose(mx, host.max(0), rtol=1e-6)


def test_quant_kernels_aot_compile_cpu():
    """Fake-device CI path of the carried-forward honesty rule: the
    codec kernels must COMPILE under JAX_PLATFORMS=cpu AOT so the
    device tier is re-earnable the moment the tunnel returns (the real
    Mosaic gate rides tools/pallas_aot.py's quant_* cases)."""
    import jax
    import jax.numpy as jnp

    from ompi_tpu.ops import pallas_quant as pq

    rows = (1 << 16) // pq.LANES
    for fn, args in (
            (pq.encode_int8,
             (jax.ShapeDtypeStruct((rows, pq.LANES), jnp.float32),)),
            (pq.dequant_accumulate,
             (jax.ShapeDtypeStruct((8, rows, pq.LANES), jnp.int8),
              jax.ShapeDtypeStruct((8, rows, 1), jnp.float32))),
            (pq.decode_int8,
             (jax.ShapeDtypeStruct((rows, pq.LANES), jnp.int8),
              jax.ShapeDtypeStruct((rows, 1), jnp.float32)))):
        compiled = fn.lower(*args, interpret=True).compile()
        assert compiled is not None


# ----------------------------------------------------------- wire tier

def _mk_conn():
    from ompi_tpu.mca.btl import tcp as tcp_mod

    s1, s2 = socket.socketpair()
    conn = tcp_mod._Conn(s1)
    conn.rank = 9
    return tcp_mod, conn, (s1, s2)


def _quant_frame(tcp_mod, x: np.ndarray, cksum: bool = True):
    """A quantized fast-header frame, built the way send() builds it."""
    from ompi_tpu.mca.btl.base import MATCH, Frag

    payload = memoryview(x).cast("B")
    enc = quant.encode_wire(payload, "int8")
    qhdr = tcp_mod._QHDR.pack(quant.codec_id("int8"), len(payload),
                              quant.block_elems())
    hdr = tcp_mod._fast_header(Frag(0, 9, 0, 5, 1, MATCH,
                                    b"x" * len(payload)))
    htype = tcp_mod._H_FAST | tcp_mod._H_QUANT
    if cksum:
        crc = zlib.crc32(memoryview(enc),
                         zlib.crc32(hdr, zlib.crc32(qhdr)))
        frame_len = (1 + tcp_mod._CKSUM.size + len(qhdr) + len(hdr)
                     + enc.nbytes)
        return bytearray(
            tcp_mod._LEN.pack(frame_len)
            + bytes((htype | tcp_mod._H_CK_BASE,))
            + tcp_mod._CKSUM.pack(crc) + qhdr + hdr + enc.tobytes())
    frame_len = 1 + len(qhdr) + len(hdr) + enc.nbytes
    return bytearray(tcp_mod._LEN.pack(frame_len) + bytes((htype,))
                     + qhdr + hdr + enc.tobytes())


def test_wire_quant_frame_roundtrip():
    tcp_mod, conn, socks = _mk_conn()
    btl = tcp_mod.TcpBtl()
    got = []
    btl.set_recv_callback(got.append)
    try:
        x = np.random.default_rng(3).standard_normal(16384).astype(
            np.float32)
        n = btl._on_bytes(conn, memoryview(_quant_frame(tcp_mod, x)))
        assert n == 1
        dec = np.frombuffer(bytes(got[0].data), np.float32)
        # the parse decodes EXACTLY what the codec encodes...
        ref = quant.decode_f32(quant.encode_f32(x, "int8"), "int8",
                               x.size)
        assert np.array_equal(dec, ref)
        # ...and lands within the codec band of the original
        assert np.abs(dec - x).max() / np.abs(x).max() <= 0.5 / 127 + 1e-9
        assert not got[0].borrowed   # decoded payload owns its memory
    finally:
        for s in socks:
            s.close()


def test_wire_quant_frames_fail_as_loudly_as_crc(capsys):
    """Corrupt quant frames: crc-armed bit rot AND a garbage quant
    sub-header both die with an attributed SanitizeError + show_help —
    never a silently-wrong delivery."""
    from ompi_tpu.base import output
    from ompi_tpu.runtime import sanitizer, spc

    spc.init()
    output._help_seen.clear()   # show_help dedups per key in a window
    tcp_mod, conn, socks = _mk_conn()
    btl = tcp_mod.TcpBtl()
    btl.set_recv_callback(lambda frag: None)
    try:
        x = np.ones(4096, np.float32)
        frame = _quant_frame(tcp_mod, x, cksum=True)
        frame[-3] ^= 0x20                 # wire bit rot under crc
        before = spc.read("wire_cksum_fail")
        with pytest.raises(sanitizer.SanitizeError):
            btl._on_bytes(conn, memoryview(frame))
        assert spc.read("wire_cksum_fail") == before + 1
        assert "corrupted on the wire" in capsys.readouterr().err
        # unchecksummed frame whose quant header lies about its length:
        # the decode length check catches it loudly
        frame2 = _quant_frame(tcp_mod, x, cksum=False)
        tcp_mod._QHDR.pack_into(frame2, tcp_mod._LEN.size + 1,
                                quant.codec_id("int8"),
                                4096 * 4 + 64, quant.block_elems())
        with pytest.raises(sanitizer.SanitizeError) as ei:
            btl._on_bytes(conn, memoryview(frame2))
        assert "rank 9" in str(ei.value)
        assert "does not decode" in capsys.readouterr().err
    finally:
        for s in socks:
            s.close()


_WIRE_JOB = """
import json
import numpy as np
import ompi_tpu
from ompi_tpu.mca.coll import quant
from ompi_tpu.runtime import spc

w = ompi_tpu.init()
n = (4 << 20) // 4
base = np.stack([np.random.default_rng([7, r]).standard_normal(n)
                 for r in range(w.size)]).astype(np.float32)
exact = base.astype(np.float64).sum(0)
got = np.asarray(w.allreduce(base[w.rank]))
rel = float(np.max(np.abs(got - exact)) / np.max(np.abs(exact)))
st = quant.wire_stats()
print("WIRE%d " % w.rank + json.dumps(
    {"orig": st["orig"], "enc": st["enc"],
     "saved": spc.read("quant_wire_bytes_saved"), "rel": rel}),
    flush=True)
ompi_tpu.finalize()
"""


def test_wire_4MB_moves_at_least_2x_fewer_bytes(tmp_path):
    """THE wire acceptance: a 4MB f32 host allreduce over loopback tcp
    with quantize-on-pack armed moves >=2x fewer payload bytes (int8
    block codec measures ~3.9x) and the result stays inside the codec
    band."""
    script = tmp_path / "wire_job.py"
    script.write_text(_WIRE_JOB)
    proc = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
         "--fake-nodes", "2",
         "--mca", "otpu_coll_sm_coll_priority", "0",
         "--mca", "otpu_coll_quant_wire", "1",
         "--mca", "otpu_coll_tuned_allreduce_algorithm",
         "recursive_doubling",
         "--mca", "pml_ob1_stripe", "0",
         "--mca", "pml_ob1_rget_limit", "0",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env=_mp_env())
    assert proc.returncode == 0, proc.stderr[-3000:]
    reps = [json.loads(ln.split(" ", 2)[2])
            for ln in proc.stdout.splitlines() if "WIRE" in ln]
    assert len(reps) == 2, proc.stdout
    for rep in reps:
        # each rank pushed its 4MB contribution through the codec
        assert rep["orig"] >= 4 << 20
        assert rep["enc"] * 2 <= rep["orig"], \
            f"only {rep['orig'] / max(1, rep['enc']):.2f}x fewer bytes"
        assert rep["saved"] == rep["orig"] - rep["enc"]
        # tolerance check: within the int8 accumulate band
        assert 1e-7 < rep["rel"] <= quant.CODEC_BANDS["int8"], rep


_CHAOS_JOB = """
import numpy as np
import ompi_tpu
from ompi_tpu.ft import chaos

w = ompi_tpu.init()
x = np.ones((256 << 10) // 4, np.float32)
for it in range(4):
    if chaos.enabled:
        chaos.kill_point("step", it)
    got = np.asarray(w.allreduce(x))
    assert np.allclose(got, w.size, atol=0.1), "silently wrong result"
print("CHAOS-QUANT-OK rank %d" % w.rank, flush=True)
ompi_tpu.finalize()
"""


def _run_chaos_quant_job(tmp_path, spec):
    script = tmp_path / "chaos_job.py"
    script.write_text(_CHAOS_JOB)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
         "--fake-nodes", "2",
         "--mca", "otpu_coll_sm_coll_priority", "0",
         "--mca", "otpu_coll_quant_wire", "1",
         "--mca", "otpu_coll_quant_min_bytes", "4k",
         "--mca", "otpu_chaos_spec", spec,
         "--mca", "otpu_chaos_seed", "3",
         "--mca", "ft_detector", "true",
         "--mca", "ft_detector_period", "0.3",
         "--mca", "ft_detector_timeout", "6.0",
         "--mca", "ft_detector_startup_grace", "6.0",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=150, cwd=REPO,
        env=_mp_env())


def test_chaos_corrupt_quant_frames_loud(tmp_path):
    """Chaos-armed wire corruption with quant frames on the wire: the
    armed crc (chaos arms checksumming) catches every flip LOUDLY —
    completion-or-attributed-error, never silent wrong data (the
    worker itself checks every result)."""
    r = _run_chaos_quant_job(tmp_path, "corrupt:p=1")
    out = r.stdout + r.stderr
    assert r.returncode != 0, "every frame corrupted yet the job passed?"
    assert ("corrupted on the wire" in out or "crc32" in out
            or "does not decode" in out), out[-3000:]


def test_chaos_kill_with_quant_wire_no_hang(tmp_path):
    """Chaos kill mid-run with the quant wire armed: the survivor
    fails loudly (detector -> ProcFailed) inside the timeout — a codec
    stage must not turn a peer death into a hang."""
    r = _run_chaos_quant_job(tmp_path, "kill:rank=1,step=2")
    out = r.stdout + r.stderr
    assert r.returncode != 0, out[-2000:]
    assert ("chaos" in out or "failed" in out.lower()), out[-3000:]


# ------------------------------------------------------- serving KV tier

def test_kv_quant_slab_e2e(world):
    """Quantized KV slabs over the partitioned persistent pairing:
    blocks land within the codec band, the epoch machinery is
    untouched, and the capacity multiplier is the users-per-chip win."""
    from ompi_tpu.runtime.progress import progress
    from ompi_tpu.serving.kv_stream import KvSlabReceiver, KvSlabSender
    from ompi_tpu.serving.worker import toy_kv

    a, b = world.as_rank(0), world.as_rank(1)
    snd = KvSlabSender(a, peer=1, slots=4, elems_per_slot=256, tag=93,
                       codec="int8")
    rcv = KvSlabReceiver(b, peer=0, slots=4, elems_per_slot=256,
                         tag=93, partitions=8, codec="int8")
    assert snd.capacity_multiplier >= 2.0
    assert rcv.slab.nbytes * 2 <= 4 * 256 * 4  # 2-4x more slots/byte
    band = quant.CODEC_BANDS["int8"]
    try:
        for epoch in range(3):
            snd.begin_epoch(epoch)
            rcv.begin_epoch(epoch)
            kv = toy_kv(epoch * 10 + 2, 256)
            snd.write_slot(2, kv)
            snd.slot_ready(2)
            for _ in range(400):
                if rcv.slot_arrived(2):
                    break
                progress()
            assert rcv.slot_arrived(2), "readied slot never arrived"
            got = rcv.read_slot(2)
            tol = band * max(1e-6, float(np.abs(kv).max()))
            assert np.allclose(got, kv, atol=tol, rtol=0.0)
            assert not np.array_equal(got, kv) or kv.max() == 0
            snd.finish_epoch(wait=True)
            rcv.finish_epoch()
    finally:
        snd.free()
        rcv.free()


def test_kv_decode_worker_verifies_within_band(world):
    """A decode-stage worker with a quantized receiver accepts the
    in-band block and stores it as its decode state."""
    from ompi_tpu.runtime.progress import progress
    from ompi_tpu.serving.kv_stream import KvSlabSender
    from ompi_tpu.serving.worker import ShardWorker, toy_kv

    a, b = world.as_rank(2), world.as_rank(3)
    wk = ShardWorker(b, router=2, role="decode", peer=2, slots=4,
                     kv_elems=256, kv_codec="int8")
    snd = KvSlabSender(a, peer=3, slots=4, elems_per_slot=256,
                       tag=7001, codec="int8")
    # point the worker's receiver at OUR sender pairing (same tag)
    wk._receiver.free()
    from ompi_tpu.serving.kv_stream import KvSlabReceiver

    wk._receiver = KvSlabReceiver(b, peer=2, slots=4,
                                  elems_per_slot=256, tag=7001,
                                  codec="int8")
    try:
        snd.begin_epoch(0)
        snd.write_slot(1, toy_kv(77, 256))
        snd.slot_ready(1)
        snd.finish_epoch(wait=True)
        # _on_kv IS the verify path under test: begin, poll, band-check
        # (raises on an out-of-band block), store, reply
        wk._on_kv(0, [(77, 1)])
        expect = toy_kv(77, 256)
        tol = quant.CODEC_BANDS["int8"] * float(np.abs(expect).max())
        assert np.allclose(wk._kv[77], expect, atol=tol, rtol=0.0)
        # drain the worker's reply so the module world stays clean
        kind, epoch, rids = a.recv_obj(3, 602)   # worker.TAG_RES
        assert (kind, epoch, rids) == ("kv_ready", 0, [77])
    finally:
        snd.free()
        wk._receiver.free()


def test_kv_codec_change_is_stale_generation():
    """A codec change bumps the PrefixStore generation: every hint
    minted against the old encoding falls back to FULL PREFILL — a
    perf miss, never wrong KV (the stale-hint guarantee surviving a
    codec change)."""
    from ompi_tpu.runtime import spc
    from ompi_tpu.serving.prefix_cache import PrefixStore, block_hashes
    from ompi_tpu.serving.worker import ShardWorker, toy_kv

    spc.init()
    wk = ShardWorker.__new__(ShardWorker)
    wk.kv_elems = 16
    wk._prefix = PrefixStore(capacity=8)
    wk._prefix.set_codec("")
    wk._prefix_hits = 0
    wk._preport_installed, wk._preport_evicted = [], []
    wk._preport_prefills = 0
    ch = block_hashes(list(range(8)), 4)
    prefills0 = spc.read("serve_prefills")
    wk._prefill_or_skip(11, 8, ch, None)
    gen0 = wk._prefix.generation
    # verified hint at the raw-codec generation: prefill skipped
    wk._prefill_or_skip(12, 8, ch, (ch[1], gen0, 2))
    assert spc.read("serve_prefills") == prefills0 + 1
    # the codec flips (reconfiguration): generation bumps
    wk._prefix.set_codec("int8")
    assert wk._prefix.generation == gen0 + 1
    stale0 = spc.read("serve_prefix_stale")
    kv = wk._prefill_or_skip(13, 8, ch, (ch[1], gen0, 2))
    np.testing.assert_array_equal(kv, toy_kv(13, 16))   # never wrong KV
    assert spc.read("serve_prefills") == prefills0 + 2, \
        "stale hint did not fall back to full prefill"
    assert spc.read("serve_prefix_stale") == stale0 + 1
    # idempotent re-set does NOT churn the generation
    g = wk._prefix.generation
    wk._prefix.set_codec("int8")
    assert wk._prefix.generation == g


# --------------------------------------------------- surfaces and pins

def test_otpu_info_quant(capsys):
    from ompi_tpu.tools.otpu_info import main

    assert main(["--quant", "--parsable"]) == 0
    out = capsys.readouterr().out
    assert "quant budget info key:otpu_quant_budget" in out
    assert "quant var otpu_coll_quant_block" in out
    assert "quant var otpu_coll_quant_wire" in out
    assert "quant var otpu_coll_quant_kv_codec" in out
    assert "quant stage quant.encode" in out
    assert "quant counter quant_wire_bytes_saved" in out


def _load(name):
    with open(REPO / name) as f:
        return json.load(f)


def test_quant_rows_pinned():
    """The committed quant bench rows (bench.py --quant) stay in the
    sweep with their contract numbers: wire ratio >=2x (pin 3.88),
    capacity multipliers, every error inside its codec band — and NO
    device row unless it carries real measurements (the tunnel-down
    honesty rule: device rows are emitted only when the probe
    succeeds)."""
    pins = _load("tests/bench_pins.json")["quant"]
    sweep = _load("BENCH_SWEEP.json")
    rows = {r.get("coll"): r for r in sweep["results"]}
    wire = rows.get("quant_wire_int8_4MB")
    assert wire is not None and wire.get("ok", True), \
        "pinned quant wire row vanished"
    assert wire["wire_ratio"] >= 2.0
    assert wire["wire_ratio"] >= 0.9 * pins["wire_ratio"]
    assert wire["max_rel_err"] <= quant.CODEC_BANDS["int8"]
    for codec in ("int8", "bf16"):
        kv = rows.get(f"quant_kv_{codec}")
        assert kv is not None, f"pinned quant KV row {codec} vanished"
        assert kv["capacity_x"] >= 0.99 * pins[f"kv_capacity_{codec}"]
        assert kv["max_rel_err"] <= quant.CODEC_BANDS[codec]
    for name, r in rows.items():
        if str(name).startswith("quant_device_"):
            assert r.get("lat_us", 0) > 0, \
                "a fake-device quant row was carried into the sweep"


def test_wire_disabled_is_identity_off():
    """Module-bool identity: with the var at its default the pml/btl
    codec stage is one bool check — no Frag carries a codec stamp."""
    from ompi_tpu.base.var import registry

    var = registry.lookup("otpu_coll_quant_wire")
    assert var is not None and not bool(var.value)
    assert quant.wire_enabled is False
