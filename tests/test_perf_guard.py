"""Host-path performance regression guards.

Round-2 review found `allreduce_host_tuned` collapsing superlinearly at
4MB (265ms on the 1-core VM — ~12x worse per byte than the 256KB point).
The fixes (escalating idle backoff + doorbell wakeups, header/payload
split frames, contiguous-datatype fast paths, zero-copy eager sends,
scratch-buffer reuse) brought it to ~40ms.  These guards pin the shape of
the curve, not absolute speed: per-byte cost may not regress superlinearly
again.  Mirrors the linear degradation of the reference's ring
(``coll_base_allreduce.c:341``) under fixed bandwidth.
"""
import json
import os
import statistics
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = textwrap.dedent("""
    import json, statistics, time
    import numpy as np, ompi_tpu

    w = ompi_tpu.init()
    out = []
    for nbytes in (262144, 4194304):
        x = np.ones(nbytes // 4, np.float32)
        for _ in range(2):
            w.allreduce(x)
        lat = []
        for _ in range(5):
            w.barrier()
            t0 = time.perf_counter()
            w.allreduce(x)
            lat.append(time.perf_counter() - t0)
        out.append((nbytes, statistics.median(lat)))
    if w.rank == 0:
        print("GUARD " + json.dumps(out))
    ompi_tpu.finalize()
""")


def test_allreduce_per_byte_cost_stays_linear(tmp_path):
    script = tmp_path / "guard.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "4",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "GUARD" in ln)
    (small_b, small_t), (big_b, big_t) = json.loads(
        line.split("GUARD ", 1)[1])
    per_byte_small = small_t / small_b
    per_byte_big = big_t / big_b
    # superlinear collapse guard: 16x the bytes may cost at most ~3x more
    # per byte (scheduling noise margin included; the round-2 pathology
    # measured ~12x)
    assert per_byte_big <= 3.5 * per_byte_small, (
        f"per-byte cost grew {per_byte_big / per_byte_small:.1f}x "
        f"from 256KB ({small_t * 1e3:.1f}ms) to 4MB ({big_t * 1e3:.1f}ms)")
    # absolute backstops: sweep measures 4MB ≈25ms / 256KB ≈1.3ms and
    # the in-suite harness runs ~1.4x slower (~35ms / ~2ms).  The
    # linearity assert above is the primary guard; these only catch a
    # catastrophic (order-of-magnitude) collapse, with enough headroom
    # that a loaded single-core CI host doesn't flake them
    assert big_t < 0.30, f"4MB allreduce took {big_t * 1e3:.0f}ms"
    assert small_t < 0.032, f"256KB allreduce took {small_t * 1e3:.1f}ms"


_FASTPATH_COPY_SCRIPT = textwrap.dedent("""
    import json
    import numpy as np, ompi_tpu
    from ompi_tpu.runtime import spc

    w = ompi_tpu.init()
    # contiguous eager messages over btl/tcp (fake-nodes forces tcp),
    # ping-ponged so the socket never backpressures: the zero-copy
    # contract says the user buffer's view rides to sendmsg with NO
    # intermediate payload copy
    x = np.ones(16 << 10, np.uint8)
    y = np.empty_like(x)
    for i in range(50):
        if w.rank == 0:
            w.send(x, dest=1, tag=1)
            w.recv(y, source=1, tag=2)
        else:
            w.recv(y, source=0, tag=1)
            w.send(x, dest=1 - w.rank, tag=2)
    c = spc.counters()
    print(f"COPYPIN{w.rank} " + json.dumps(
        [c.get("fastpath_payload_copies", -1),
         c.get("fastpath_hdr_fast", -1),
         c.get("fastpath_hdr_pickle", -1)]))
    ompi_tpu.finalize()
""")


_SCHED_CACHE_SCRIPT = textwrap.dedent("""
    import json
    import numpy as np, ompi_tpu
    from ompi_tpu.runtime import spc

    w = ompi_tpu.init()
    big = np.ones(65536, np.float32)      # 256KB: above the eager lane
    small = np.ones(256, np.float32)      # 1KB: eager lane
    w.allreduce(big)
    base_hits = spc.read("fastpath_sched_hits")
    w.allreduce(big)                      # identical second call
    hits_after = spc.read("fastpath_sched_hits")
    w.allreduce(small)
    if w.rank == 0:
        print("SCHEDPIN " + json.dumps(
            [base_hits, hits_after,
             spc.read("fastpath_eager_lane")]))
    ompi_tpu.finalize()
""")


def test_fastpath_zero_copy_tcp_send(tmp_path):
    """The fastpath acceptance pin: on the contiguous tcp send path the
    payload must never be copied (SPC ``fastpath_payload_copies`` == 0
    — the sender's memoryview rides to sendmsg) and the fixed fast
    header must carry the data frames (pickle only for the handshake's
    exotic frames)."""
    script = tmp_path / "copy_pin.py"
    script.write_text(_FASTPATH_COPY_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "2",
         "--fake-nodes", "2", sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    for rank in (0, 1):
        line = next(ln for ln in r.stdout.splitlines()
                    if f"COPYPIN{rank}" in ln)
        copies, fast, pickle_h = json.loads(
            line.split(f"COPYPIN{rank} ", 1)[1])
        assert copies == 0, (
            f"rank {rank}: {copies} payload copies on the contiguous "
            f"tcp send path (zero-copy contract broken)")
        assert fast >= 50, f"rank {rank}: only {fast} fast headers"


def test_tuned_schedule_cache_hits_on_second_call(tmp_path):
    """coll/tuned decision+schedule caching: the second identical
    allreduce must hit the cached pick (SPC ``fastpath_sched_hits``
    grows), and a small allreduce must take the SPC-counted eager
    lane.  ^sm_coll isolates the tuned ladder (on one host coll/sm owns
    sub-slot payloads)."""
    script = tmp_path / "sched_pin.py"
    script.write_text(_SCHED_CACHE_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "4",
         "--mca", "coll", "^sm_coll", sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "SCHEDPIN" in ln)
    base_hits, hits_after, lane = json.loads(
        line.split("SCHEDPIN ", 1)[1])
    assert hits_after > base_hits, (
        f"second identical allreduce did not hit the schedule cache "
        f"({base_hits} -> {hits_after})")
    assert lane >= 1, "small allreduce skipped the eager lane"


def test_sanitizer_off_zero_overhead():
    """OTPU_SANITIZE off must cost the 4KB eager lane NOTHING: the
    @hot_path decorator is identity (no wrapper object on any tagged hot
    function — the strongest possible zero-overhead proof), the
    memchecker hook stays dormant, and the sanitizer flag is a module
    bool no hot path reads outside its cold branches."""
    from ompi_tpu.datatype.convertor import Convertor
    from ompi_tpu.mca.accelerator.jax_acc import _StagingPool
    from ompi_tpu.mca.btl.tcp import TcpBtl
    from ompi_tpu.mca.coll.tuned import TunedModule
    from ompi_tpu.runtime import hotpath, memchecker, progress, sanitizer

    assert sanitizer.enabled is False          # default off
    assert memchecker.enabled() is False       # hook dormant

    def f():
        return 1

    assert hotpath.hot_path(f) is f            # decorator is identity
    # every tagged hot function is the plain function object — no
    # wrapper, no __wrapped__, nothing to pay per call
    for fn in (TcpBtl.send, TcpBtl._flush_locked, TcpBtl._on_bytes,
               TunedModule.allreduce, Convertor.pack_borrow,
               _StagingPool.acquire, _StagingPool.release,
               progress.progress):
        assert not hasattr(fn, "__wrapped__"), fn
    # the registry recorded the eager-lane path's hot functions
    regs = hotpath.registered()
    for qual in ("TcpBtl.send", "TunedModule.allreduce",
                 "Convertor.pack_borrow", "_StagingPool.acquire"):
        assert any(q.endswith(qual) for q in regs), qual


def test_reactor_off_zero_overhead():
    """otpu_progress_native=0 must be IDENTITY: no reactor thread, no
    handle, no drain callback on the progress tick path, and drain()
    itself is a pure-Python two-load early return (no ctypes call ever
    fires).  The fallback selector lane in btl/tcp is the same code
    that shipped before the reactor existed."""
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import progress, reactor, spc

    var = registry.lookup("otpu_progress_native")
    saved = var.value
    var.set(False)
    try:
        assert not reactor.configured()
        assert not reactor.engage()              # declines, no side effects
        assert reactor._handle == 0              # no native object
        assert not reactor.active()
        with progress._lock:
            assert reactor.drain not in progress._callbacks
            assert reactor.drain not in progress._lp_callbacks
        spc.init()
        before = (spc.read("progress_native_drains"),
                  spc.read("fastpath_native_frags"))
        assert reactor.drain() == 0              # early return, no ctypes
        assert (spc.read("progress_native_drains"),
                spc.read("fastpath_native_frags")) == before
    finally:
        var.set(saved)
        progress.reset_for_testing()


def test_weave_off_zero_overhead():
    """With no weave run active (the production state — OTPU_SANITIZE
    off, no explorer), the interleaving instrumentation must cost the
    lock layer NOTHING: no run object exists, instrument() returns its
    argument with every _guarded_by lock attribute untouched (a plain
    threading primitive — no wrapper on Lock acquire), make_lock hands
    back a plain RLock, and pause/signal are immediate returns."""
    import threading

    from ompi_tpu.analysis import weave
    from ompi_tpu.mca.accelerator.jax_acc import _StagingPool
    from ompi_tpu.runtime import sanitizer

    assert sanitizer.enabled is False
    assert weave.active() is None
    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    lock_before = pool._lock
    assert weave.instrument(pool) is pool
    assert pool._lock is lock_before
    # the plain runtime lock type, not a WeaveLock wrapper: acquire is
    # the raw C primitive
    assert isinstance(pool._lock, type(threading.RLock()))
    assert not isinstance(pool._lock, weave.WeaveLock)
    assert isinstance(weave.make_lock("x"), type(threading.RLock()))
    weave.pause("never")             # no-ops, no run to yield into
    weave.signal("never")
    assert weave.active() is None


def test_chaos_disabled_zero_overhead():
    """An empty otpu_chaos_spec must cost the wire NOTHING: chaos is a
    module bool the hot paths read in one cold branch (the
    trace/sanitizer discipline), no engine exists, the frame checksum
    stays unarmed, and every hook is an immediate return."""
    from ompi_tpu.ft import chaos
    from ompi_tpu.mca.btl import tcp as tcp_mod
    from ompi_tpu.runtime import spc

    assert chaos.enabled is False              # default off
    assert chaos._engine is None               # nothing armed
    assert tcp_mod._cksum_armed() is False     # no crc on the wire
    # every hook is inert without an engine — no draws, no counters
    before = {k: spc.read(k) for k in
              ("chaos_drop", "chaos_delay", "chaos_dup", "chaos_corrupt",
               "chaos_reset", "chaos_stall", "chaos_disconnect",
               "chaos_kill")}
    assert chaos.wire_send("tcp", True) is None
    assert chaos.wire_recv("tcp", True) is None
    assert chaos.coord_stall("put") is None
    assert chaos.coord_disconnect("put") is False
    chaos.kill_point("step", n=0)
    assert {k: spc.read(k) for k in before} == before
    # install/uninstall restores the zero-cost identity
    chaos.install_spec("delay:ms=1,p=1", rank=0)
    assert chaos.enabled is True
    chaos.uninstall()
    assert chaos.enabled is False and chaos._engine is None
    assert tcp_mod._cksum_armed() is False


def test_small_pack_skips_pool_dispatch(monkeypatch):
    """fastpath satellite: packs below ``_POOL_PACK_MIN`` must never
    reach the worker pool — the threads_pool_pack_4MB bench measured
    pool dispatch barely breaking even at 4MB, so sub-threshold packs
    keep the serial native loop with zero pool traffic."""
    import numpy as np

    from ompi_tpu.datatype import convertor as conv_mod
    from ompi_tpu.datatype import core as dt_core
    from ompi_tpu.mca.threads import base as threads_base

    # the threshold itself is part of the contract
    assert conv_mod._POOL_PACK_MIN >= (1 << 21), \
        "parallel-pack fan-out threshold regressed below 2MB"
    calls = []
    monkeypatch.setattr(threads_base, "get_pool",
                        lambda: calls.append(1))
    vec = dt_core.vector(2, 1, 2, dt_core.FLOAT32)
    n = (conv_mod._POOL_PACK_MIN // vec.size) - 1   # just under
    buf = np.zeros(n * (vec.extent // 4), np.float32)
    packed = conv_mod.Convertor(vec, n, buf).pack()
    assert packed.nbytes == n * vec.size
    assert not calls, "sub-threshold pack dispatched to the pool"


_TRACE_PIN_SCRIPT = textwrap.dedent("""
    import json, time
    import numpy as np, ompi_tpu
    from ompi_tpu.api import op as op_mod
    from ompi_tpu.runtime import trace

    w = ompi_tpu.init()
    # conductor-world stacked layout: one 1KB row per hosted rank
    x = np.ones((w.size, 256), np.float32)
    wrapped = w.c_coll["allreduce"]          # trace wrapper (outermost)
    inner = wrapped
    while hasattr(inner, "__wrapped__"):
        inner = inner.__wrapped__

    def one(fn, n=2000):
        for _ in range(100):
            fn(w, x, op_mod.SUM)
        t0 = time.perf_counter()
        for _ in range(n):
            fn(w, x, op_mod.SUM)
        return (time.perf_counter() - t0) / n

    # paired, interleaved reps: host-load drift hits both callables in
    # the same window instead of biasing whichever ran second
    t_wrapped = t_direct = float("inf")
    for rep in range(6):
        if rep % 2:
            a, b = one(inner), one(wrapped)
        else:
            b, a = one(wrapped), one(inner)
        t_direct = min(t_direct, a)
        t_wrapped = min(t_wrapped, b)
    print("TRACEPIN " + json.dumps(
        [t_wrapped, t_direct, trace.recorded_count(), len(trace.histograms())]))
    ompi_tpu.finalize()
""")


_PREADY_PIN_SCRIPT = textwrap.dedent("""
    import json, time
    import numpy as np, ompi_tpu
    from ompi_tpu.base.var import registry
    from ompi_tpu.mca.part import part_framework
    from ompi_tpu.runtime import trace

    w = ompi_tpu.init()
    part_framework().open()
    # aggregation threshold above the partition count: every pready but
    # the last is pure bookkeeping (bitmap bit + run merge), isolating
    # the hot call from the wire send
    P = 512
    registry.set("otpu_part_persist_min_partitions", P + 1)
    a, b = w.as_rank(0), w.as_rank(1)
    x = np.zeros(P * 8, np.float32)
    y = np.zeros(P * 8, np.float32)
    s = a.psend_init(x, P, dest=1, tag=1)
    r = b.precv_init(y, P, source=0, tag=1)

    def epoch():
        s.start(); r.start()
        t0 = time.perf_counter()
        for p in range(P - 1):
            s.pready(p)
        dt = time.perf_counter() - t0
        s.pready(P - 1)
        s.wait(); r.wait()
        return dt / (P - 1)

    epoch()                           # warmup
    per_call = min(epoch() for _ in range(5))
    print("PREADYPIN " + json.dumps(
        [per_call, trace.recorded_count(), len(trace.histograms())]))
    ompi_tpu.finalize()
""")


_SESSION_PIN_SCRIPT = textwrap.dedent("""
    import json, time
    import ompi_tpu
    from ompi_tpu.runtime import trace
    from ompi_tpu import instance as inst_mod

    w = ompi_tpu.init()           # boots the instance ONCE (held by world)
    boot_inst = inst_mod.current()

    def cycle(n=400):
        t0 = time.perf_counter()
        for _ in range(n):
            s = ompi_tpu.Session.init()
            s.finalize()
        return (time.perf_counter() - t0) / n

    cycle(50)                     # warmup
    per = min(cycle() for _ in range(3))
    assert inst_mod.current() is boot_inst   # never re-booted
    print("SESSIONPIN " + json.dumps(
        [per, trace.recorded_count(), len(trace.histograms())]))
    ompi_tpu.finalize()
""")


def test_session_acquire_disabled_path_cost(tmp_path):
    """Refcounted Session.init/finalize on an already-booted instance
    must be bookkeeping only: (a) no RTE re-boot (same instance object
    throughout — an accidental re-fence/pml re-select would cost ms and
    trip the bound), (b) zero otpu-trace events/histograms while tracing
    is disabled (the boot spans are enabled-path only), (c) per-cycle
    cost far below any boot work; headroom absorbs 1-core CI noise."""
    script = tmp_path / "session_pin.py"
    script.write_text(_SESSION_PIN_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "SESSIONPIN" in ln)
    per_cycle, recorded, hists = json.loads(
        line.split("SESSIONPIN ", 1)[1])
    assert recorded == 0, f"{recorded} trace events while disabled"
    assert hists == 0, f"{hists} histogram bins while disabled"
    # measured ~3us/cycle (lock + refcount + Session object); 100us of
    # headroom still catches any boot-path work (fence/pml/modex are
    # milliseconds) leaking into the refcounted acquire
    assert per_cycle < 100e-6, \
        f"session acquire/release costs {per_cycle * 1e6:.1f}us/cycle"


def test_pready_disabled_path_overhead(tmp_path):
    """The Pready hot call (one per gradient bucket per step in the
    overlap pattern) with tracing disabled must stay bookkeeping-cheap
    and record nothing: (a) zero trace events/histogram bins, (b)
    per-call cost bounded far below a wire send — a catastrophic
    regression (per-call flush scan, accidental tracing) trips it, CI
    scheduler noise does not."""
    script = tmp_path / "pready_pin.py"
    script.write_text(_PREADY_PIN_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "PREADYPIN" in ln)
    per_call, recorded, hists = json.loads(line.split("PREADYPIN ", 1)[1])
    assert recorded == 0, f"{recorded} trace events while disabled"
    assert hists == 0, f"{hists} histogram bins while disabled"
    # measured ~3us/call on the 1-core CI VM (spc bump + checks + bitmap
    # + run merge); 50us of headroom absorbs host load without letting
    # an O(partitions) scan per call (~0.5ms at P=512) sneak in
    assert per_call < 50e-6, f"pready costs {per_call * 1e6:.1f}us/call"


def test_tracing_disabled_overhead_is_one_flag_check(tmp_path):
    """The otpu-trace coll-table wrapper is installed unconditionally at
    comm_select; with tracing disabled (the default) its cost on the
    allreduce hot path must be one flag check — pinned as (a) zero
    events/histograms recorded and (b) per-call overhead vs the
    unwrapped slot within scheduling noise of the seed."""
    script = tmp_path / "trace_pin.py"
    script.write_text(_TRACE_PIN_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "TRACEPIN" in ln)
    t_wrapped, t_direct, recorded, hists = json.loads(
        line.split("TRACEPIN ", 1)[1])
    # the disabled path must not have recorded anything at all
    assert recorded == 0, f"{recorded} events recorded while disabled"
    assert hists == 0, f"{hists} histogram bins touched while disabled"
    # the measured disabled-path cost is ~0.5us (flag check + argument
    # forwarding).  The bound is absolute-or-relative: 4us of fixed
    # headroom, widened to 30% of the direct call on hosts where the
    # baseline itself is tens of us (scheduler noise scales with call
    # time on the loaded 1-core CI VM).  Gross per-call work creeping
    # into the disabled path still trips it, and the zero-records
    # asserts above catch any accidental recording regardless of
    # timing.
    overhead = t_wrapped - t_direct
    assert overhead < max(4e-6, 0.3 * t_direct), (
        f"tracing-disabled wrapper costs {overhead * 1e9:.0f}ns/call "
        f"(wrapped {t_wrapped * 1e6:.2f}us vs direct "
        f"{t_direct * 1e6:.2f}us)")


def test_flow_disabled_zero_overhead():
    """otpu-crit satellite pin: with ``otpu_trace_flow`` off (or
    tracing off entirely) the flow layer is an identity — flow_start/
    flow_finish record nothing, pml spans carry no flow key, requests
    never grow a _flow stamp, the coll wrapper allocates no cseq, and
    the SPC flow counters stay flat.  The record path must be byte-
    identical to the pre-otpu-crit tracer."""
    import numpy as np

    import ompi_tpu
    from ompi_tpu.base.var import registry as _registry
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import spc, trace

    # default-off half: tracing disabled forces flow off whatever the
    # flow var says, and the flow calls are guarded no-ops
    _registry.set("otpu_trace_enable", False)
    trace.reset_for_testing()
    assert trace.flow_enabled is False
    before = spc.read("flow_starts"), spc.read("flow_finishes")
    trace.flow_start("pml_msg", (0, 0, 1, 0))
    trace.flow_finish("pml_msg", (0, 0, 1, 0))
    assert trace.recorded_count() == 0
    # tracing ON, flow OFF: spans record exactly the pre-flow shape
    rt.reset_for_testing()
    _registry.set("otpu_trace_enable", True)
    _registry.set("otpu_trace_flow", False)
    trace.reset_for_testing()
    try:
        assert trace.enabled is True and trace.flow_enabled is False
        w = ompi_tpu.init()
        x = np.ones(64, np.float32)
        buf = np.empty_like(x)
        a, b = w.as_rank(0), w.as_rank(1)
        sreq = a.isend(x, dest=1, tag=9)
        b.recv(buf, source=0, tag=9)
        sreq.wait()
        evs = trace.chrome_events()
        pml = [e for e in evs if e.get("cat") == "pml"]
        assert pml, "pml spans missing"
        for e in pml:
            assert "fid" not in (e.get("args") or {}), e
        assert not [e for e in evs if e["ph"] in ("s", "f")]
        # no request ever carried a flow stamp
        assert trace._coll_seq == {}
        assert (spc.read("flow_starts"),
                spc.read("flow_finishes")) == before
        # conductor world: collectives take a leading rank axis
        w.allreduce(np.ones((w.size, 4), np.float32))
        colls = [e for e in trace.chrome_events()
                 if e.get("cat") == "coll"]
        assert colls and all("cseq" not in (e.get("args") or {})
                             for e in colls)
    finally:
        _registry.set("otpu_trace_enable", False)
        _registry.set("otpu_trace_flow", True)
        trace.reset_for_testing()
        rt.reset_for_testing()


def test_requests_disabled_zero_overhead():
    """otpu-req satellite pin: with ``otpu_trace_requests`` off (the
    default) the request layer is an identity even while tracing is
    fully ON — a whole serving run emits no serve_req spans, no
    rid.hop flow halves, no rid keys anywhere in the trace, requests
    never grow the request-layer lifecycle stamps, and the req_*/slo_*
    SPC counters stay flat (SLO accounting is gated by its own target
    var, unset here)."""
    import threading

    import ompi_tpu
    from ompi_tpu.base.var import registry as _registry
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import spc, trace

    rt.reset_for_testing()
    _registry.set("otpu_trace_enable", True)
    trace.reset_for_testing()
    try:
        assert trace.enabled is True and trace.requests_enabled is False
        w = ompi_tpu.init()
        from ompi_tpu.serving import (ContinuousBatchScheduler, Router,
                                      ShardWorker)
        from ompi_tpu.serving.driver import PoissonDriver

        before = (spc.read("req_traced"), spc.read("req_stages"),
                  spc.read("slo_goodput"), spc.read("slo_breaches"))
        workers = [ShardWorker(w.as_rank(r), router=0) for r in (1, 2)]
        threads = [threading.Thread(target=wk.serve, daemon=True)
                   for wk in workers]
        for t in threads:
            t.start()
        r = Router(w.as_rank(0),
                   scheduler=ContinuousBatchScheduler(
                       max_batch=4, max_batch_tokens=4096),
                   workers=[1, 2], decode_chunk=4)
        rep = PoissonDriver(rate_rps=800, n_requests=8,
                            seed=2).run(r, max_wall_s=60)
        r.shutdown()
        for t in threads:
            t.join(timeout=10)
        assert rep["requests"] == 8
        evs = trace.chrome_events()
        assert not [e for e in evs if e.get("cat") == "serve_req"]
        assert not [e for e in evs if e.get("ph") in ("s", "f")
                    and e.get("name") == "serve_req"]
        for e in evs:
            assert "rid" not in (e.get("args") or {}), e
        # the request-layer stamps never fired (admit/done stamp
        # unconditionally — they predate otpu-req; the three new
        # single-write stamps are requests-gated)
        for q in r.completed():
            assert q.dispatch_ns is None and q.decode_ns is None \
                and q.last_res_ns is None, q.rid
        assert (spc.read("req_traced"), spc.read("req_stages"),
                spc.read("slo_goodput"),
                spc.read("slo_breaches")) == before
    finally:
        _registry.set("otpu_trace_enable", False)
        trace.reset_for_testing()
        rt.reset_for_testing()


def test_telemetry_disabled_zero_overhead():
    """otpu-top satellite pin: with otpu_telemetry_interval_ms at its
    default (0), the telemetry plane is an identity — no sampler
    object, no thread, sources are one dict insert at component init,
    and nothing ever snapshots trace/SPC state (the chaos-disabled
    discipline)."""
    import threading

    from ompi_tpu.runtime import flight, telemetry

    assert telemetry.enabled is False            # default off
    assert telemetry._sampler is None            # no sampler object
    assert not [t for t in threading.enumerate()
                if t.name == "otpu-telemetry"], "sampler thread exists"

    # start() without an interval (or without a coord client) stays off
    class _NoClientRte:
        client = None
        my_world_rank = 0

    assert telemetry.start(_NoClientRte()) is False
    assert telemetry.enabled is False and telemetry._sampler is None
    # the flight recorder is likewise inert until armed: dump() with no
    # armed RTE is a no-op returning None, whatever the enable var says
    flight.reset_for_testing()
    assert flight.dump("abort", detail="not armed") is None
    # registered sources are bookkeeping only — nothing calls them
    calls = []
    telemetry.register_source("tcp", lambda: calls.append(1))
    try:
        assert not calls
    finally:
        telemetry.unregister_source("tcp")
    # an undeclared source name is rejected loudly
    import pytest as _pytest

    with _pytest.raises(ValueError):
        telemetry.register_source("not_in_schema", dict)


def test_frontdoor_disabled_zero_overhead():
    """Front-door satellite pin: with no FrontDoor constructed the
    admission plane is an identity — module bool off, no armed
    instance, no thread ever (the door pumps on the fleet tick even
    when armed), the router completion hook is one module-attribute
    check, speculative decoding defaults off, and the
    serve_shed/serve_preempt SPC counters stay EXACTLY flat."""
    import threading

    from ompi_tpu.runtime import spc
    from ompi_tpu.serving import frontdoor
    from ompi_tpu.serving.worker import _spec_k_var

    assert frontdoor.enabled is False            # default off
    assert frontdoor._active is None             # no armed instance
    assert not [t for t in threading.enumerate()
                if "frontdoor" in t.name.lower()], "door thread exists"
    shed0 = spc.read("serve_shed")
    pre0 = spc.read("serve_preempt")
    # the module observe() hook with no door armed is a no-op
    frontdoor.observe("pool", "interactive", 5.0)
    frontdoor.observe("pool", "batch", 5.0)
    assert spc.read("serve_shed") == shed0
    assert spc.read("serve_preempt") == pre0
    # disarm without a door is likewise inert
    frontdoor.disarm()
    assert frontdoor.enabled is False and frontdoor._active is None
    # speculative decoding is off by default: otpu_serving_spec_k=0
    # means one target pass per token, draft model never consulted
    assert int(_spec_k_var.value or 0) == 0


_TELEMETRY_PIN_SCRIPT = textwrap.dedent("""
    import json, os, time
    from ompi_tpu.rte.coord import CoordServer

    srv = CoordServer(1)
    os.environ["OTPU_COORD"] = f"{srv.addr[0]}:{srv.addr[1]}"
    os.environ["OTPU_RANK"] = "0"
    os.environ["OTPU_NPROCS"] = "1"

    import numpy as np, ompi_tpu
    from ompi_tpu.api import op as op_mod
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import init as rt
    from ompi_tpu.runtime import spc, telemetry

    w = ompi_tpu.init()
    x = np.ones(1024, np.float32)               # the 4KB hot loop

    def one(n=1500):
        for _ in range(100):
            w.allreduce(x, op_mod.SUM)
        t0 = time.perf_counter()
        for _ in range(n):
            w.allreduce(x, op_mod.SUM)
        return (time.perf_counter() - t0) / n

    registry.lookup("otpu_telemetry_interval_ms").set(50)
    # paired, interleaved reps: sampler armed vs disarmed in the same
    # load window (the TRACEPIN discipline)
    t_on = t_off = float("inf")
    for rep in range(6):
        if rep % 2:
            telemetry.start(rt.get_rte())
            a = one()
            telemetry.stop()
            b = one()
        else:
            b = one()
            telemetry.start(rt.get_rte())
            a = one()
            telemetry.stop()
        t_on = min(t_on, a)
        t_off = min(t_off, b)
    # the 1-rank timing reps can finish inside one 50ms interval; give
    # the sampler one dedicated window to prove it actually publishes
    telemetry.start(rt.get_rte())
    time.sleep(0.25)
    telemetry.stop()
    samples = spc.read("telemetry_samples")
    print("TELEPIN " + json.dumps([t_on, t_off, samples]))
    ompi_tpu.finalize()
    srv.close()
""")


def test_telemetry_enabled_overhead_bounded(tmp_path):
    """The enabled-sampler pin: at a 50ms interval the sampler touches
    NO hot path (it snapshots counters on its own thread), so the 4KB
    allreduce loop must cost the same with it running.  The designed
    overhead is sub-1%; the asserted bound is absolute-or-relative
    (2us fixed headroom, widened to 30% of the baseline) because the
    1-core CI VM's scheduler noise dwarfs 1% — gross per-call work
    (a lock on the allreduce path, a snapshot per call) still trips
    it.  The sampler must also have actually sampled."""
    script = tmp_path / "tele_pin.py"
    script.write_text(_TELEMETRY_PIN_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "TELEPIN" in ln)
    t_on, t_off, samples = json.loads(line.split("TELEPIN ", 1)[1])
    assert samples >= 1, "sampler never published a sample"
    overhead = t_on - t_off
    assert overhead < max(2e-6, 0.3 * t_off), (
        f"telemetry-enabled allreduce costs {overhead * 1e9:.0f}ns/call "
        f"extra (on {t_on * 1e6:.2f}us vs off {t_off * 1e6:.2f}us)")


def test_profile_disabled_zero_overhead():
    """otpu-prof satellite pin: with otpu_profile_stages off and
    otpu_profile_interval_ms at its default (0), the profile plane is
    an identity — no sampler thread/object, no stage state ever
    recorded (not even mark objects for bogus names), and the
    instrumented datapath functions stay the plain @hot_path-unwrapped
    function objects."""
    import threading

    from ompi_tpu.datatype.convertor import Convertor
    from ompi_tpu.mca.accelerator.jax_acc import _StagingPool
    from ompi_tpu.mca.btl.sm import SmBtl
    from ompi_tpu.mca.btl.tcp import TcpBtl
    from ompi_tpu.mca.coll.tuned import TunedModule
    from ompi_tpu.mca.pml.ob1 import Ob1Pml
    from ompi_tpu.runtime import profile

    assert profile.enabled is False              # default off
    assert profile._profiler is None             # no sampler object
    assert not [t for t in threading.enumerate()
                if t.name == "otpu-prof"], "profiler thread exists"
    # start() without an interval stays off
    class _Rte:
        my_world_rank = 0

    assert profile.start(_Rte()) is False
    assert profile._profiler is None
    # disabled stage calls record NOTHING (no mark objects, no table
    # walk — a bogus name doesn't even raise)
    profile.stage_span("definitely.not.a.stage", 12345)
    profile.stage_mark("definitely.not.a.stage")
    assert profile.stage_snapshot() == {}
    assert profile.profiler_stats() is None
    # the instrumented datapath stays unwrapped plain functions
    for fn in (TcpBtl.send, TcpBtl._flush_locked, TcpBtl._on_bytes,
               SmBtl.send, SmBtl.progress, Ob1Pml.isend,
               Ob1Pml._recv_frag, Ob1Pml._recv_data_frag,
               TunedModule.allreduce, Convertor.pack_borrow,
               _StagingPool.acquire):
        assert not hasattr(fn, "__wrapped__"), fn


_PROFILE_PIN_SCRIPT = textwrap.dedent("""
    import json, os, time
    from ompi_tpu.rte.coord import CoordServer

    srv = CoordServer(1)
    os.environ["OTPU_COORD"] = f"{srv.addr[0]}:{srv.addr[1]}"
    os.environ["OTPU_RANK"] = "0"
    os.environ["OTPU_NPROCS"] = "1"

    import numpy as np, ompi_tpu
    from ompi_tpu.api import op as op_mod
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import profile

    w = ompi_tpu.init()
    x = np.ones(1024, np.float32)               # 4KB payload
    buf = np.empty_like(x)

    def one(n=1200):
        # self send/recv crosses the instrumented pml datapath
        # (pack -> deliver -> complete) on a 1-rank world, where an
        # allreduce would shortcut past pml/btl entirely
        for _ in range(100):
            w.send(x, dest=0, tag=7)
            w.recv(buf, source=0, tag=7)
        t0 = time.perf_counter()
        for _ in range(n):
            w.send(x, dest=0, tag=7)
            w.recv(buf, source=0, tag=7)
        return (time.perf_counter() - t0) / n

    stages_var = registry.lookup("otpu_profile_stages")
    # paired, interleaved min-of-6 reps: stage clocks armed vs
    # disarmed in the same load window (the TRACEPIN discipline)
    t_on = t_off = float("inf")
    for rep in range(6):
        if rep % 2:
            stages_var.set(True)
            a = one()
            stages_var.set(False)
            b = one()
        else:
            b = one()
            stages_var.set(True)
            a = one()
            stages_var.set(False)
        t_on = min(t_on, a)
        t_off = min(t_off, b)
    stages_var.set(True)
    w.send(x, dest=0, tag=7)
    w.recv(buf, source=0, tag=7)
    recorded = sum(v["n"] for v in profile.stage_stats().values())
    stages_var.set(False)
    print("PROFPIN " + json.dumps([t_on, t_off, recorded]))
    ompi_tpu.finalize()
    srv.close()
""")


def test_profile_enabled_overhead_bounded(tmp_path):
    """The enabled-stage-clock pin: armed, a 4KB self send/recv pays a
    few perf_counter_ns pairs + locked histogram folds per message —
    designed low single-digit us on a tens-of-us e2e.  Asserted
    absolute-or-relative (4us fixed headroom, widened to 35% of the
    baseline: 1-core CI scheduler noise) via paired interleaved
    min-of-6 reps.  The clocks must also have actually recorded."""
    script = tmp_path / "prof_pin.py"
    script.write_text(_PROFILE_PIN_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, str(script)],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = next(ln for ln in r.stdout.splitlines() if "PROFPIN" in ln)
    t_on, t_off, recorded = json.loads(line.split("PROFPIN ", 1)[1])
    assert recorded >= 1, "stage clocks never recorded while armed"
    overhead = t_on - t_off
    assert overhead < max(4e-6, 0.35 * t_off), (
        f"stage-clock-armed allreduce costs {overhead * 1e9:.0f}ns/call "
        f"extra (on {t_on * 1e6:.2f}us vs off {t_off * 1e6:.2f}us)")
