"""Process topologies: dims_create, cart/graph/dist_graph, cart_sub,
neighbor collectives (SURVEY.md §2.3 topo framework)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errors import MpiError
from ompi_tpu.api.status import PROC_NULL
from ompi_tpu.mca.topo import CartTopo, GraphTopo, dims_create
from ompi_tpu.runtime import init as rt

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def world():
    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


class TestDimsCreate:
    def test_balanced_factorization(self):
        assert dims_create(8, 3) == [2, 2, 2]
        assert dims_create(12, 2) == [4, 3]
        assert dims_create(7, 2) == [7, 1]
        assert dims_create(24, 3) == [4, 3, 2]

    def test_fixed_dims_honored(self):
        assert dims_create(8, 2, [2, 0]) == [2, 4]
        assert dims_create(8, 2, [0, 8]) == [1, 8]
        with pytest.raises(MpiError):
            dims_create(7, 2, [2, 0])  # 7 not divisible by 2

    def test_exact_fixed(self):
        assert dims_create(6, 2, [2, 3]) == [2, 3]
        with pytest.raises(MpiError):
            dims_create(8, 2, [2, 3])


class TestCartTopo:
    def test_rank_coords_roundtrip(self):
        t = CartTopo([2, 4], [False, False])
        for r in range(8):
            assert t.rank_of(t.coords_of(r)) == r
        assert t.coords_of(5) == [1, 1]
        assert t.rank_of([1, 1]) == 5

    def test_shift_nonperiodic_edges(self):
        t = CartTopo([4], [False])
        assert t.shift(0, 0, 1) == (PROC_NULL, 1)
        assert t.shift(3, 0, 1) == (2, PROC_NULL)
        assert t.shift(1, 0, 1) == (0, 2)

    def test_shift_periodic_wraps(self):
        t = CartTopo([4], [True])
        assert t.shift(0, 0, 1) == (3, 1)
        assert t.shift(3, 0, 1) == (2, 0)

    def test_graph_neighbors(self):
        # square: 0-1, 0-3, 1-2, 2-3
        g = GraphTopo([2, 4, 6, 8], [1, 3, 0, 2, 1, 3, 0, 2])
        assert g.neighbors_of(0) == [1, 3]
        assert g.neighbors_of(2) == [1, 3]


class TestDeviceWorldCart:
    def test_cart_create_and_accessors(self, world):
        if world.size < 8:
            pytest.skip("needs 8 ranks")
        cart = world.cart_create([2, 4], periods=[True, False])
        assert cart is not None
        dims, periods, coords = cart.cart_get()
        assert dims == [2, 4] and periods == [True, False]
        assert cart.cart_rank(coords) == cart.rank
        src, dst = cart.cart_shift(1, 1)
        if coords[1] == 3:
            assert dst == PROC_NULL
        cart.free()

    def test_cart_excludes_extra_ranks(self, world):
        if world.size < 8:
            pytest.skip("needs 8 ranks")
        # 6-rank grid on an 8-rank comm: top facade ranks get None
        high = world.as_rank(world.size - 1)
        assert high.cart_create([2, 3]) is None

    def test_cart_sub_splits_axes(self, world):
        if world.size < 8:
            pytest.skip("needs 8 ranks")
        cart = world.cart_create([2, 4])
        row = cart.cart_sub([False, True])   # keep the 4-axis
        assert row.size == 4
        assert row.topo.dims == [4]
        col = cart.cart_sub([True, False])
        assert col.size == 2
        assert col.topo.dims == [2]

    def test_neighbor_allgather_conductor(self, world):
        if world.size < 8:
            pytest.skip("needs 8 ranks")
        cart = world.cart_create([8], periods=[True])
        table = np.arange(8, dtype=np.int64)[:, None] * 10
        got = cart.neighbor_allgather(table)
        # ring: neighbors of rank 0 are 7 (minus) and 1 (plus)
        assert got[0][0] == 70 and got[1][0] == 10

    def test_neighbor_alltoall_conductor(self, world):
        if world.size < 8:
            pytest.skip("needs 8 ranks")
        cart = world.cart_create([8], periods=[True])
        # rank r sends [r, 0] to its minus neighbor, [r, 1] to its plus
        bufs = np.array([[[r, 0], [r, 1]] for r in range(8)], np.int64)
        got = cart.neighbor_alltoall(bufs)
        # slot 0 (from minus neighbor 7): 7 sent its plus-slot [7, 1]
        assert got[0].tolist() == [7, 1]
        # slot 1 (from plus neighbor 1): 1 sent its minus-slot [1, 0]
        assert got[1].tolist() == [1, 0]


def _tpurun(n, script, timeout=240):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


class TestMultiprocessTopo:
    def test_halo_exchange(self, tmp_path):
        script = tmp_path / "halo.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            w = ompi_tpu.init()
            cart = w.cart_create([2, 2], periods=[True, True])
            dims, periods, coords = cart.cart_get()
            # 1-D halo along each axis via cart_shift + sendrecv
            local = np.full(4, float(cart.rank))
            for d in range(2):
                src, dst = cart.cart_shift(d, 1)
                halo = np.zeros(4)
                cart.sendrecv(local, dst, halo, src)
                expect = cart.cart_rank(
                    [(c - (1 if i == d else 0)) % dims[i]
                     for i, c in enumerate(coords)])
                assert halo[0] == float(expect), (d, halo, expect)
            # neighbor allgather: 4 slots (2 dims x minus/plus)
            got = cart.neighbor_allgather(local)
            assert len(got) == 4
            if w.rank == 0:
                print("TOPO HALO OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "TOPO HALO OK" in r.stdout


def test_topo_test_and_type_introspection(world):
    """MPI_Topo_test + MPI_Type_get_contents/set_name
    (``ompi/mpi/c/topo_test.c``, ``type_get_contents.c``)."""
    assert world.topo_test() == "undefined"
    cart = world.cart_create([world.size], periods=[True])
    assert cart.topo_test() == "cart"
    cart.free()

    from ompi_tpu.datatype import FLOAT32, vector

    dt = vector(3, 2, 5, FLOAT32)
    comb, contents = dt.get_envelope()
    assert comb == "vector"
    assert dt.get_contents() == contents
    dt.set_name("my_vec")
    assert dt.get_name() == "my_vec"
    d2 = dt.dup()
    assert d2.get_envelope()[0] == "dup"
