"""One-sided RMA: windows, put/get/accumulate/atomics, fence/lock/PSCW
(SURVEY.md §2.3 osc framework)."""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.runtime import init as rt

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def world():
    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


class TestLocalWindows:
    def test_create_put_get(self, world):
        win = ompi_tpu.Win.create(world, size=8)
        win.put(np.arange(4, dtype=np.float64), target=1, offset=2)
        got = win.get(4, target=1, offset=2)
        assert got.tolist() == [0.0, 1.0, 2.0, 3.0]
        assert win.get(1, target=1, offset=0)[0] == 0.0
        win.free()

    def test_accumulate_and_fetch(self, world):
        win = ompi_tpu.Win.create(world, size=4)
        win.accumulate(np.ones(4), target=0)
        win.accumulate(np.ones(4) * 2, target=0)
        assert win.get(4, target=0).tolist() == [3.0] * 4
        old = win.get_accumulate(np.ones(4), target=0)
        assert old.tolist() == [3.0] * 4
        assert win.get(4, target=0).tolist() == [4.0] * 4
        win.free()

    def test_fetch_and_op_cas(self, world):
        win = ompi_tpu.Win.create(world, size=2)
        assert win.fetch_and_op(5.0, target=0) == 0.0
        assert win.fetch_and_op(3.0, target=0) == 5.0
        assert win.compare_and_swap(9.0, compare=8.0, target=0) == 8.0
        assert win.get(1, target=0)[0] == 9.0
        win.free()

    def test_expose_existing_base(self, world):
        base = np.arange(6, dtype=np.int64)
        win = ompi_tpu.Win.create(world, base=base)
        assert win.get(3, target=world.rank, offset=3).tolist() == [3, 4, 5]
        win.put(np.array([99]), target=world.rank, offset=0)
        assert base[0] == 99  # window exposes, not copies, my own base
        win.free()

    def test_sync_noops_and_free(self, world):
        win = ompi_tpu.Win.create(world, size=2)
        win.fence()
        win.lock(0)
        win.unlock(0)
        win.lock_all()
        win.unlock_all()
        win.flush_all()
        win.free()
        with pytest.raises(Exception):
            win.put(np.zeros(1), 0)


def _tpurun(n, script, timeout=420):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


class TestMultiprocessRma:
    def test_put_get_fence(self, tmp_path):
        script = tmp_path / "rma.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            w = ompi_tpu.init()
            win = ompi_tpu.Win.create(w, size=8)
            win.fence()
            # everyone writes its rank into slot [rank] of the right neighbor
            t = (w.rank + 1) % w.size
            win.put(np.array([float(w.rank)]), target=t, offset=w.rank)
            win.fence()
            left = (w.rank - 1) % w.size
            assert win.local[left] == float(left), win.local
            # direct remote read of the left neighbor's region
            got = win.get(1, target=left, offset=(left - 1) % w.size)
            assert got[0] == float((left - 1) % w.size)
            win.fence()
            win.free()
            if w.rank == 0:
                print("RMA FENCE OK")
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RMA FENCE OK" in r.stdout

    def test_passive_lock_accumulate(self, tmp_path):
        script = tmp_path / "lockacc.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            w = ompi_tpu.init()
            win = ompi_tpu.Win.create(w, size=1)
            # all ranks atomically add into rank 0's counter under lock
            for _ in range(10):
                win.lock(0, win.LOCK_SHARED)
                win.accumulate(np.ones(1), target=0)
                win.unlock(0)
            w.barrier()
            if w.rank == 0:
                assert win.local[0] == 10.0 * w.size, win.local
                print("RMA LOCK OK")
            win.free()
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RMA LOCK OK" in r.stdout

    def test_exclusive_lock_read_modify_write(self, tmp_path):
        script = tmp_path / "excl.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            w = ompi_tpu.init()
            win = ompi_tpu.Win.create(w, size=1)
            # non-atomic get+put forced atomic by the exclusive lock
            for _ in range(5):
                win.lock(0, win.LOCK_EXCLUSIVE)
                cur = win.get(1, target=0)[0]
                win.put(np.array([cur + 1.0]), target=0)
                win.unlock(0)
            w.barrier()
            if w.rank == 0:
                assert win.local[0] == 5.0 * w.size, win.local
                print("RMA EXCL OK")
            win.free()
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RMA EXCL OK" in r.stdout

    def test_fetch_and_op_global_counter(self, tmp_path):
        script = tmp_path / "fao.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            w = ompi_tpu.init()
            win = ompi_tpu.Win.create(w, size=1, dtype=np.int64)
            # classic ticket counter: each rank draws 5 unique tickets
            tickets = [int(win.fetch_and_op(1, target=0)) for _ in range(5)]
            w.barrier()
            all_t = w.allgather(np.array(tickets, dtype=np.int64))
            if w.rank == 0:
                flat = sorted(np.asarray(all_t).ravel().tolist())
                assert flat == list(range(5 * w.size)), flat
                print("RMA FAO OK")
            win.free()
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RMA FAO OK" in r.stdout

    def test_pscw(self, tmp_path):
        script = tmp_path / "pscw.py"
        script.write_text(textwrap.dedent("""
            import numpy as np, ompi_tpu
            from ompi_tpu.api.group import Group
            w = ompi_tpu.init()
            win = ompi_tpu.Win.create(w, size=4)
            others = Group([r for r in range(w.size) if r != w.rank])
            win.post(others)      # expose to everyone else
            win.start(others)     # access everyone else
            for t in range(w.size):
                if t != w.rank:
                    win.put(np.array([float(w.rank)]), target=t,
                            offset=w.rank % 4)
            win.complete()
            win.wait()
            for r in range(w.size):
                if r != w.rank:
                    assert win.local[r % 4] == float(r), win.local
            if w.rank == 0:
                print("RMA PSCW OK")
            win.free()
            ompi_tpu.finalize()
        """))
        r = _tpurun(4, script)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "RMA PSCW OK" in r.stdout


def test_dynamic_window_attach_detach(tmp_path):
    """MPI_Win_create_dynamic + attach/detach: RMA into regions exposed
    after window creation (``ompi/mpi/c/win_create_dynamic.c``)."""
    import textwrap

    script = tmp_path / "dyn.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu
        from ompi_tpu.api.win import Win

        w = ompi_tpu.init()
        win = Win.create_dynamic(w)
        mem = np.full(4, w.rank * 10.0)
        h = win.attach_region(mem)
        # share my handle with everyone (the app-level address exchange
        # real MPI dynamic windows also need)
        handles = w.allgather(np.array([h], np.int64))
        handles = [int(np.ravel(x)[0]) for x in np.asarray(handles)]
        w.barrier()
        peer = (w.rank + 1) % w.size
        got = win.get(4, peer, offset=0, region=handles[peer])
        assert got.tolist() == [peer * 10.0] * 4, got
        win.put(np.array([99.0]), peer, offset=1, region=handles[peer])
        win.fence()
        w.barrier()
        assert mem[1] == 99.0, mem
        win.detach_region(h)
        w.barrier()   # both sides detached before probing
        # detached region: gets raise, puts are dropped (erroneous per MPI)
        from ompi_tpu.api.errors import MpiError
        try:
            win.get(4, peer, offset=0, region=handles[peer])
            raise AssertionError("get from detached region succeeded")
        except MpiError:
            pass
        w.barrier()
        win.free()
        print(f"DYN OK {w.rank}")
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("DYN OK") == 2
