"""API-completeness batch: the remaining reference bindings
(``ompi/mpi/c``) — spawn_multiple, intercomm_create, comm_join,
reduce_scatter_block, nonblocking v-variants, neighbor v/w variants,
persistent buffered/ready sends, imrecv, MPI_Win_test, cart/graph_map,
type_match_size, MPI_Pcontrol, and MPI_Register_datarep/external32
file views."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import ompi_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


def test_dup_with_info_and_compare(world):
    from ompi_tpu.api.info import Info

    info = Info()
    info.set("foo", "bar")
    d = world.dup_with_info(info)
    assert d.get_info().get("foo") == "bar"
    assert world.get_info().get("foo") is None
    assert world.compare(d) == world.CONGRUENT
    d.free()


def test_reduce_scatter_block_device_world(world):
    n = world.size
    x = np.arange(n * n * 3, dtype=np.float64).reshape(n, n * 3)
    out = world.reduce_scatter_block(x)
    want = x.sum(0).reshape(n, 3)
    np.testing.assert_allclose(np.asarray(out), want)


def test_nonblocking_variants_smoke(world):
    n = world.size
    x = np.arange(n * 4, dtype=np.float64).reshape(n, 4)
    r = world.iscan(x)
    np.testing.assert_allclose(
        np.asarray(r.result), np.cumsum(x, axis=0))
    r = world.iexscan(x)
    assert np.asarray(r.result)[0].sum() == 0
    r = world.igatherv(list(x))
    assert len(r.result) == n
    r = world.ireduce_scatter_block(np.ones((n, n * 2)))
    np.testing.assert_allclose(np.asarray(r.result),
                               np.full((n, 2), float(n)))


def test_neighbor_v_variants_cart(world):
    cart = world.cart_create([world.size], periods=[True])
    # device world: table of per-rank buffers with DIFFERENT sizes
    table = [np.arange(r + 1, dtype=np.float64) * (r + 1)
             for r in range(world.size)]
    out = cart.neighbor_allgatherv(table)
    srcs, _ = cart.topo.neighbors(cart.rank)
    for got, s in zip(out, srcs):
        np.testing.assert_allclose(got, table[s])
    r = cart.ineighbor_allgatherv(table)
    for got, s in zip(r.result, srcs):
        np.testing.assert_allclose(got, table[s])
    cart.free()


def test_cart_and_graph_map(world):
    from ompi_tpu.api.status import UNDEFINED

    n = world.size
    assert world.cart_map([n]) == world.rank
    assert world.cart_map([1]) == (0 if world.rank == 0 else UNDEFINED)
    assert world.graph_map([2, 3], [1, 0, 0]) in (world.rank, UNDEFINED)


def test_type_match_size():
    from ompi_tpu.datatype import core

    assert core.match_size("integer", 4) is core.INT32
    assert core.match_size("real", 8) is core.FLOAT64
    assert core.match_size("complex", 16) is core.COMPLEX128
    with pytest.raises(ValueError):
        core.match_size("integer", 3)


def test_pcontrol():
    from ompi_tpu.api import env

    env.pcontrol(0)
    assert env.pcontrol_level() == 0
    env.pcontrol(2, "extra", "args")
    assert env.pcontrol_level() == 2
    env.pcontrol()
    assert env.pcontrol_level() == 1


def test_file_external32_and_register_datarep(tmp_path, world):
    from ompi_tpu.api import file as fmod
    from ompi_tpu.datatype import core

    path = str(tmp_path / "ext32.bin")
    f = fmod.File.open(None, path,
                       fmod.MODE_CREATE | fmod.MODE_RDWR)
    f.set_view(etype=core.INT32, datarep="external32")
    data = np.array([1, 2, 3, 4], np.int32)
    f.write_at(0, data)
    raw = open(path, "rb").read()
    assert raw == data.byteswap().tobytes()   # big-endian on disk
    out = np.zeros(4, np.int32)
    f.read_at(0, out)
    np.testing.assert_array_equal(out, data)
    f.close()

    # user-registered rep: xor-masked stream both ways
    def mask(data, etype):
        return bytes(b ^ 0x5A for b in data)

    fmod.register_datarep("xor5a", mask, mask)
    path2 = str(tmp_path / "xor.bin")
    f = fmod.File.open(None, path2,
                       fmod.MODE_CREATE | fmod.MODE_RDWR)
    f.set_view(datarep="xor5a")
    payload = np.frombuffer(b"hello-datarep!", np.uint8)
    f.write_at(0, payload)
    assert open(path2, "rb").read() == mask(payload.tobytes(), None)
    back = np.zeros(payload.size, np.uint8)
    f.read_at(0, back)
    np.testing.assert_array_equal(back, payload)
    f.close()
    with pytest.raises(Exception):
        fmod.register_datarep("external32", mask, mask)


def test_win_pscw_test_rdma(tmp_path):
    script = tmp_path / "wtest.py"
    script.write_text(textwrap.dedent("""
        import time
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win

        w = ompi_tpu.init()
        win = Win.create(w, size=8, dtype=np.float64)
        grp_other = w.group.incl([1 - w.rank])
        if w.rank == 0:
            win.post(grp_other)
            spins = 0
            while not win.test():        # MPI_Win_test polling loop
                time.sleep(0.005)
                spins += 1
                assert spins < 2000, "win.test never completed"
            assert win.local[0] == 7.0, win.local
            print("WTEST OK", flush=True)
        else:
            win.start(grp_other)
            win.put(np.array([7.0]), 0, 0)
            time.sleep(0.2)   # target must poll test() a few times
            win.complete()
        win.free()
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert "WTEST OK" in r.stdout, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_spawn_multiple_and_join(tmp_path):
    childa = tmp_path / "childa.py"
    childa.write_text(textwrap.dedent("""
        import ompi_tpu
        w = ompi_tpu.init()
        inter = ompi_tpu.get_parent()
        full = inter.merge(high=True)
        import numpy as np
        out = full.allreduce(np.array([1.0]))
        print(f"CHILD-A rank {w.rank} of {w.size} sum {out[0]}",
              flush=True)
    """))
    childb = tmp_path / "childb.py"
    childb.write_text(childa.read_text().replace("CHILD-A", "CHILD-B"))
    script = tmp_path / "spawnm.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        inter = w.spawn_multiple(
            [[sys.executable, {str(childa)!r}],
             [sys.executable, {str(childb)!r}]], [2, 1])
        assert inter.remote_size == 3
        full = inter.merge(high=False)
        out = full.allreduce(np.array([1.0]))
        assert out[0] == 5.0, out    # 2 parents + 3 children
        print("SPAWNM OK", flush=True)
    """))
    r = _tpurun(2, script, timeout=300)
    assert "SPAWNM OK" in r.stdout, r.stdout + r.stderr
    # one child WORLD of 3 spanning both commands
    assert "CHILD-A rank" in r.stdout and "of 3" in r.stdout
    assert "CHILD-B rank" in r.stdout


def test_comm_join_and_intercomm_create(tmp_path):
    script = tmp_path / "join.py"
    script.write_text(textwrap.dedent("""
        import socket
        import numpy as np, ompi_tpu
        from ompi_tpu import dpm

        w = ompi_tpu.init()
        # build a plain connected socket pair between ranks 0 and 1
        if w.rank == 0:
            srv = socket.create_server(("127.0.0.1", 0))
            w.send_obj(srv.getsockname(), 1, tag=9)
            sock, _ = srv.accept()
        else:
            addr = w.recv_obj(0, tag=9)
            sock = socket.create_connection(tuple(addr))
        inter = dpm.join(sock)
        assert inter.is_inter and inter.remote_size == 1
        # talk across it
        if w.rank == 0:
            inter.send(np.array([42.0]), dest=0, tag=1)
        else:
            buf = np.zeros(1)
            inter.recv(buf, source=0, tag=1)
            assert buf[0] == 42.0
        # MPI_Intercomm_create: two SELF "groups" bridged over world
        half = w.split(w.rank)         # 1-rank comms
        inter2 = half.create_intercomm(0, w, 1 - w.rank, tag=3)
        assert inter2.is_inter and inter2.remote_size == 1
        if w.rank == 0:
            inter2.send(np.array([7.0]), dest=0, tag=2)
        else:
            buf = np.zeros(1)
            inter2.recv(buf, source=0, tag=2)
            assert buf[0] == 7.0
        print(f"JOIN OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.stdout.count("JOIN OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_imrecv_and_persistent_send_modes(tmp_path):
    script = tmp_path / "imrecv.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api import buffer as bsendbuf

        w = ompi_tpu.init()
        if w.rank == 0:
            bsendbuf.attach(1 << 16)
            req = w.bsend_init(np.arange(8.0), dest=1, tag=4)
            req.start(); req.wait()
            req.start(); req.wait()
            rreq = w.rsend_init(np.arange(4.0) * 2, dest=1, tag=5)
            rreq.start(); rreq.wait()
            bsendbuf.detach()
        else:
            for _ in range(2):
                msg = w.mprobe(source=0, tag=4)
                buf = np.zeros(8)
                r = msg.irecv(buf)        # MPI_Imrecv
                r.wait()
                assert buf.tolist() == list(range(8)), buf
            buf = np.zeros(4)
            w.recv(buf, source=0, tag=5)
            assert buf[3] == 6.0
        print(f"IMRECV OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.stdout.count("IMRECV OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_neighbor_v_variants_multiprocess(tmp_path):
    script = tmp_path / "nv.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu

        w = ompi_tpu.init()
        cart = w.cart_create([w.size], periods=[True])
        r = cart.rank
        mine = np.arange(r + 1, dtype=np.float64) * (r + 1)
        out = cart.neighbor_allgatherv(mine)
        srcs, dsts = cart.topo.neighbors(r)
        for got, s in zip(out, srcs):
            want = np.arange(s + 1, dtype=np.float64) * (s + 1)
            assert np.allclose(got, want), (r, s, got)
        # alltoallv: distinct payload per destination, varying sizes
        sends = [np.full(d + 2, float(r * 10 + d)) for d in dsts]
        got = cart.neighbor_alltoallv(sends)
        for g, s in zip(got, srcs):
            # the peer s sent us a buffer labeled s*10 + (my rank)
            assert g[0] == s * 10 + r and len(g) == r + 2, (r, s, g)
        # alltoallw: reinterpret received bytes per source
        gotw = cart.neighbor_alltoallw(
            [b.view(np.uint8) for b in sends], recvtypes=np.float64)
        for g, s in zip(gotw, srcs):
            assert g.dtype == np.float64 and g[0] == s * 10 + r
        print(f"NV OK {r}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(3, script)
    assert r.stdout.count("NV OK") == 3, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_session_api_surface(world):
    """MPI-4 Sessions bindings (``ompi/mpi/c/session_*.c``): init/
    finalize, info + errhandler, pset enumeration, and the sessions-
    model construction chain Group_from_session_pset →
    Comm_create_from_group (full lifecycle coverage in
    test_session.py; device-world crossing in test_device_world.py)."""
    from ompi_tpu.api.errhandler import ERRORS_RETURN
    from ompi_tpu.api.session import Session

    s = Session.init(errhandler=ERRORS_RETURN)
    try:
        n = s.get_num_psets()
        names = [s.get_nth_pset(i) for i in range(n)]
        assert "mpi://WORLD" in names and "mpi://SELF" in names
        info = s.get_pset_info("mpi://WORLD")
        g = ompi_tpu.Group.from_session_pset(s, "mpi://WORLD")
        assert int(info.get("mpi_size")) == g.size
        comm = ompi_tpu.Comm.create_from_group(g, "completeness")
        assert comm.size == g.size and comm.cid >= 2
        np.testing.assert_allclose(
            np.asarray(comm.allreduce_array(
                np.ones((comm.size, 2), np.float32))).ravel(),
            comm.size)
        comm.free()
        lo = g.incl(range(g.size // 2))
        hi = g.difference(lo)
        inter = ompi_tpu.Comm.create_intercomm_from_groups(
            lo, 0, hi, 0, "completeness-inter")
        assert inter.is_inter and inter.remote_size == hi.size
        inter.free()
    finally:
        s.finalize()


def test_partitioned_communication(world):
    """MPI-4 partitioned p2p (Psend_init/Precv_init/Pready/Pready_range/
    Pready_list/Parrived — mca/part/persist); full coverage in
    test_part.py."""
    a, b = world.as_rank(0), world.as_rank(1)
    x = np.arange(24.0)
    y = np.zeros(24)
    s = a.psend_init(x, 6, dest=1, tag=21)
    r = b.precv_init(y, 4, source=0, tag=21)   # mismatched counts
    from ompi_tpu.api.request import start_all

    start_all([s, r])
    s.pready(5)
    s.pready_range(0, 1)
    assert not r.parrived(2)
    s.pready_list([3, 2, 4])
    s.wait()
    r.wait()
    np.testing.assert_array_equal(y, x)
    assert all(r.parrived(p) for p in range(4))


def test_partitioned_collective_init(world):
    """Pallreduce_init analog: bucketed persistent allreduce released
    bucket-by-bucket with Pready."""
    n = world.size
    buckets = [np.full((n, 2), float(i), np.float64) for i in range(1, 4)]
    req = world.pallreduce_init(buckets)
    req.start()
    req.pready_list([2, 0, 1])
    req.wait()
    for i, got in enumerate(req.result):
        np.testing.assert_allclose(np.asarray(got), (i + 1) * n)


def test_host_persistent_collective_and_ext_queries(tmp_path):
    """mpiext analogs: pcollreq on the host path (restartable persistent
    collective), MPIX_Get_affinity, MPIX_Query_cuda_support."""
    from ompi_tpu.api import env

    aff = env.get_affinity()
    assert isinstance(aff, list)
    assert isinstance(env.query_accelerator_support(), bool)

    script = tmp_path / "pcoll.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu

        w = ompi_tpu.init()
        x = np.full(4, float(w.rank + 1))
        req = w.coll_init("allreduce", x)
        for _ in range(3):                 # restartable: MPI_Start loop
            req.start()
            req.wait()
        total = w.size * (w.size + 1) / 2
        assert np.allclose(req.result, total), req.result
        print(f"PCOLL OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(3, script)
    assert r.stdout.count("PCOLL OK") == 3, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_mpi_t_pvar_discoverability_complete():
    """MPI_T completeness (otpu-top satellite): every SPC counter and
    every otpu-trace histogram pvar must be discoverable AND readable
    through an ``api/tool.py`` PvarSession — the contract otpu_top and
    external MPI_T tools rely on.  The histogram pvars register lazily
    per touched (coll, size-bin) cell, so the test records one cell
    first, then demands the full family (count/sum/p50/p99)."""
    from ompi_tpu.api import tool
    from ompi_tpu.runtime import spc, trace

    spc.init()
    trace.init()
    trace.hist_record("allreduce", 4096, 1_500_000)   # 4k bin, 1.5ms
    tool.init_thread()
    try:
        n = tool.pvar_get_num()
        names = {tool.pvar_get_info(i).name: i for i in range(n)}
        # every declared SPC counter is discoverable
        for counter in spc._COUNTERS:
            assert f"otpu_runtime_spc_{counter}" in names, counter
        # the tracer's own pvar and the touched histogram cell's family
        assert "otpu_trace_events_recorded" in names
        for suffix in ("count", "sum_us", "p50_us", "p99_us"):
            assert f"otpu_trace_hist_allreduce_4k_{suffix}" in names, \
                suffix
        # ...and every one of them is readable through a session handle
        session = tool.pvar_session_create()
        for pname, idx in names.items():
            if not (pname.startswith("otpu_runtime_spc_")
                    or pname.startswith("otpu_trace_")):
                continue
            h = session.handle_alloc(idx)
            h.start()
            val = h.read()
            assert isinstance(val, (int, float)), pname
            h.stop()
            session.handle_free(h)
        # the percentile pvars derive from the live population
        p50 = tool.pvar_get_info(
            names["otpu_trace_hist_allreduce_4k_p50_us"]).read()
        assert p50 > 0, "percentile pvar read 0 after a recorded cell"
        tool.pvar_session_free(session)
    finally:
        tool.finalize()
