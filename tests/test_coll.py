"""Collective tests on the 8-virtual-device world: coll/xla device
collectives vs numpy references, conductor host collectives, selection."""
import numpy as np
import pytest

import ompi_tpu


@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    if w.size != 8:
        pytest.skip("needs 8 virtual devices")
    yield w
    rt.reset_for_testing()


@pytest.fixture(scope="module")
def xla(world):
    from ompi_tpu.mca.coll.xla import XlaCollModule

    return next(m for m in world.coll_modules
                if isinstance(m, XlaCollModule))


def _world_data(xla, shape=(4,), dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    host = rng.standard_normal((8, *shape)).astype(dtype)
    return host, xla.make_world_array(host)


def test_selection_order(world):
    # xla (90) must own the *_array slots; conductor (40) the host slots
    assert world.c_coll["allreduce_array"].__self__.__class__.__name__ \
        == "XlaCollModule"
    assert world.c_coll["allreduce"].__self__.__class__.__name__ \
        == "ConductorModule"


def test_device_allreduce_sum(world, xla):
    host, dev = _world_data(xla)
    out = np.asarray(world.allreduce_array(dev))
    np.testing.assert_allclose(out, host.sum(0), rtol=1e-5)


def test_device_allreduce_max_min(world, xla):
    from ompi_tpu.api import op

    host, dev = _world_data(xla, seed=1)
    np.testing.assert_allclose(
        np.asarray(world.allreduce_array(dev, op.MAX)), host.max(0))
    np.testing.assert_allclose(
        np.asarray(world.allreduce_array(dev, op.MIN)), host.min(0))


def test_device_allreduce_prod_band(world, xla):
    from ompi_tpu.api import op

    host = np.ones((8, 3), np.float32) * 2
    dev = xla.make_world_array(host)
    np.testing.assert_allclose(
        np.asarray(world.allreduce_array(dev, op.PROD)), host.prod(0))
    hosti = (np.arange(24).reshape(8, 3) % 7 + 1).astype(np.int32)
    devi = xla.make_world_array(hosti)
    np.testing.assert_array_equal(
        np.asarray(world.allreduce_array(devi, op.BAND)),
        np.bitwise_and.reduce(hosti, 0))


def test_device_bcast(world, xla):
    host, dev = _world_data(xla, seed=2)
    out = np.asarray(world.bcast_array(dev, root=3))
    for i in range(8):
        np.testing.assert_allclose(out[i], host[3], rtol=1e-6)


def test_device_allgather(world, xla):
    host, dev = _world_data(xla, seed=3)
    out = np.asarray(world.allgather_array(dev))
    np.testing.assert_allclose(out, host, rtol=1e-6)


def test_device_reduce_scatter(world, xla):
    host = np.random.default_rng(4).standard_normal((8, 8, 5)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    out = np.asarray(world.reduce_scatter_array(dev))
    # rank i's block = sum over ranks of block i
    expect = host.sum(0)  # (8, 5)
    np.testing.assert_allclose(out.reshape(8, 5), expect, rtol=1e-4)


def test_device_alltoall(world, xla):
    host = np.arange(8 * 8 * 2, dtype=np.float32).reshape(8, 8, 2)
    dev = xla.make_world_array(host)
    out = np.asarray(world.alltoall_array(dev))
    np.testing.assert_array_equal(out, np.swapaxes(host, 0, 1))


def test_device_ppermute_ring(world, xla):
    host, dev = _world_data(xla, seed=5)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = np.asarray(world.ppermute_array(dev, perm))
    np.testing.assert_allclose(out, np.roll(host, 1, axis=0), rtol=1e-6)


def test_device_barrier(world):
    world.barrier()  # conductor host barrier → device barrier; must not hang


def test_host_collectives(world):
    from ompi_tpu.api import op

    host = np.arange(16, dtype=np.float64).reshape(8, 2)
    np.testing.assert_allclose(world.allreduce(host), host.sum(0))
    np.testing.assert_allclose(world.allgather(host), host)
    np.testing.assert_allclose(world.reduce(host, op.MAX), host.max(0))
    np.testing.assert_allclose(world.scan(host), np.cumsum(host, 0))
    ex = world.exscan(host)
    assert np.all(ex[0] == 0)
    np.testing.assert_allclose(ex[1:], np.cumsum(host, 0)[:-1])
    a2a = np.arange(8 * 8, dtype=np.int64).reshape(8, 8)
    np.testing.assert_array_equal(world.alltoall(a2a), a2a.T)
    rs = world.reduce_scatter(np.ones((8, 16), np.float32))
    assert np.asarray(rs).shape == (8, 2)
    assert np.all(np.asarray(rs) == 8)


def test_nonblocking_host(world):
    req = world.iallreduce(np.ones((8, 2), np.float32))
    req.wait()
    np.testing.assert_allclose(req.result, np.full(2, 8.0))
    world.ibarrier().wait()


def test_agree(world):
    assert world.agree(0b1011) == 0b1011


def test_comm_self_collectives():
    from ompi_tpu.runtime import init as rt

    s = rt.comm_self()
    assert s.size == 1
    out = s.allreduce(np.array([3.0]))
    assert out[0] == 3.0
    assert s.c_coll["allreduce"].__self__.__class__.__name__ \
        == "SelfCollModule"


def test_comm_dup_split(world):
    d = world.dup()
    assert d.cid != world.cid and d.size == 8
    halves = world.split(color=0 if world.rank < 4 else 1, key=0)
    assert halves is not None
    d.free()


def test_split_device_subcomm(world, xla):
    """Splitting the device world yields a sub-mesh communicator whose
    coll/xla runs on the member devices only."""
    sub = world.create(world.group.incl([0, 2, 4, 6]))
    assert sub is not None and sub.size == 4
    from ompi_tpu.mca.coll.xla import XlaCollModule

    submod = [m for m in sub.coll_modules if isinstance(m, XlaCollModule)]
    assert submod, "coll/xla must select on the sub-communicator"
    host = np.ones((4, 3), np.float32)
    out = np.asarray(sub.allreduce_array(submod[0].make_world_array(host)))
    np.testing.assert_allclose(out, np.full(3, 4.0))


def test_device_reduce_root_semantics(world, xla):
    host, dev = _world_data(xla, seed=10)
    out = np.asarray(world.reduce_array(dev, root=2))
    np.testing.assert_allclose(out[2], host.sum(0), rtol=1e-5)
    for i in (0, 1, 3, 7):
        np.testing.assert_array_equal(out[i], np.zeros_like(out[i]))


def test_device_gather_root_semantics(world, xla):
    host, dev = _world_data(xla, seed=11)
    out = np.asarray(world.gather_array(dev, root=5))
    np.testing.assert_allclose(out[5], host, rtol=1e-6)
    assert not out[0].any() and not out[7].any()


def test_device_scatter_from_root(world, xla):
    # per-rank buffers (8, 8, 3); only root's row is significant
    rng = np.random.default_rng(12)
    host = rng.standard_normal((8, 8, 3)).astype(np.float32)
    dev = xla.make_world_array(host)
    out = np.asarray(world.scatter_array(dev, root=4))
    # rank i receives root's block i
    np.testing.assert_allclose(out, host[4], rtol=1e-6)


def test_device_scan_exscan(world, xla):
    host, dev = _world_data(xla, seed=13)
    out = np.asarray(world.scan_array(dev))
    np.testing.assert_allclose(out, np.cumsum(host, 0), rtol=1e-4)
    ex = np.asarray(world.exscan_array(dev))
    np.testing.assert_array_equal(ex[0], np.zeros_like(ex[0]))
    np.testing.assert_allclose(ex[1:], np.cumsum(host, 0)[:-1], rtol=1e-4)


def test_device_allgatherv(world, xla):
    host = np.random.default_rng(14).standard_normal((8, 4, 2)) \
        .astype(np.float32)
    dev = xla.make_world_array(host)
    counts = [1, 2, 3, 4, 4, 3, 2, 1]
    outs = world.allgatherv_array(dev, counts)
    for i, o in enumerate(outs):
        np.testing.assert_allclose(np.asarray(o), host[i, :counts[i]],
                                   rtol=1e-6)


def test_device_alltoallv(world, xla):
    host = np.arange(8 * 8 * 3, dtype=np.float32).reshape(8, 8, 3)
    dev = xla.make_world_array(host)
    # asymmetric so a counts[i][j]/counts[j][i] transpose bug is caught
    counts = [[(2 * i + j) % 4 for j in range(8)] for i in range(8)]
    outs = world.alltoallv_array(dev, counts)
    for i in range(8):
        for j in range(8):
            np.testing.assert_array_equal(
                np.asarray(outs[i][j]), host[j, i, :counts[j][i]])


def test_persistent_allreduce(world, xla):
    host, dev = _world_data(xla, seed=15)
    h = world.allreduce_array_init(dev)
    out = np.asarray(h(dev))
    np.testing.assert_allclose(out, host.sum(0), rtol=1e-5)
    req = h.start(dev)
    req.wait()
    np.testing.assert_allclose(np.asarray(req.result), host.sum(0),
                               rtol=1e-5)
    # same shape/op/dtype shares the compiled program with the eager path
    assert h.fn is xla._cache[("allreduce", "SUM", dev.shape, dev.dtype)][0]


def test_spc_device_counters_bump(world, xla):
    from ompi_tpu.runtime import spc

    before = spc.read("device_collectives")
    host, dev = _world_data(xla, seed=16)
    world.allreduce_array(dev)
    assert spc.read("device_collectives") >= before + 1


def test_alltoallw_per_peer_dtypes(world):
    """MPI_Alltoallw: per-peer buffers and datatypes
    (``ompi/mpi/c/alltoallw.c``) — conductor matrix form."""
    n = world.size
    # sendbufs[src][dst]: int32 to even receivers, float64 to odd
    sendbufs = [[np.array([s], np.int32) if d % 2 == 0
                 else np.array([s + 0.5], np.float64) for d in range(n)]
                for s in range(n)]
    recvtypes = [np.int32 if r % 2 == 0 else np.float64 for r in range(n)]
    out = world.alltoallw(sendbufs, recvtypes)
    for r in range(n):
        for s in range(n):
            got = out[r][s][0]
            assert got == (s if r % 2 == 0 else s + 0.5), (r, s, got)
