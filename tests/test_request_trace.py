"""otpu-req — per-request distributed tracing, tail-cohort attribution,
and SLO burn-rate accounting.

Coverage layers:

* pure units: request-key (``rid.hop``) round-trip through the real
  trace ring and Chrome export; SLO-accountant window math (burn rate,
  window pruning vs full-run totals, inert while no target is set);
* flight-recorder classification: a survivor whose recovery path dies
  on a secondary exception must dump ``proc-failed`` (the failed-set
  already observed wins), never ``uncaught`` — the fleet-soak flake;
* in-process engines (colocated + staged over ``as_rank`` views):
  every completed request decomposes into six stages that reconcile
  against its own e2e (stage-sum/e2e in (0, 1.25] — the single-stamp
  discipline pin) and renders a complete ``rid.hop`` arrow chain; the
  staged chain's middle hop rides the KV slab's Pready keys;
* multiprocess under tpurun: THE chaos-armed 2-pool/2-tenant soak with
  a designed-slow worker (``delay:ms=8,rank=2,site=serve_work``) —
  >=95% of completed requests decompose, the p99 tail cohort names a
  stage/tenant consistent with the slow worker, and the telemetry
  plane's burn rate agrees with the exact per-request sample within a
  declared band.
"""
import json
import os
import subprocess
import sys
import threading

import pytest

import ompi_tpu
from ompi_tpu.base.var import registry
from ompi_tpu.tools.otpu_analyze import (REQ_STAGES, _req_collect,
                                         requests_report)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ pure units

def test_request_key_round_trip():
    """A (rid, hop) flow key survives the real ring -> chrome export ->
    analyzer collect round trip: the export renders the dot-joined id
    at the TOP LEVEL of the flow event (Chrome's binding field), and
    ``_req_collect`` parses it back to the same (rid, hop) ints."""
    from ompi_tpu.runtime import trace

    registry.set("otpu_trace_enable", True)
    registry.set("otpu_trace_requests", True)
    trace.reset_for_testing()
    try:
        assert trace.requests_enabled is True
        t0 = trace.now()
        trace.flow_start("serve_req", (7, 0), t0)
        trace.flow_finish("serve_req", (7, 0))
        trace.flow_start("serve_req", (7, 2))
        trace.flow_finish("serve_req", (7, 2))
        trace.span("req_queue", "serve_req", t0,
                   args={"rid": 7, "tenant": "t", "pool": "p",
                         "worker": 1})
        evs = trace.chrome_events()
        halves = [e for e in evs if e.get("ph") in ("s", "f")
                  and e.get("name") == "serve_req"]
        assert [e["id"] for e in halves] == ["7.0", "7.0", "7.2", "7.2"]
        spans, flows = _req_collect(evs)
        assert set(flows) == {7} and set(flows[7]) == {0, 2}
        for hop in flows[7].values():
            assert set(hop) == {"s", "f"}
        assert set(spans) == {7} and "queue" in spans[7]
    finally:
        registry.set("otpu_trace_enable", False)
        registry.set("otpu_trace_requests", False)
        trace.reset_for_testing()


def test_slo_accountant_window_math():
    """Burn rate is (windowed breach fraction) / 1% budget; the rolling
    window prunes old completions while the full-run totals keep them;
    goodput counts only in-SLO completions."""
    from ompi_tpu.runtime import telemetry
    from ompi_tpu.serving import fleet  # noqa: F401  (registers target var)

    target = registry.lookup("otpu_serving_slo_p99_ms")
    window = registry.lookup("otpu_serving_slo_window_s")
    target.set(50.0)
    acct = telemetry.SloAccountant()
    try:
        for dur in (10.0, 20.0, 30.0):
            assert acct.observe("p", "ten", dur) is True
        assert acct.observe("p", "ten", 80.0) is False   # breach
        snap = acct.snapshot()
        cell = snap["pools"]["p"]["ten"]
        assert snap["target_ms"] == 50.0
        assert snap["budget"] == telemetry.SLO_BUDGET == 0.01
        assert cell["total"] == 4 and cell["breaches"] == 1
        # burn = (1/4) / 0.01 — 25x the error budget
        assert cell["burn"] == pytest.approx(25.0)
        assert cell["goodput_rps"] > 0
        assert cell["run_total"] == 4 and cell["run_breaches"] == 1
        # age the window out: everything prunes, run totals survive
        with acct._lock:
            dq = acct._win[("p", "ten")]
            aged = [(t - 3600.0, ok) for t, ok in dq]
            dq.clear()
            dq.extend(aged)
        cell = acct.snapshot()["pools"]["p"]["ten"]
        assert cell["total"] == 0 and cell["breaches"] == 0
        assert cell["burn"] == 0.0
        assert cell["run_total"] == 4 and cell["run_breaches"] == 1
    finally:
        target.set(0)
        window.set(60.0)


def test_slo_accountant_inert_without_target():
    """No target (the default) means NO state, no SPC traffic, and a
    None snapshot — the serving hot path pays one float compare."""
    from ompi_tpu.runtime import spc, telemetry
    from ompi_tpu.serving import fleet  # noqa: F401

    assert float(registry.lookup("otpu_serving_slo_p99_ms").value
                 or 0.0) == 0.0
    acct = telemetry.SloAccountant()
    before = spc.read("slo_goodput"), spc.read("slo_breaches")
    assert acct.observe("p", "ten", 1e9) is True      # even a "breach"
    assert acct.snapshot() is None
    assert not acct._win and not acct._totals
    assert (spc.read("slo_goodput"), spc.read("slo_breaches")) == before


# ----------------------------------------------- flight classification

def _hook_dumps(monkeypatch, failed):
    from ompi_tpu.ft import state as ft_state
    from ompi_tpu.runtime import flight

    dumps = []
    monkeypatch.setattr(flight, "dump",
                        lambda reason, detail="": dumps.append(
                            (reason, detail)))
    monkeypatch.setattr(ft_state, "failed_ranks", lambda: set(failed))
    monkeypatch.setattr(flight, "_orig_excepthook", lambda *a: None)
    flight._excepthook(ValueError, ValueError("boom"), None)
    return dumps


def test_flight_excepthook_prefers_proc_failed(monkeypatch):
    """The fleet-soak flake: a survivor observing dead peers dies on a
    secondary exception (its recovery-path coord RPC timed out) — the
    dump must classify by the failure already observed (proc-failed,
    failed set in the detail), with the exception riding along."""
    dumps = _hook_dumps(monkeypatch, failed={2})
    assert len(dumps) == 1
    reason, detail = dumps[0]
    assert reason == "proc-failed"
    assert detail.startswith("2 ") and "ValueError('boom')" in detail


def test_flight_excepthook_uncaught_when_no_failures(monkeypatch):
    dumps = _hook_dumps(monkeypatch, failed=())
    assert dumps == [("uncaught", "ValueError('boom')")]


# ------------------------------------------------- in-process engines

@pytest.fixture(scope="module")
def world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    from ompi_tpu.mca.part import part_framework

    part_framework().open()
    yield w
    rt.reset_for_testing()


@pytest.fixture()
def requests_on():
    from ompi_tpu.runtime import trace

    registry.set("otpu_trace_enable", True)
    registry.set("otpu_trace_requests", True)
    trace.reset_for_testing()
    assert trace.requests_enabled
    yield
    registry.set("otpu_trace_enable", False)
    registry.set("otpu_trace_requests", False)
    trace.reset_for_testing()


def _run_engine(world, stages, n_requests):
    from ompi_tpu.serving import ContinuousBatchScheduler, Router, \
        ShardWorker
    from ompi_tpu.serving.driver import PoissonDriver

    if stages:
        workers = [ShardWorker(world.as_rank(1), router=0,
                               role="prefill", peer=2, slots=8,
                               kv_elems=64),
                   ShardWorker(world.as_rank(2), router=0,
                               role="decode", peer=1, slots=8,
                               kv_elems=64, kv_partitions=16)]
    else:
        workers = [ShardWorker(world.as_rank(r), router=0)
                   for r in (1, 2)]
    threads = [threading.Thread(target=wk.serve, daemon=True)
               for wk in workers]
    for t in threads:
        t.start()
    r = Router(world.as_rank(0),
               scheduler=ContinuousBatchScheduler(max_batch=8,
                                                  max_batch_tokens=8192,
                                                  slots=8),
               workers=[1, 2], stages=stages, decode_chunk=3,
               kv_elems=64)
    rep = PoissonDriver(rate_rps=800, n_requests=n_requests,
                        seed=6).run(r, max_wall_s=90)
    r.shutdown()
    for t in threads:
        t.join(timeout=10)
    return rep


def test_colocated_requests_decompose(world, requests_on):
    """Satellite pin (single-stamp discipline): every completed request
    decomposes into the six stages, and the stage sum reconciles
    against the request's OWN e2e — in (0, 1.25] — which fails if any
    lifecycle point double-reads now() or a span pair crosses."""
    from ompi_tpu.runtime import spc, trace

    rep = _run_engine(world, stages=False, n_requests=16)
    report = requests_report(trace.chrome_events())
    assert report["requests_seen"] == rep["requests"] == 16
    assert report["decomposed"] == 16
    assert set(report["stage_median_us"]) == set(REQ_STAGES)
    band = report["stage_over_e2e"]
    assert 0.0 < band["min"] and band["max"] <= 1.25, band
    # colocated chains skip the kv hop (no slab stream) but still run
    # dispatch (0) -> completion (2) with both halves of each hop
    assert report["flows"]["chains_complete"] == 16
    assert spc.read("req_traced") >= 16


def test_staged_requests_full_chain(world, requests_on):
    """Disaggregated prefill/decode: the middle hop of the arrow chain
    rides the KV slab's per-sequence Pready partition key, so the
    sample chain has all three hops and the kv stage is non-trivial."""
    from ompi_tpu.runtime import trace

    rep = _run_engine(world, stages=True, n_requests=12)
    report = requests_report(trace.chrome_events())
    assert report["requests_seen"] == rep["requests"] == 12
    assert report["decomposed"] == 12
    band = report["stage_over_e2e"]
    assert 0.0 < band["min"] and band["max"] <= 1.25, band
    flows = report["flows"]
    assert flows["chains_complete"] == 12
    assert len(flows["sample"]["hops"]) == 3, flows["sample"]
    # every staged request streamed one KV block: the kv stage median
    # is a real measured wait, not a zero-width placeholder
    assert report["stage_median_us"]["kv"] > 0


# --------------------------------------------------- tpurun chaos soak

_SOAK = """
import json, sys
import ompi_tpu

w = ompi_tpu.init()
if w.rank == 0:
    from ompi_tpu.runtime import telemetry
    from ompi_tpu.serving import FleetController, MixedPoissonDriver
    fleet = FleetController(w, tenants={"ten_a": 2, "ten_b": 1})
    drv = MixedPoissonDriver({
        "ten_a": dict(model="m_a", rate_rps=300, n_requests=int(sys.argv[1]),
                      prompt_lens=(4, 16), decode_lens=(4, 10),
                      prefixes=2, prefix_len=16),
        "ten_b": dict(model="m_b", rate_rps=200, n_requests=int(sys.argv[2]),
                      prompt_lens=(4, 16), decode_lens=(4, 10),
                      prefixes=1, prefix_len=16),
    }, seed=7)
    rep = drv.run(fleet, max_wall_s=150)
    slo = telemetry.slo_snapshot()
    fleet.shutdown()
    print("REQSOAK " + json.dumps({"requests": rep["requests"],
                                   "slo": slo}), flush=True)
else:
    if w.rank == 2:
        from ompi_tpu.ft import chaos
        chaos.install_spec("delay:ms=8,rank=2,site=serve_work")
    from ompi_tpu.serving import ShardWorker
    ShardWorker(w, router=0).serve()
ompi_tpu.finalize()
"""

_SLO_MS = 50.0


def test_request_soak_chaos_tail_and_slo(tmp_path):
    """THE acceptance scenario: 2 pools / 2 tenants under mixed Poisson
    load with rank 2 (a pool-m_a worker) designed slow by 8ms per
    micro-batch.  Over the run's MERGED timeline: >=95% of completed
    requests decompose into six stages each reconciling against its
    own e2e; a complete router->worker->router arrow chain renders for
    at least one sampled request; the p99 tail cohort names a stage
    consistent with the slow worker and the tenant routed onto it; and
    the telemetry plane's rolling burn rate agrees with the exact
    per-request breach fraction within the declared band (25% relative
    + 0.05 absolute on the breach fraction)."""
    from ompi_tpu.tools.otpu_analyze import load_events

    script = tmp_path / "req_soak.py"
    script.write_text(_SOAK)
    td = tmp_path / "traces"
    n_a, n_b = 24, 16
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", "5",
         "--pool", "m_a:1,2", "--pool", "m_b:3,4",
         "--mca", "otpu_trace_enable", "1",
         "--mca", "otpu_trace_requests", "1",
         "--mca", "otpu_trace_dir", str(td),
         "--mca", "otpu_serving_slo_p99_ms", str(_SLO_MS),
         sys.executable, str(script), str(n_a), str(n_b)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    line = next((ln for ln in r.stdout.splitlines() if "REQSOAK" in ln),
                None)
    assert r.returncode == 0 and line, r.stdout + r.stderr
    soak = json.loads(line.split("REQSOAK ", 1)[1])
    assert soak["requests"] == n_a + n_b

    report = requests_report(load_events([str(td)]), slo_ms=_SLO_MS)
    # >=95% decompose, each reconciling against its own e2e
    assert report["requests_seen"] >= 0.95 * (n_a + n_b)
    assert report["decomposed_fraction"] >= 0.95, report
    band = report["stage_over_e2e"]
    assert 0.0 < band["min"] and band["max"] <= 1.25, band
    # the merged timeline renders a complete per-request arrow chain
    flows = report["flows"]
    assert flows["chains_complete"] >= 1, flows
    sample = flows["sample"]
    assert sample["hops"][0].startswith("0:r0->") \
        and sample["hops"][-1].endswith("->r0"), sample
    # tail attribution: the 8ms/micro-batch delay on rank 2 lands in
    # the decode stage (or backs the queue up); the cohort is the
    # tenant whose pool holds the slow worker
    tail = report["tail"]
    assert tail["cohort"] >= 1
    assert tail["dominant_stage"] in ("decode", "queue"), tail
    assert tail["hottest_tenant"] == "ten_a", tail
    if tail["dominant_stage"] == "decode":
        assert tail["bounding_worker"] == 2, tail
    # SLO agreement: telemetry's windowed accounting vs the analyzer's
    # exact per-request sample, within the declared band
    exact = report["slo_exact"]
    assert exact["target_ms"] == _SLO_MS
    slo = soak["slo"]
    assert slo and slo["target_ms"] == _SLO_MS
    tot = breaches = 0
    for tenants in slo["pools"].values():
        for cell in tenants.values():
            tot += cell["run_total"]
            breaches += cell["run_breaches"]
    assert tot >= 0.95 * (n_a + n_b)
    frac_t = breaches / max(1, tot)
    frac_e = exact["breach_fraction"]
    assert abs(frac_t - frac_e) <= 0.05 + 0.25 * frac_e, (
        f"telemetry breach fraction {frac_t:.4f} vs exact "
        f"{frac_e:.4f} — outside the declared band")
