"""Randomized traffic worker for the vprotocol replay fuzz.

Both ranks derive the SAME op plan from ``VPF_SEED`` (a piecewise-
deterministic exchange program: per round, single- or dual-comm sends
with seed-chosen comms/tags, each side consuming channels in plan-chosen
order) plus a kill spec for rank 1 (after its sends, or between its two
recvs of a dual round — the in-flight-message windows).  The pytest
side replays the crashed job from the pessimist logs and checks the
final states against :func:`simulate`.
"""
import os
import random

import numpy as np

VEC = 4


def build_plan(seed: int, rounds: int):
    """(ops, kill_round, kill_pos) — identical on every rank."""
    rng = random.Random(seed)
    ops = []
    for _ in range(rounds):
        ops.append(dict(
            comm=rng.choice(["w", "d"]),
            tag=rng.choice([5, 9]),
            dual=rng.random() < 0.5,     # one message per comm, both comms
            swap=rng.random() < 0.5,     # receiver consumes comms swapped
        ))
    kill_round = rng.randrange(1, rounds - 1)
    kill_pos = rng.choice(["after_send", "mid_recv"])
    if kill_pos == "mid_recv":
        ops[kill_round]["dual"] = True   # the window needs two recvs
    return ops, kill_round, kill_pos


def payloads(state, rd):
    """The two wire payloads a rank emits in round rd (B unused when
    the round is single-comm)."""
    return 0.5 * state + float(rd), 0.25 * state - float(rd)


def fold(state, p_a, p_b, rd):
    """Receiver's asymmetric state update (a swapped A/B corrupts it)."""
    return 0.45 * state + 0.3 * p_a - 0.15 * p_b + float(rd)


def simulate(seed: int, rounds: int, niter: int):
    """Failure-free reference recurrence for ``niter`` rounds."""
    ops, _, _ = build_plan(seed, rounds)
    states = [np.full(VEC, 1.0), np.full(VEC, 2.0)]
    for rd in range(niter):
        spec = ops[rd]
        prev = [s.copy() for s in states]
        for r in (0, 1):
            p_a, p_b = payloads(prev[1 - r], rd)
            if not spec["dual"]:
                p_b = np.zeros(VEC)
            states[r] = fold(prev[r], p_a, p_b, rd)
    return states


def main():
    import ompi_tpu

    seed = int(os.environ["VPF_SEED"])
    rounds = int(os.environ["VPF_ROUNDS"])
    niter = int(os.environ["VPF_NITER"])
    die = os.environ.get("VPF_DIE", "") == "1"
    ops, kill_round, kill_pos = build_plan(seed, rounds)

    w = ompi_tpu.init()
    d = w.dup()
    comms = {"w": w, "d": d}
    r = w.rank
    peer = 1 - r
    state = np.full(VEC, float(r + 1))
    for rd in range(niter):
        spec = ops[rd]
        p_a, p_b = payloads(state, rd)
        if spec["dual"]:
            # emission order differs per rank; the receiver's plan-chosen
            # consumption order can invert it -> cross-channel interleave
            first, second = (("w", p_a), ("d", p_b)) if r == 0 \
                else (("d", p_b), ("w", p_a))
            q1 = comms[first[0]].isend(first[1], dest=peer, tag=spec["tag"])
            q2 = comms[second[0]].isend(second[1], dest=peer,
                                        tag=spec["tag"])
            if (die and r == 1 and rd == kill_round
                    and kill_pos == "after_send"):
                os._exit(9)         # both of peer's messages in flight
            order = ["w", "d"] if not spec["swap"] else ["d", "w"]
            bufs = {}
            got_one = False
            for c in order:
                bufs[c] = np.empty(VEC)
                comms[c].recv(bufs[c], source=peer, tag=spec["tag"])
                if (die and r == 1 and rd == kill_round
                        and kill_pos == "mid_recv" and not got_one):
                    os._exit(9)     # second channel's message in flight
                got_one = True
            q1.wait()
            q2.wait()
            state = fold(state, bufs["w"], bufs["d"], rd)
        else:
            c = comms[spec["comm"]]
            q = c.isend(p_a, dest=peer, tag=spec["tag"])
            if (die and r == 1 and rd == kill_round
                    and kill_pos == "after_send"):
                os._exit(9)         # peer's message for me in flight
            inb = np.empty(VEC)
            c.recv(inb, source=peer, tag=spec["tag"])
            q.wait()
            state = fold(state, inb, np.zeros(VEC), rd)
    np.save(os.environ["VPF_OUT"] + f".{r}.npy", state)
    print(f"VPF DONE {r}", flush=True)
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
