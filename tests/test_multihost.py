"""Multi-host wire-up: external coord service + per-"host" rank launch.

The reference's multi-node story (SURVEY §3.4): a launcher starts daemons
per host, procs PMIx_Init back to them.  Our equivalent: any external
launcher (slurm/k8s) exports ``OTPU_COORD`` pointing at the coord service
and per-rank identity env — exactly what this test does by hand, WITHOUT
tpurun, across two emulated hosts (``OTPU_NODE_ID`` hostA/hostB).

Asserts the transport matrix is what a two-host job must produce: btl/sm
within a host, btl/tcp (the DCN path) across hosts — the hook/comm_method
dump decision, selected per-peer by bml/r2 from modexed node identity.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from ompi_tpu.rte.coord import CoordServer

_WORKER = textwrap.dedent("""
    import os
    import numpy as np
    import ompi_tpu

    w = ompi_tpu.init()
    rank, n = w.rank, w.size
    me_node = os.environ["OTPU_NODE_ID"]

    # transport matrix: same-node neighbour via sm, cross-node via tcp
    pml = w.pml
    inner = getattr(pml, "_inner", pml)       # unwrap monitoring/vprotocol
    while hasattr(inner, "_inner"):
        inner = inner._inner
    bml = inner.bml
    same = rank ^ 1            # ranks 0,1 on hostA; 2,3 on hostB
    cross = (rank + 2) % n
    ep_same = bml.endpoint(same)
    ep_cross = bml.endpoint(cross)
    assert ep_same.btl.name == "sm", f"want sm intra-node, got {ep_same.btl.name}"
    assert ep_cross.btl.name == "tcp", f"want tcp inter-node, got {ep_cross.btl.name}"

    # cross-host p2p over tcp
    if rank == 0:
        w.send(np.arange(5.0), dest=2, tag=3)
    elif rank == 2:
        buf = np.zeros(5)
        st = w.recv(buf, source=0, tag=3)
        assert buf.tolist() == [0, 1, 2, 3, 4]

    # world collective spanning both hosts
    out = w.allreduce(np.array([rank + 1.0]))
    assert out[0] == n * (n + 1) / 2, out

    # han two-level composition must see 2 nodes x 2 ranks
    color = w.split_type("shared").size
    assert color == 2, f"intra-node comm size {color}"
    print(f"MULTIHOST_OK rank={rank} node={me_node}")
    ompi_tpu.finalize()
""")


def test_two_emulated_hosts_external_launcher(tmp_path):
    n = 4
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    server = CoordServer(nprocs=n)
    host, port = server.addr
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for rank in range(n):
            env = dict(os.environ)
            env.update({
                "OTPU_COORD": f"{host}:{port}",
                "OTPU_RANK": str(rank),
                "OTPU_NPROCS": str(n),
                "OTPU_NODE_ID": "hostA" if rank < 2 else "hostB",
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": pkg_root + os.pathsep
                + env.get("PYTHONPATH", ""),
            })
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=100)
            outs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{out}"
            assert "MULTIHOST_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.close()


_FT_WORKER = textwrap.dedent("""
    import os, sys, time
    import numpy as np
    import ompi_tpu
    from ompi_tpu.ft import state as ft_state

    w = ompi_tpu.init()
    rank, n = w.rank, w.size
    w.barrier()              # transports up, endpoints warmed, hb flowing
    print(f"READY {rank}", flush=True)
    if rank == 2:
        sys.stdin.readline()   # parent signals AFTER killing the coord
        os._exit(1)            # die abruptly with the coord already gone
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        if ft_state.is_failed(2):
            print(f"DETECTED {rank}", flush=True)
            os._exit(0)      # coord is dead: no clean finalize possible
        time.sleep(0.2)
    print(f"TIMEOUT {rank}", flush=True)
    os._exit(3)
""")


def test_detector_survives_coord_death(tmp_path):
    """VERDICT weak #4: the failure detector must not ride the coord
    SPOF.  Wire up 3 ranks, KILL the coordination service, then kill a
    rank — survivors must still detect it via p2p btl heartbeats
    (``comm_ft_detector.c``'s active-message carrier + the propagator's
    p2p flood)."""
    import threading
    import time

    n = 3
    script = tmp_path / "ft_worker.py"
    script.write_text(_FT_WORKER)
    server = CoordServer(nprocs=n)
    host, port = server.addr
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    outs = {i: "" for i in range(n)}
    ready = {i: threading.Event() for i in range(n)}

    def pump(i, p):
        for line in p.stdout:
            outs[i] += line
            if "READY" in line:
                ready[i].set()

    pumps = []
    try:
        for rank in range(n):
            env = dict(os.environ)
            env.update({
                "OTPU_COORD": f"{host}:{port}",
                "OTPU_RANK": str(rank),
                "OTPU_NPROCS": str(n),
                "JAX_PLATFORMS": "cpu",
                "OTPU_MCA_ft_detector": "1",
                "OTPU_MCA_ft_detector_period": "0.3",
                "OTPU_MCA_ft_detector_timeout": "2.0",
                "OTPU_MCA_ft_detector_startup_grace": "2.0",
                "PYTHONPATH": pkg_root + os.pathsep
                + env.get("PYTHONPATH", ""),
            })
            env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, str(script)], env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        pumps = [threading.Thread(target=pump, args=(i, p), daemon=True)
                 for i, p in enumerate(procs)]
        for t in pumps:
            t.start()
        for i in range(n):
            assert ready[i].wait(90), (i, outs)
        server.close()            # <-- the SPOF dies here, BEFORE the kill
        time.sleep(0.5)
        procs[2].stdin.write("die\n")
        procs[2].stdin.close()
        rcs = {}
        for i, p in enumerate(procs):
            rcs[i] = p.wait(timeout=60)
        for t in pumps:
            t.join(5)
        assert rcs[2] == 1                      # the killed rank
        for i in (0, 1):
            assert "DETECTED" in outs[i], (i, outs[i], rcs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        try:
            server.close()
        except Exception:
            pass
