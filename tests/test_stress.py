"""Seeded protocol-crossover stress: mixed message sizes, tags and
orderings driven across every host-path protocol boundary in one job —
eager (<=512k), RNDV, RGET (>512k), multi-rail striping (>2m) — plus a
mixed-collective soak against numpy goldens.  The reference leans on
external suites (ompi-tests/MTT) for this class of coverage; here it is
in-tree and deterministic (fixed seed)."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


def test_p2p_protocol_crossover_stress(tmp_path):
    script = tmp_path / "p2p_stress.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu

        w = ompi_tpu.init()
        rng = np.random.default_rng(1234)     # same stream on both ranks
        # sizes straddle every protocol boundary: eager<=512k, rndv/rget
        # >512k, striping >2m; plus odd sizes and 1-byte messages
        sizes = [1, 7, 1024, 65536, 262144, 524287, 524289,
                 1 << 20, (2 << 20) + 13, 3 << 20]
        NOPS = 60
        plan = [(int(rng.integers(len(sizes))), int(rng.integers(50)),
                 int(rng.integers(2))) for _ in range(NOPS)]
        peer = 1 - w.rank
        for i, (si, tag, nb) in enumerate(plan):
            n = sizes[si]
            if w.rank == 0:
                data = (np.arange(n, dtype=np.uint8) + i) % 251
                if nb:
                    w.isend(data, dest=peer, tag=tag).wait()
                else:
                    w.send(data, dest=peer, tag=tag)
            else:
                buf = np.empty(n, np.uint8)
                st = w.recv(buf, source=0, tag=tag)
                want = (np.arange(n, dtype=np.uint8) + i) % 251
                assert np.array_equal(buf, want), (i, n, tag)
        # reverse direction with several in-flight irecvs (ooo matching)
        if w.rank == 1:
            for i in range(8):
                n = sizes[i % len(sizes)]
                w.send((np.arange(n, dtype=np.uint8) * 3 + i) % 249,
                       dest=0, tag=100 + i)
        else:
            reqs, bufs = [], []
            for i in range(8):
                n = sizes[i % len(sizes)]
                bufs.append(np.empty(n, np.uint8))
                reqs.append(w.irecv(bufs[-1], source=1, tag=100 + i))
            for i, r in enumerate(reqs):
                r.wait()
                n = sizes[i % len(sizes)]
                want = (np.arange(n, dtype=np.uint8) * 3 + i) % 249
                assert np.array_equal(bufs[i], want), i
        print(f"P2P STRESS OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.stdout.count("P2P STRESS OK") == 2, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr


def test_collective_mixed_size_soak(tmp_path):
    script = tmp_path / "coll_soak.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api import op

        w = ompi_tpu.init()
        n = w.size
        rng = np.random.default_rng(77)       # same stream on all ranks
        # sizes straddle the coll/sm slot boundary (2m) and the tuned
        # ladder breakpoints
        sizes = [8, 1000, 65536, 262144, 1 << 20, (2 << 20) + 40]
        for it in range(12):
            nel = sizes[int(rng.integers(len(sizes)))] // 8
            coll = int(rng.integers(4))
            base = np.arange(nel, dtype=np.float64)
            mine = base * (w.rank + 1) + it
            all_rows = np.stack([base * (r + 1) + it for r in range(n)])
            if coll == 0:
                got = w.allreduce(mine)
                np.testing.assert_allclose(got, all_rows.sum(0), rtol=1e-12)
            elif coll == 1:
                got = w.allreduce(mine, op.MAX)
                np.testing.assert_allclose(got, all_rows.max(0))
            elif coll == 2:
                got = w.bcast(mine if w.rank == it % n else
                              np.empty_like(mine), root=it % n)
                np.testing.assert_allclose(
                    got, base * (it % n + 1) + it)
            else:
                got = w.allgather(mine)
                np.testing.assert_allclose(np.asarray(got), all_rows)
        w.barrier()
        print(f"COLL SOAK OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(4, script)
    assert r.stdout.count("COLL SOAK OK") == 4, r.stdout + r.stderr
    assert r.returncode == 0, r.stdout + r.stderr
