"""MPI-4 Sessions (``ompi_tpu/instance`` + ``api/session.py``): boot
without MPI_Init, pset enumeration, sessions-model communicator
construction, instance refcount interleavings with the world model, and
the error paths.

Single-process tests run against the conductor device world (conftest's
8 virtual devices); the multiprocess cases launch real tpurun jobs where
psets come from the coord service.
"""
import os
import random
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.api.errhandler import ERRORS_RETURN
from ompi_tpu.api.errors import ErrorClass, MpiError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpurun(n, script, extra=(), timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
           *extra, sys.executable, str(script)]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)


@pytest.fixture(autouse=True)
def _fresh_runtime():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    yield
    rt.reset_for_testing()


# -- sessions without MPI_Init ------------------------------------------

def test_session_boots_without_world_init():
    from ompi_tpu import instance as inst_mod

    s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    assert not ompi_tpu.initialized()          # no MPI_Init happened
    assert inst_mod.refcount() == 1
    names = s.psets()
    assert "mpi://WORLD" in names and "mpi://SELF" in names
    assert s.get_num_psets() == len(names)
    assert s.get_nth_pset(0) == names[0]
    info = s.get_pset_info("mpi://WORLD")
    assert int(info.get("mpi_size")) == len(
        s.group_from_pset("mpi://WORLD"))
    g = ompi_tpu.Group.from_session_pset(s, "mpi://SELF")
    assert g.size == 1
    s.finalize()
    assert inst_mod.refcount() == 0


def test_session_comm_from_pset_collectives():
    s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    g = s.group_from_pset("mpi://WORLD")
    comm = ompi_tpu.Comm.create_from_group(g, "t0")
    assert comm is not None and comm.size == g.size
    assert comm.cid >= 2          # 0/1 stay reserved for WORLD/SELF
    y = comm.allreduce_array(np.ones((comm.size, 2), np.float32))
    assert float(np.asarray(y).ravel()[0]) == comm.size
    comm.free()
    s.finalize()


def test_two_concurrent_sessions_disjoint_comms():
    s1 = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    s2 = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    world = s1.group_from_pset("mpi://WORLD")
    n = world.size
    g1 = world.incl(range(n // 2))
    g2 = world.incl(range(n // 2, n))
    c1 = ompi_tpu.Comm.create_from_group(g1, "lo")
    c2 = ompi_tpu.Comm.create_from_group(g2, "hi")
    assert c1.cid != c2.cid
    assert set(c1.group.world_ranks).isdisjoint(c2.group.world_ranks)
    y1 = c1.allreduce_array(np.ones((c1.size, 1), np.float32))
    y2 = c2.allreduce_array(np.full((c2.size, 1), 2.0, np.float32))
    assert float(np.asarray(y1).ravel()[0]) == c1.size
    assert float(np.asarray(y2).ravel()[0]) == 2.0 * c2.size
    # finalizing the session that built c1 must not kill the runtime
    # (s2 still holds a reference) nor c1 itself (comms are independent
    # objects per MPI-4)
    s1.finalize()
    y1b = c1.allreduce_array(np.ones((c1.size, 1), np.float32))
    assert float(np.asarray(y1b).ravel()[0]) == c1.size
    c1.free()
    c2.free()
    s2.finalize()


def test_intercomm_create_from_groups_single_process():
    s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    world = s.group_from_pset("mpi://WORLD")
    n = world.size
    lo = world.incl(range(n // 2))
    hi = world.incl(range(n // 2, n))
    # the conductor hosts rank 0, so the lo side is "my" side
    inter = ompi_tpu.Comm.create_intercomm_from_groups(
        lo, 0, hi, 0, "bridge")
    assert inter.is_inter
    assert inter.size == lo.size and inter.remote_size == hi.size
    assert inter.local_comm.size == lo.size
    with pytest.raises(MpiError):
        ompi_tpu.Comm.create_intercomm_from_groups(
            lo, 0, world, 0, "overlap")     # groups overlap
    inter.free()
    s.finalize()


# -- world init + session refcount interleavings ------------------------

def test_world_and_session_share_one_boot():
    from ompi_tpu import instance as inst_mod

    s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    inst_before = inst_mod.current()
    w = ompi_tpu.init()
    # world init joined the session's boot instead of re-booting
    assert inst_mod.current() is inst_before
    assert inst_mod.refcount() == 2
    assert w.rte is inst_before.rte
    ompi_tpu.finalize()
    # the session keeps the runtime alive past world finalize
    assert ompi_tpu.finalized()
    assert inst_mod.refcount() == 1
    g = s.group_from_pset("mpi://WORLD")
    c = ompi_tpu.Comm.create_from_group(g, "post-finalize")
    y = c.allreduce_array(np.ones((c.size, 1), np.float32))
    assert float(np.asarray(y).ravel()[0]) == c.size
    c.free()
    s.finalize()
    assert inst_mod.refcount() == 0


def test_init_finalize_init_under_refcounting():
    """The MPI-4 relaxation: MPI_Init after MPI_Finalize works (each
    init/finalize pair is one acquire/release of the instance)."""
    w1 = ompi_tpu.init()
    size1 = w1.size
    assert np.asarray(w1.allreduce(np.ones((size1, 1))))[0] == size1
    ompi_tpu.finalize()
    assert ompi_tpu.finalized()
    w2 = ompi_tpu.init()
    assert not ompi_tpu.finalized() and ompi_tpu.initialized()
    assert w2.size == size1
    assert np.asarray(w2.allreduce(np.ones((size1, 1))))[0] == size1
    ompi_tpu.finalize()


def test_finalize_order_fuzz():
    """Random interleavings of session opens/finalizes and world
    init/finalize: every order must keep the refcount consistent, end
    fully torn down, and allow the next round to boot."""
    from ompi_tpu import instance as inst_mod

    rng = random.Random(7)
    for round_no in range(4):
        owners = []      # closers, in open order
        n_open = rng.randint(1, 4)
        world_open = False
        for _ in range(n_open):
            if not world_open and rng.random() < 0.4:
                ompi_tpu.init()
                owners.append(ompi_tpu.finalize)
                world_open = True
            else:
                s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
                owners.append(s.finalize)
        assert inst_mod.refcount() == len(owners)
        rng.shuffle(owners)
        for i, close in enumerate(owners):
            close()
            assert inst_mod.refcount() == len(owners) - i - 1
        assert inst_mod.current() is None, f"round {round_no}"


# -- error paths --------------------------------------------------------

def test_session_error_paths():
    s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
    with pytest.raises(MpiError) as exc:
        s.get_pset_info("mpi://no-such-set")
    assert exc.value.error_class == ErrorClass.ERR_ARG
    with pytest.raises(MpiError):
        s.group_from_pset("mpi://no-such-set")
    with pytest.raises(MpiError):
        s.get_nth_pset(10**6)
    s.finalize()
    # every post-finalize use is ERR_SESSION
    for call in (s.finalize, s.get_num_psets, s.psets,
                 lambda: s.group_from_pset("mpi://WORLD"),
                 lambda: s.get_pset_info("mpi://WORLD"),
                 s.get_info):
        with pytest.raises(MpiError) as exc:
            call()
        assert exc.value.error_class == ErrorClass.ERR_SESSION


def test_create_from_group_needs_instance():
    with pytest.raises(MpiError) as exc:
        ompi_tpu.Comm.create_from_group(ompi_tpu.Group([0]), "orphan")
    assert exc.value.error_class == ErrorClass.ERR_SESSION


def test_session_info_and_errhandler():
    from ompi_tpu.api.info import Info

    info = Info({"app": "test"})
    s = ompi_tpu.Session.init(info=info, errhandler=ERRORS_RETURN)
    got = s.get_info()
    assert got.get("app") == "test"
    assert got.get("thread_level") == "MPI_THREAD_MULTIPLE"
    assert s.get_errhandler() is ERRORS_RETURN
    with pytest.raises(MpiError):
        s.call_errhandler(int(ErrorClass.ERR_OTHER))
    s.finalize()


# -- multiprocess: psets from the coord service -------------------------

def test_mp_sessions_psets_and_comms(tmp_path):
    """Sessions across real processes, NO MPI_Init anywhere: coord-
    served psets (builtin world, per-host, user --pset), the sessions-
    model construction chain, and an intercomm from bare groups."""
    script = tmp_path / "sess.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu
        from ompi_tpu.api.errhandler import ERRORS_RETURN

        s = ompi_tpu.Session.init(errhandler=ERRORS_RETURN)
        assert not ompi_tpu.initialized()
        names = s.psets()
        assert "mpi://WORLD" in names and "evens" in names, names
        assert any(n.startswith("mpi://host/") for n in names), names
        g = ompi_tpu.Group.from_session_pset(s, "mpi://WORLD")
        comm = ompi_tpu.Comm.create_from_group(g, "app")
        out = comm.allreduce(np.array([float(comm.rank + 1)]))
        assert float(np.asarray(out)[0]) == 6.0, out   # 1+2+3
        ge = s.group_from_pset("evens")
        assert ge.world_ranks == (0, 2), ge
        info = s.get_pset_info("evens")
        assert info.get("mpi_size") == "2"
        assert info.get("otpu_source") == "user"
        ce = ompi_tpu.Comm.create_from_group(ge, "even-side")
        if comm.rank % 2 == 0:
            assert ce is not None and ce.size == 2
            out = ce.allreduce(np.array([1.0]))
            assert float(np.asarray(out)[0]) == 2.0
            ce.free()
        else:
            assert ce is None      # not a member
        # intercomm from bare groups: evens vs odds
        godd = g.difference(ge)
        mine, other = (ge, godd) if comm.rank % 2 == 0 else (godd, ge)
        inter = ompi_tpu.Comm.create_intercomm_from_groups(
            mine, 0, other, 0, "eo")
        assert inter.is_inter and inter.remote_size == other.size
        if comm.rank == 0:
            inter.send(np.array([5.0]), dest=0, tag=2)
        elif comm.rank == 1:
            buf = np.zeros(1)
            inter.recv(buf, source=0, tag=2)
            assert buf[0] == 5.0
        print(f"MPSESS OK {comm.rank}", flush=True)
        inter.free(); comm.free()
        s.finalize()
    """))
    r = _tpurun(3, script, extra=("--pset", "evens:0,2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("MPSESS OK") == 3, r.stdout + r.stderr


def test_mp_world_init_after_finalize(tmp_path):
    """Init → finalize → init across real processes: the second world
    boots a fresh RTE boot-to-boot (new fences, new modex) and its
    collectives still work."""
    script = tmp_path / "reinit.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu

        w = ompi_tpu.init()
        assert float(np.asarray(w.allreduce(np.ones(1)))[0]) == w.size
        ompi_tpu.finalize()
        w = ompi_tpu.init()
        assert float(np.asarray(w.allreduce(np.ones(1)))[0]) == w.size
        print(f"REINIT OK {w.rank}", flush=True)
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, script)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("REINIT OK") == 2, r.stdout + r.stderr


def test_mp_shrink_publishes_surviving_pset(tmp_path):
    """The ULFM recovery hook: after a rank dies, the coord service
    advertises ``mpi://surviving`` and shrink publishes the agreed
    survivor set as a dynamic pset a session can resolve by name."""
    script = tmp_path / "shrink_pset.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        import numpy as np
        import ompi_tpu

        w = ompi_tpu.init()
        if w.rank == 1:
            os._exit(17)          # die without finalize
        deadline = time.time() + 30
        from ompi_tpu.ft import state as ft_state
        while not ft_state.is_failed(1):
            time.sleep(0.1)
            assert time.time() < deadline, "failure never detected"
        sub = w.shrink()
        assert 1 not in sub.group.world_ranks
        s = ompi_tpu.Session.init()
        names = s.psets()
        assert "mpi://surviving" in names, names
        surv = s.group_from_pset("mpi://surviving")
        assert 1 not in surv.world_ranks and w.rank in surv.world_ranks
        shrunk = [n for n in names if n.startswith("mpi://shrunk/")]
        assert shrunk, names
        g2 = s.group_from_pset(shrunk[0])
        assert tuple(g2.world_ranks) == tuple(sub.group.world_ranks)
        print(f"SHRINKPSET OK {w.rank}", flush=True)
        s.finalize()
        ompi_tpu.finalize()
    """))
    r = _tpurun(3, script, extra=("--enable-recovery",))
    assert r.stdout.count("SHRINKPSET OK") == 2, r.stdout + r.stderr
