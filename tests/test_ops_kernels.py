"""Pallas kernel + op-framework tests (run on the CPU mesh, interpret mode).

Reference model: the op/avx kernel tests ``test/datatype/reduce_local.c``
+ ``check_op.sh`` — every op kernel checked against a golden host
computation — and the op framework selection in
``ompi/mca/op/base/op_base_op_select.c``.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ompi_tpu.ops import pallas_reduce as pr


class TestPallasReduce:
    @pytest.mark.parametrize("op,npfn", [
        ("SUM", np.add), ("PROD", np.multiply),
        ("MAX", np.maximum), ("MIN", np.minimum),
    ])
    def test_combine2_float(self, op, npfn):
        rng = np.random.RandomState(3)
        a = rng.normal(size=(7, 531)).astype(np.float32)
        b = rng.normal(size=(7, 531)).astype(np.float32)
        out = pr.combine2(op, jnp.asarray(a), jnp.asarray(b))
        np.testing.assert_allclose(np.asarray(out), npfn(a, b), rtol=1e-6)

    @pytest.mark.parametrize("op,npfn", [
        ("BAND", np.bitwise_and), ("BOR", np.bitwise_or),
        ("BXOR", np.bitwise_xor),
    ])
    def test_combine2_bitwise(self, op, npfn):
        rng = np.random.RandomState(4)
        a = rng.randint(0, 1 << 30, size=773).astype(np.int32)
        b = rng.randint(0, 1 << 30, size=773).astype(np.int32)
        out = pr.combine2(op, jnp.asarray(a), jnp.asarray(b))
        np.testing.assert_array_equal(np.asarray(out), npfn(a, b))

    def test_combine2_logical(self):
        a = jnp.asarray([0, 1, 2, 0], jnp.int32)
        b = jnp.asarray([0, 0, 3, 5], jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(pr.combine2("LXOR", a, b)), [0, 1, 0, 1])

    @pytest.mark.parametrize("k", [2, 5, 8])
    def test_reduce_stack(self, k):
        rng = np.random.RandomState(k)
        x = rng.normal(size=(k, 3, 411)).astype(np.float32)
        out = pr.reduce_stack("SUM", jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5,
                                   atol=1e-6)

    def test_reduce_stack_k1_and_large(self):
        x = np.arange(10, dtype=np.float32).reshape(1, 10)
        np.testing.assert_array_equal(
            np.asarray(pr.reduce_stack("MAX", jnp.asarray(x))), x[0])
        big = np.ones((4, 70000), np.float32)
        np.testing.assert_array_equal(
            np.asarray(pr.reduce_stack("SUM", jnp.asarray(big))),
            np.full(70000, 4, np.float32))

    def test_device_fold_coverage(self):
        assert pr.device_fold("SUM", jnp.float32) is not None
        assert pr.device_fold("BAND", jnp.float32) is None  # bitwise≠float
        assert pr.device_fold("BAND", jnp.int32) is not None
        assert pr.device_fold("MAXLOC", jnp.float32) is None


class TestOpFramework:
    def test_selection_and_fallback(self):
        from ompi_tpu.api import op as op_mod
        from ompi_tpu.mca.op import base as op_base

        fn = op_mod.jax_fold(op_mod.SUM, jnp.float32)
        a, b = jnp.arange(8.0), jnp.ones(8)
        np.testing.assert_allclose(np.asarray(fn(a, b)),
                                   np.arange(8.0) + 1)
        # MAXLOC has no elementwise device kernel in any component
        with pytest.raises(Exception):
            op_mod.jax_fold(op_mod.MAXLOC, jnp.float32)
        assert op_base.select_fold("SUM", jnp.float32) is not None

    def test_exclude_component_var(self):
        """--mca op ^pallas_vpu forces the plain-XLA fold (reference:
        ``--mca op ^avx``)."""
        from ompi_tpu.base import mca
        from ompi_tpu.mca.op import base as op_base

        fw = mca.framework("op")
        names = set(fw.components) if fw.opened else None
        if names is not None:
            assert {"pallas_vpu", "xla"} <= names
        op_base.reset_cache()
        fold = op_base.select_fold("PROD", jnp.float32)
        a, b = jnp.full(4, 3.0), jnp.full(4, 2.0)
        np.testing.assert_allclose(np.asarray(fold(a, b)), np.full(4, 6.0))


class TestFlashAttention:
    def _rand(self, b=1, h=2, sq=64, skv=32, d=16):
        key = jax.random.PRNGKey(7)
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (b, h, sq, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, h, skv, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, h, skv, d), jnp.float32)
        return q, k, v

    def test_block_update_matches_softmax(self):
        from ompi_tpu.ops.flash_attention import flash_block_update

        q, k, v = self._rand()
        m = jnp.full(q.shape[:-1], -jnp.inf)
        num = jnp.zeros_like(q)
        den = jnp.zeros(q.shape[:-1])
        m, num, den = flash_block_update(q, k, v, m, num, den)
        k2, v2 = k * 0.5 + 1.0, v - 0.25
        m, num, den = flash_block_update(q, k2, v2, m, num, den)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, jnp.concatenate([k, k2], 2)) \
            / math.sqrt(q.shape[-1])
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                         jnp.concatenate([v, v2], 2))
        got = num / den[..., None]
        # CPU interpret is exact-ish; TPU MXU default precision ≈1e-3
        tol = 1e-5 if jax.default_backend() != "tpu" else 8e-3
        assert float(jnp.abs(got - ref).max()) < tol

    def test_ring_attention_flash_matches_jnp(self):
        """Flash and jnp ring paths agree on the 8-device sp mesh."""
        from ompi_tpu.base.jaxenv import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from ompi_tpu.parallel.model import ring_attention

        ndev = len(jax.devices())
        mesh = Mesh(np.array(jax.devices()), ("sp",))
        b, h, s, d = 2, 2, 8 * ndev, 16
        q, k, v = self._rand(b, h, s, s, d)

        def run(use_flash):
            def body(qq, kk, vv):
                return ring_attention(qq, kk, vv, "sp", ndev,
                                      use_flash=use_flash)
            spec = P(None, None, "sp", None)
            fn = jax.jit(shard_map(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False))
            return fn(q, k, v)

        np.testing.assert_allclose(np.asarray(run(True)),
                                   np.asarray(run(False)),
                                   rtol=2e-4, atol=2e-5)

    def test_flash_gradients(self):
        """custom_vjp backward matches autodiff through the jnp path."""
        from ompi_tpu.parallel.model import ring_attention

        q, k, v = self._rand(1, 1, 16, 16, 8)

        def loss(use_flash):
            def f(qq):
                o = ring_attention(qq, k, v, "none", 1, use_flash=use_flash)
                return jnp.sum(o * o)
            return jax.grad(f)(q)

        np.testing.assert_allclose(np.asarray(loss(True)),
                                   np.asarray(loss(False)),
                                   rtol=1e-4, atol=1e-5)
