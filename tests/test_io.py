"""MPI-IO: File object, views, individual + collective I/O, sharedfp.

Mirrors the reference's io test strategy (SURVEY §4): datatype-view
round-trips single-process, then tpurun multi-rank collective I/O with the
two-phase fcoll path, ending in the SURVEY Phase-6 payoff — a sharded-array
checkpoint written and restored through subarray file views across 4 ranks.
"""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


# -- single-process: views + fbtl ---------------------------------------

def test_view_extents_contiguous_and_vector():
    from ompi_tpu.datatype import FLOAT64, core
    from ompi_tpu.mca.io.ompio import view_extents

    # contiguous byte view
    runs = list(view_extents(0, core.BYTE, 3, 5))
    assert runs == [(3, 5)]
    # vector view: 2 doubles every 4 doubles → stream maps to strided file
    ft = core.vector(2, 2, 4, FLOAT64)
    runs = list(view_extents(100, ft, 0, 48))
    # tile = 32 data bytes over extent 8*... : first tile two blocks of 16
    assert runs[0] == (100, 16)
    assert runs[1] == (100 + 32, 16)
    assert sum(ln for _, ln in runs) == 48


def test_file_individual_roundtrip(tmp_path):
    import ompi_tpu
    from ompi_tpu.api.file import File

    path = str(tmp_path / "ind.dat")
    w = ompi_tpu.init()
    f = File.open(ompi_tpu.COMM_SELF, path, "c+")
    data = np.arange(100, dtype=np.float32)
    assert f.write_at(0, data) == 400
    back = np.zeros(100, np.float32)
    assert f.read_at(0, back) == 100
    assert np.array_equal(back, data)
    # individual pointer I/O
    f.seek(0)
    f.write(np.array([7, 8, 9], np.int64))
    assert f.get_position() == 24
    f.seek(8)
    one = np.zeros(1, np.int64)
    f.read(one)
    assert one[0] == 8
    assert f.get_size() == 400
    f.set_size(16)
    assert f.get_size() == 16
    f.close()
    File.delete(path)
    assert not os.path.exists(path)


def test_file_strided_view(tmp_path):
    """A vector filetype interleaves two ranks' columns in one file."""
    import ompi_tpu
    from ompi_tpu.api.file import File
    from ompi_tpu.datatype import FLOAT64, core

    path = str(tmp_path / "view.dat")
    f = File.open(ompi_tpu.COMM_SELF, path, "c+")
    # even slots through a 1-every-2 vector view
    ft = core.vector(4, 1, 2, FLOAT64)
    f.set_view(0, FLOAT64, ft)
    f.write_at(0, np.array([1., 2., 3., 4.]))
    # odd slots: same view displaced one double
    f.set_view(8, FLOAT64, ft)
    f.write_at(0, np.array([10., 20., 30., 40.]))
    f.set_view(0, FLOAT64, FLOAT64)   # flat view
    allv = np.zeros(8)
    f.read_at(0, allv)
    assert allv.tolist() == [1., 10., 2., 20., 3., 30., 4., 40.]
    f.close()


def test_file_datatype_buffer_triple(tmp_path):
    """Non-contiguous MEMORY through the convertor pack/unpack path."""
    import ompi_tpu
    from ompi_tpu.api.file import File
    from ompi_tpu.datatype import FLOAT64, core

    from ompi_tpu.api.errors import MpiError

    path = str(tmp_path / "triple.dat")
    f = File.open(ompi_tpu.COMM_SELF, path, "c+")
    mem = np.arange(8, dtype=np.float64)
    # memory type: every other element (vector 4x1 stride 2)
    mt = core.vector(4, 1, 2, FLOAT64)
    f.write_at(0, (mem, 1, mt))            # writes 0,2,4,6
    back = np.zeros(4)
    f.read_at(0, back)
    assert back.tolist() == [0., 2., 4., 6.]
    # read back into strided memory
    dst = np.zeros(8)
    f.read_at(0, (dst, 1, mt))
    assert dst.tolist() == [0., 0., 2., 0., 4., 0., 6., 0.]
    # pointer-based triple read: advances by the STREAM size (32 bytes),
    # not the destination array's 64 bytes
    f.seek(0)
    dst2 = np.zeros(8)
    f.read((dst2, 1, mt))
    assert f.get_position() == 32
    assert dst2.tolist() == [0., 0., 2., 0., 4., 0., 6., 0.]
    with pytest.raises(MpiError):
        f.seek(0, whence=9)
    f.close()
    with pytest.raises(MpiError):
        f.write(np.zeros(1))   # closed file must error, not hit a stale fd


def test_file_errors(tmp_path):
    import ompi_tpu
    from ompi_tpu.api.errors import MpiError
    from ompi_tpu.api.file import File

    with pytest.raises(MpiError):
        File.delete(str(tmp_path / "missing.dat"))
    f = File.open(ompi_tpu.COMM_SELF, str(tmp_path / "e.dat"), "c+")
    f.close()
    with pytest.raises(MpiError):
        f.read_at(0, np.zeros(1))
    with pytest.raises(MpiError):
        File.open(ompi_tpu.COMM_SELF, str(tmp_path / "e.dat"), "cx+")


# -- multi-process: collective I/O + sharedfp ---------------------------

def test_mp_collective_write_read(tmp_path):
    """4 ranks interleave blocks via write_at_all (two-phase), read back
    with read_at_all, and exercise the shared file pointer."""
    path = tmp_path / "coll.dat"
    script = tmp_path / "coll_io.py"
    script.write_text(textwrap.dedent(f"""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.file import File
        w = ompi_tpu.init()
        r = w.rank
        f = File.open(w, {str(path)!r}, "c+")
        # rank r owns bytes [r*32, (r+1)*32): contiguous blocks
        # (offsets are in etype units = bytes under the default view)
        data = np.full(8, float(r), np.float32)
        f.write_at_all(r * 32, data)
        # overlapping read: everyone reads the whole file collectively
        back = np.zeros(32, np.float32)
        f.read_at_all(0, back)
        expect = np.repeat(np.arange(4, dtype=np.float32), 8)
        assert np.array_equal(back, expect), back
        # shared file pointer: every rank appends one record; records are
        # disjoint and cover 4 slots
        f.set_view(128, None, None)   # past the collective region
        rec = np.full(2, 100.0 + r, np.float32)
        f.write_shared(rec)
        w.barrier()
        tail = np.zeros(8, np.float32)
        f.read_at(0, tail)
        got = sorted(set(tail.tolist()))
        assert got == [100.0, 101.0, 102.0, 103.0], tail
        f.close()
        # reopening must start the shared pointer at 0 again (no leak of
        # the previous open's counter)
        f3 = File.open(w, {str(path)!r}, "+")
        f3.set_view(128, None, None)
        one = np.zeros(2, np.float32)
        f3.read_shared(one)
        assert one[0] in (100.0, 101.0, 102.0, 103.0), one
        f3.close()
        print(f"coll io OK rank {{r}}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("coll io OK") == 4


def test_mp_sharded_checkpoint_subarray(tmp_path):
    """SURVEY Phase-6 payoff: a (8, 8) global array sharded 2x2 across 4
    ranks checkpoints through subarray file views with write_at_all and
    restores through the same views — and the file equals the dense
    row-major global array."""
    path = tmp_path / "ckpt.dat"
    script = tmp_path / "ckpt.py"
    script.write_text(textwrap.dedent(f"""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.file import File
        from ompi_tpu.datatype import FLOAT64, core
        w = ompi_tpu.init()
        r = w.rank
        G, B = 8, 4                     # global 8x8, 4x4 blocks, 2x2 grid
        gi, gj = divmod(r, 2)
        block = (np.arange(B * B, dtype=np.float64).reshape(B, B)
                 + 100.0 * r)
        ft = core.subarray([G, G], [B, B], [gi * B, gj * B],
                           core.ORDER_C, FLOAT64)
        f = File.open(w, {str(path)!r}, "c+")
        f.set_view(0, FLOAT64, ft)
        f.write_at_all(0, block)        # collective checkpoint
        f.close()

        # restore through the same view
        f2 = File.open(w, {str(path)!r}, "r")
        f2.set_view(0, FLOAT64, ft)
        back = np.zeros((B, B))
        f2.read_at_all(0, back)
        assert np.array_equal(back, block), (r, back)
        f2.close()

        # rank 0 validates the dense file layout
        if r == 0:
            whole = np.fromfile({str(path)!r}, np.float64).reshape(G, G)
            for rr in range(4):
                i, j = divmod(rr, 2)
                expect = (np.arange(16, dtype=np.float64).reshape(4, 4)
                          + 100.0 * rr)
                assert np.array_equal(
                    whole[i*4:(i+1)*4, j*4:(j+1)*4], expect), rr
        w.barrier()
        print(f"checkpoint OK rank {{r}}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("checkpoint OK") == 4


def test_mp_two_aggregator_fcoll(tmp_path):
    """Force 2 aggregators so the aggregator-to-aggregator piece exchange
    path runs (the deadlock-prone corner of two-phase I/O)."""
    path = tmp_path / "agg2.dat"
    script = tmp_path / "agg2.py"
    script.write_text(textwrap.dedent(f"""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.file import File
        w = ompi_tpu.init()
        r = w.rank
        f = File.open(w, {str(path)!r}, "c+")
        # strided interleave: rank r writes 4-byte words at stride 4
        data = np.full(64, r + 1, np.uint8)
        f.write_at_all(r * 64, data)
        back = np.zeros(256, np.uint8)
        f.read_at_all(0, back)
        expect = np.repeat(np.arange(1, 5, dtype=np.uint8), 64)
        assert np.array_equal(back, expect)
        f.close()
        print(f"agg2 OK rank {{r}}")
    """))
    r = _tpurun(4, [sys.executable, str(script)],
                extra=("--mca", "io_ompio_num_aggregators", "2"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("agg2 OK") == 4


def test_mp_fcoll_dynamic_ragged_pattern(tmp_path):
    """fcoll/dynamic_gen2 analog: a ragged pattern — two dense data
    islands separated by a huge hole.  Static address stripes would give
    one aggregator nearly all bytes (the hole splits the span, not the
    data); the dynamic strategy negotiates equal accessed-byte shares
    from the ranks' extents.  Runs the SAME pattern under both forced
    strategies plus auto (which must pick dynamic here), and all three
    files must agree byte-for-byte."""
    for alg in ("dynamic", "static", "auto"):
        path = tmp_path / f"rag_{alg}.dat"
        script = tmp_path / f"rag_{alg}.py"
        script.write_text(textwrap.dedent(f"""
            import numpy as np, ompi_tpu
            from ompi_tpu.api.file import File
            from ompi_tpu.datatype import core
            w = ompi_tpu.init()
            r = w.rank
            f = File.open(w, {str(path)!r}, "c+")
            # ONE collective call spans two 1KB data islands 1MB apart
            # (vector view: 2 blocks of 256B, 1MB stride): the spanned
            # region is ~0.2% data -> the auto heuristic must go dynamic
            ft = core.vector(2, 256, 1 << 20, core.BYTE)
            f.set_view(r * 256, core.BYTE, ft)
            data = np.concatenate([
                np.full(256, 10 * (r + 1), np.uint8),
                np.full(256, 10 * (r + 1) + 5, np.uint8)])
            f.write_at_all(0, data)
            mod = f.io_module
            assert mod.last_fcoll_alg == {("dynamic" if alg == "auto"
                                           else alg)!r}, \\
                (mod.last_fcoll_alg, {alg!r})
            f.set_view(0, core.BYTE, core.BYTE)
            back = np.zeros(1024, np.uint8)
            f.read_at_all(0, back)
            expect = np.repeat(np.arange(1, 5, dtype=np.uint8) * 10, 256)
            assert np.array_equal(back, expect), back[::256]
            back2 = np.zeros(1024, np.uint8)
            f.read_at_all(1 << 20, back2)
            assert np.array_equal(
                back2, np.repeat(np.arange(1, 5, dtype=np.uint8) * 10 + 5,
                                 256)), back2[::256]
            f.close()
            print(f"ragged {alg} OK rank {{r}}")
        """))
        r = _tpurun(4, [sys.executable, str(script)],
                    extra=("--mca", "io_ompio_num_aggregators", "2",
                           "--mca", "io_ompio_fcoll", alg))
        assert r.returncode == 0, (alg, r.stdout + r.stderr)
        assert r.stdout.count(f"ragged {alg} OK") == 4, (alg, r.stdout)
    ref = (tmp_path / "rag_dynamic.dat").read_bytes()
    assert (tmp_path / "rag_static.dat").read_bytes() == ref
    assert (tmp_path / "rag_auto.dat").read_bytes() == ref


def test_fcoll_domain_partitioning_unit():
    """The dynamic partition balances ACCESSED bytes: two islands of
    equal size with a huge hole between them -> with 2 aggregators the
    cut lands in the hole, one island per aggregator (static would hand
    both islands to aggregator 0 when the hole dominates the right
    half... or split island A)."""
    from ompi_tpu.mca.io.ompio import OmpioModule

    class FakeComm:
        size = 2
        rank = 0

        def allgatherv(self, flat):
            import numpy as np
            # rank 0: island A [0, 1000); rank 1: island B [10**6, 10**6+1000)
            return [np.array([0, 1000], np.int64),
                    np.array([1 << 20, 1000], np.int64)]

    class FakeComponent:
        class fcoll_var:
            value = "dynamic"

        class num_aggs_var:
            value = 2

    mod = OmpioModule.__new__(OmpioModule)
    mod._c = FakeComponent
    aggs, edges = mod._file_domains(FakeComm(), [[0, 1000]])
    assert len(edges) == 3
    # the cut must land between the islands, giving each agg ~1000 bytes
    assert 1000 <= edges[1] <= (1 << 20), edges
    # routing splits a run crossing the cut
    pieces = list(OmpioModule._route(edges, 900, 200))
    assert sum(t for _, _, t in pieces) == 200


def test_split_collectives(tmp_path):
    """MPI_File_*_all_begin/end semantics: one outstanding split
    collective per handle, matching end, same buffer at end
    (``ompi/mpi/c/file_read_all_begin.c`` family)."""
    from ompi_tpu.api import file as fmod

    path = str(tmp_path / "split.bin")
    f = fmod.File.open(None, path, fmod.MODE_CREATE | fmod.MODE_RDWR)
    data = np.arange(8, dtype=np.int32)
    f.write_all_begin(data)
    with pytest.raises(RuntimeError):       # one outstanding per handle
        f.write_all_begin(data)
    with pytest.raises(RuntimeError):       # mismatched end kind
        f.read_all_end(data)
    assert f.write_all_end(data) == data.nbytes
    with pytest.raises(RuntimeError):       # end without begin
        f.write_all_end(data)

    f.seek(0)
    out = np.zeros_like(data)
    f.read_all_begin(out)
    with pytest.raises(RuntimeError):       # wrong buffer at end
        f.read_all_end(np.zeros_like(data))
    f.read_all_end(out)
    np.testing.assert_array_equal(out, data)

    # at-variants do not move the individual pointer
    fp_before = f.get_position()
    two = (data * 2).copy()
    f.write_at_all_begin(0, two)
    f.write_at_all_end(two)
    back = np.zeros_like(data)
    f.read_at_all_begin(0, back)
    f.read_at_all_end(back)
    np.testing.assert_array_equal(back, two)
    assert f.get_position() == fp_before
    f.close()


def test_ordered_single_process(tmp_path):
    from ompi_tpu.api import file as fmod

    path = str(tmp_path / "ordered.bin")
    f = fmod.File.open(None, path, fmod.MODE_CREATE | fmod.MODE_RDWR)
    a = np.arange(4, dtype=np.float32)
    b = np.arange(4, 8, dtype=np.float32)
    assert f.write_ordered(a) == a.nbytes   # appends at shared pointer
    f.write_ordered_begin(b)
    assert f.write_ordered_end(b) == b.nbytes
    f.seek_shared(0)
    out = np.zeros(8, np.float32)
    f.read_ordered_begin(out)
    f.read_ordered_end(out)
    np.testing.assert_array_equal(out, np.arange(8, dtype=np.float32))
    f.close()


def test_mp_ordered_collective(tmp_path):
    """read/write_ordered across 4 ranks: rank-ordered disjoint regions
    from ONE shared-pointer carve-out (sharedfp ordered algorithm)."""
    path = tmp_path / "ordered_mp.dat"
    script = tmp_path / "ordered_mp.py"
    script.write_text(textwrap.dedent(f"""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.file import File
        w = ompi_tpu.init()
        r = w.rank
        f = File.open(w, {str(path)!r}, "c+")
        # ragged per-rank records: rank r writes r+1 floats of value r
        rec = np.full(r + 1, float(r), np.float32)
        f.write_ordered(rec)
        w.barrier()
        # the file must be rank-ordered: 0 | 1 1 | 2 2 2 | 3 3 3 3
        whole = np.zeros(10, np.float32)
        f.read_at(0, whole)
        expect = np.concatenate([np.full(i + 1, float(i), np.float32)
                                 for i in range(4)])
        assert np.array_equal(whole, expect), whole
        # ordered read: same carve-out discipline, everyone gets its own
        # region back
        f.seek_shared(0)
        w.barrier()
        mine = np.zeros(r + 1, np.float32)
        f.read_ordered(mine)
        assert np.array_equal(mine, rec), (r, mine)
        f.close()
        print(f"ordered io OK rank {{r}}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("ordered io OK") == 4


def test_nonblocking_individual_and_shared(tmp_path):
    """MPI_File_iread/iwrite (+_all/_at_all/_shared) request forms and
    the byte-offset/type-extent/shared-position accessors."""
    from ompi_tpu.api import file as fmod
    from ompi_tpu.datatype import FLOAT32, vector

    path = str(tmp_path / "nb.bin")
    f = fmod.File.open(None, path, fmod.MODE_CREATE | fmod.MODE_RDWR)
    data = np.arange(16, dtype=np.int32)
    r = f.iwrite(data)
    r.wait()
    assert r.result == data.nbytes
    assert f.get_position() == data.nbytes  # etype BYTE: bytes==etypes
    f.seek(0)
    out = np.zeros_like(data)
    f.iread(out).wait()
    np.testing.assert_array_equal(out, data)

    # nonblocking collectives (single-rank degenerate but full path)
    f.seek(0)
    f.iwrite_all(data * 3).wait()
    f.seek(0)
    out2 = np.zeros_like(data)
    f.iread_all(out2).wait()
    np.testing.assert_array_equal(out2, data * 3)
    f.iwrite_at_all(0, data).wait()
    out3 = np.zeros_like(data)
    f.iread_at_all(0, out3).wait()
    np.testing.assert_array_equal(out3, data)

    # shared-pointer request forms + get_position_shared
    assert f.get_position_shared() == 0
    f.iwrite_shared(data).wait()
    assert f.get_position_shared() == data.nbytes
    out4 = np.zeros_like(data)
    f._shared_reset(0)
    f.iread_shared(out4).wait()
    np.testing.assert_array_equal(out4, data)

    # get_byte_offset through a strided view; get_type_extent per datarep
    ft = vector(2, 1, 2, FLOAT32)         # 4B used, 4B gap, 4B used
    f.set_view(8, FLOAT32, ft)
    # etype offset 0 -> disp; offset 1 -> second used f32 (skip the gap)
    assert f.get_byte_offset(0) == 8
    assert f.get_byte_offset(1) == 8 + 8
    # offset 2 -> next tile (extent 12 bytes)
    assert f.get_byte_offset(2) == 8 + 12
    assert f.get_type_extent(ft) == ft.extent
    f.close()
