"""osc/rdma — mapped-window one-sided RMA (no target-side agent).

Re-creates the osc/pt2pt multiprocess scenarios on the direct path the
reference implements in ``ompi/mca/osc/rdma/``: put/get as direct stores,
accumulate under the native accumulate lock, CAS-backed passive locks, and
message-free PSCW over shared counters.
"""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from ompi_tpu import native

# every scenario here asserts RdmaModule SELECTION, and osc/rdma's
# comm_query requires the native atomics — without the toolchain the
# same jobs run correctly on osc/pt2pt (covered by test_osc.py), so
# there is nothing rdma-specific left to test
pytestmark = pytest.mark.skipif(not native.available(),
                                reason="osc/rdma needs native atomics")

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_rdma_selected_and_put_get_fence(tmp_path):
    script = tmp_path / "rdma1.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win
        w = ompi_tpu.init()
        r = w.rank
        win = Win.create(w, size=8, dtype=np.float64)
        assert type(win.module).__name__ == 'RdmaModule', type(win.module)
        # no servicing agent thread: the one-sided property
        assert not hasattr(win.module, '_agent')
        win.local[:] = r * 1.0
        win.fence()
        # everyone writes its rank into the right neighbor's slot r
        win.put(np.array([100.0 + r]), (r + 1) % w.size, offset=r)
        win.fence()
        # and reads the left neighbor's whole region: its writer was
        # rank left-1, who wrote 100+writer at offset writer
        left = (r - 1) % w.size
        writer = (left - 1) % w.size
        got = win.get(8, left, offset=0)
        assert got[writer] == 100.0 + writer, got
        win.fence()
        win.free()
        print(f"rdma putget OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("rdma putget OK") == 4


def test_rdma_accumulate_and_fetch_op(tmp_path):
    script = tmp_path / "rdma2.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win
        w = ompi_tpu.init()
        r = w.rank
        win = Win.create(w, size=2, dtype=np.int64)
        assert type(win.module).__name__ == 'RdmaModule'
        win.fence()
        # concurrent atomic accumulates into rank 0's counter
        for _ in range(50):
            win.accumulate(np.array([1], np.int64), 0, offset=0)
        win.fence()
        if r == 0:
            assert win.local[0] == 50 * w.size, win.local
        # fetch_and_op global ticket counter at rank 0 slot 1
        t = int(win.fetch_and_op(1, 0, offset=1))
        assert 0 <= t < w.size
        win.fence()
        if r == 0:
            assert win.local[1] == w.size
        win.free()
        print(f"rdma acc OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("rdma acc OK") == 4


def test_rdma_passive_lock_and_cas(tmp_path):
    script = tmp_path / "rdma3.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win
        w = ompi_tpu.init()
        r = w.rank
        win = Win.create(w, size=4, dtype=np.int64)
        win.fence()
        # exclusive-lock read-modify-write on rank 0 (lock via CAS word)
        for _ in range(25):
            win.lock(0, Win.LOCK_EXCLUSIVE)
            v = win.get(1, 0, offset=0)
            win.put(v + 1, 0, offset=0)
            win.unlock(0)
        w.barrier()
        if r == 0:
            assert win.local[0] == 25 * w.size, win.local
        # native int64 CAS: single winner election
        old = win.compare_and_swap(r + 1, 0, 0, offset=2)
        wins = np.asarray(w.allgather(
            np.array([1 if old == 0 else 0], np.int64)))
        assert wins.sum() == 1, wins
        win.fence()
        win.free()
        print(f"rdma lock OK rank {r}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("rdma lock OK") == 4


def test_rdma_pscw(tmp_path):
    """PSCW epochs ride shared counters — zero messages, zero agent."""
    script = tmp_path / "rdma4.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win
        w = ompi_tpu.init()
        r = w.rank
        win = Win.create(w, size=4, dtype=np.float64)
        origin_group = w.group.incl([0]) if hasattr(w.group, 'incl') else None
        from ompi_tpu.api.group import Group
        origins = Group([w.group.world_rank(0)])
        targets = Group([w.group.world_rank(1)])
        if r == 1:
            win.post(origins)       # expose to rank 0
            win.wait()
            assert win.local[2] == 77.5, win.local
        elif r == 0:
            win.start(targets)
            win.put(np.array([77.5]), 1, offset=2)
            win.complete()
        w.barrier()
        win.free()
        print(f"rdma pscw OK rank {r}")
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("rdma pscw OK") == 2


def test_rdma_excluded_falls_back_to_pt2pt(tmp_path):
    script = tmp_path / "rdma5.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu.api.win import Win
        w = ompi_tpu.init()
        win = Win.create(w, size=2, dtype=np.float64)
        assert type(win.module).__name__ == 'Pt2ptModule', type(win.module)
        win.fence()
        win.put(np.array([5.0]), (w.rank + 1) % w.size, offset=0)
        win.fence()
        assert win.local[0] == 5.0
        win.free()
        print("fallback OK")
    """))
    r = _tpurun(2, [sys.executable, str(script)],
                extra=("--mca", "osc", "^rdma"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("fallback OK") == 2


def test_shared_query_and_request_rma(tmp_path):
    """MPI_Win_allocate_shared + shared_query direct load/store view, and
    the request-based Rput/Rget family (``win_shared_query.c``,
    ``rput.c``)."""

    script = tmp_path / "wsq.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import ompi_tpu
        from ompi_tpu.api.win import Win

        w = ompi_tpu.init()
        win, buf = Win.allocate_shared(w, 8, np.float64)
        buf[:] = w.rank * 100.0
        win.fence()
        # direct view of the right neighbour's memory (same node: shm)
        peer = (w.rank + 1) % w.size
        view = win.shared_query(peer)
        assert view[0] == peer * 100.0, view
        win.fence()
        # request-based RMA
        r1 = win.rput(np.array([7.0]), peer, offset=1)
        r1.wait()
        win.flush(peer)
        r2 = win.rget(2, peer, offset=0)
        r2.wait()
        got = r2.result
        assert got[1] == 7.0, got
        win.fence()
        win.free()
        print(f"WSQ OK {w.rank}")
        ompi_tpu.finalize()
    """))
    r = _tpurun(2, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("WSQ OK") == 2
