"""Randomized convertor fuzz: deep random datatype nestings
(vector/hvector/indexed/indexed_block/struct/resized/contiguous, depth
<=3) must pack to exactly size*count bytes, unpack-repack byte-identical,
and match partial packs with position resume.  Fixed seed; the round-5
400-trial sweep of the same generator found no defect — this guards
that property."""
TRIALS = 80

import numpy as np
from ompi_tpu.datatype import core
from ompi_tpu.datatype.convertor import Convertor

rng = np.random.default_rng(424242)
BASES = [core.FLOAT32, core.FLOAT64, core.INT32, core.INT8, core.INT64]

def random_type(depth=0):
    if depth >= 3 or rng.random() < 0.35:
        return BASES[rng.integers(0, len(BASES))]
    kind = rng.choice(["vector", "hvector", "indexed", "contiguous",
                       "struct", "indexed_block", "resized"])
    inner = random_type(depth + 1)
    if kind == "vector":
        return core.vector(int(rng.integers(1, 4)),
                           int(rng.integers(1, 3)),
                           int(rng.integers(1, 5)), inner)
    if kind == "hvector":
        stride = int(rng.integers(1, 4)) * inner.extent
        return core.hvector(int(rng.integers(1, 4)),
                            int(rng.integers(1, 3)), stride, inner)
    if kind == "contiguous":
        return core.contiguous(int(rng.integers(1, 5)), inner)
    if kind == "indexed":
        nb = int(rng.integers(1, 4))
        disps = sorted(rng.choice(range(0, 12), nb, replace=False))
        return core.indexed([int(rng.integers(1, 3)) for _ in range(nb)],
                            [int(d) for d in disps], inner)
    if kind == "indexed_block":
        nb = int(rng.integers(1, 4))
        disps = sorted(rng.choice(range(0, 12), nb, replace=False))
        return core.indexed_block(1, [int(d) for d in disps], inner)
    if kind == "struct":
        t2 = random_type(depth + 1)
        off2 = inner.extent + int(rng.integers(0, 8))
        return core.create_struct([1, 1], [0, off2], [inner, t2])
    if kind == "resized":
        return core.resized(inner, 0,
                            inner.extent + int(rng.integers(0, 16)))
    raise AssertionError

def test_convertor_random_nested_roundtrips():
    bad = []
    for trial in range(TRIALS):
        dt = random_type()
        if dt.size == 0:
            continue
        count = int(rng.integers(1, 20))
        # buffer must cover [min(0, lb), lb + count*extent) from base 0
        end = max(dt.ub + (count - 1) * dt.extent,
                  dt.lb + count * dt.extent,
                  dt.true_ub + (count - 1) * dt.extent)
        mem = rng.integers(0, 256, end + 64, dtype=np.uint8)
        try:
            c = Convertor(dt, count, mem)
            packed = c.pack()
            assert len(packed) == dt.size * count, "size mismatch"
            # roundtrip into a fresh buffer, repack must match
            mem2 = np.zeros_like(mem)
            c2 = Convertor(dt, count, mem2)
            c2.unpack(packed)
            c3 = Convertor(dt, count, mem2)
            repacked = c3.pack()
            assert bytes(repacked) == bytes(packed), "roundtrip mismatch"
            # partial pack with position resume == whole pack
            c4 = Convertor(dt, count, mem)
            chunks = []
            while True:
                chunk = c4.pack(max_bytes=int(rng.integers(1, 64)))
                if chunk.size == 0:
                    break
                chunks.append(bytes(chunk))
            assert b"".join(chunks) == bytes(packed), "partial-pack mismatch"
        except AssertionError as e:
            bad.append((trial, str(e), dt.combiner))
            print("FAIL", trial, e, dt.combiner, flush=True)
        except Exception as e:
            bad.append((trial, f"EXC {e}", dt.combiner))
            print("EXC", trial, str(e)[:120], dt.combiner, flush=True)

    assert not bad, bad[:5]
