"""ompi_tpu.parallel: mesh factoring, ring attention, MoE, pipeline, train.

Numerical references are single-device jnp computations; the parallel
versions must match them exactly (same math, different schedule) — the
analog of the reference's coll algorithm-vs-basic cross-checks.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ompi_tpu.base.jaxenv import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ompi_tpu.parallel.mesh import MeshSpec, default_axis_sizes, make_mesh
from ompi_tpu.parallel.model import ring_attention
from ompi_tpu.parallel.pipeline import pipeline_apply
from ompi_tpu.parallel.train import build_train_step, init_params, model_dims


def test_default_axis_sizes():
    assert default_axis_sizes(8) == MeshSpec(dp=2, pp=1, sp=2, tp=2)
    assert default_axis_sizes(16) == MeshSpec(dp=2, pp=2, sp=2, tp=2)
    assert default_axis_sizes(1) == MeshSpec()
    assert default_axis_sizes(4).n == 4
    assert default_axis_sizes(12).n == 12


def _ref_attention(q, k, v):
    # q,k,v: (b, h, s, hd) global — plain softmax attention
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def test_ring_attention_matches_dense():
    n_sp = 4
    mesh = Mesh(np.array(jax.devices()[:n_sp]), ("sp",))
    rng = np.random.RandomState(0)
    b, h, s, hd = 2, 2, 8, 4
    q, k, v = (rng.normal(0, 1, (b, h, s, hd)).astype(np.float32)
               for _ in range(3))

    fn = jax.jit(shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp", n_sp),
        mesh=mesh, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_vma=False))
    out = fn(q, k, v)
    np.testing.assert_allclose(out, _ref_attention(q, k, v),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_matches_sequential():
    pp = 4
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))
    rng = np.random.RandomState(1)
    M, mb, d = 3, 2, 4
    x = rng.normal(0, 1, (M, mb, d)).astype(np.float32)
    w = rng.normal(0, 0.5, (pp, d, d)).astype(np.float32)

    def stage(wi, z):
        return jnp.tanh(z @ wi[0])

    fn = jax.jit(shard_map(
        # outputs live on the last stage only; psum over pp collects them
        lambda w_, x_: jax.lax.psum(pipeline_apply(stage, w_, x_, pp=pp),
                                    "pp"),
        mesh=mesh, in_specs=(P("pp", None, None), P()),
        out_specs=P(), check_vma=False))
    out = fn(w, x)

    ref = x
    for i in range(pp):
        ref = jnp.tanh(ref @ w[i])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [1, 4, 8])
def test_train_step_descends(n):
    mesh, spec = make_mesh(jax.devices()[:n])
    dims = model_dims(spec)
    step, place = build_train_step(mesh, spec)
    rng = np.random.RandomState(2)
    x = rng.normal(0, 1, (dims["batch"], dims["seq"], dims["d"]))
    params, xd = place(init_params(spec), x)
    p1, l1 = step(params, xd)
    _, l2 = step(p1, xd)
    assert np.isfinite(float(l1))
    assert float(l2) < float(l1)


def test_ulysses_matches_ring_and_full():
    """Ulysses (all-to-all SP) == ring attention == unsharded reference."""
    import jax
    import jax.numpy as jnp
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ompi_tpu.parallel.model import (_full_attention, ring_attention,
                                         ulysses_attention)

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    b, h, s, hd = 2, 2 * ndev, 4 * ndev, 8
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, h, s, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, hd), jnp.float32)

    spec = P(None, None, "sp", None)

    def run(fn):
        body = lambda qq, kk, vv: fn(qq, kk, vv, "sp", ndev)
        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))(q, k, v)

    ref = _full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(run(ulysses_attention)),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(run(lambda *a: ring_attention(*a, use_flash=False))),
        np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_composed_step_with_active_pipeline_axis():
    """The 4-axis step with pp>=2 ACTIVE: loss descends on the
    {dp:1,pp:2,sp:2,tp:2} mesh (round-2 gap: the composed dp x pp x sp
    x tp program had only ever run with pp=1)."""
    from ompi_tpu.parallel.dryrun import make_step_and_args
    from ompi_tpu.parallel.mesh import MeshSpec

    step, (params, xd), spec = make_step_and_args(
        jax.devices()[:8], MeshSpec(dp=1, pp=2, sp=2, tp=2))
    assert spec.pp == 2
    p1, l1 = step(params, xd)
    _, l2 = step(p1, xd)
    assert np.isfinite(float(l1))
    assert float(l2) < float(l1), (float(l1), float(l2))


def test_pp2_matches_pp1_same_model():
    """Grad-sync equivalence: the SAME 2-layer model + input stepped on a
    pp=2 mesh (8 devices, one layer per stage) and a pp=1 mesh (4
    devices, both layers local) must produce the same loss and the same
    updated parameters — pipelining is an execution schedule, not a
    different function."""
    from ompi_tpu.parallel.mesh import MeshSpec, make_mesh
    from ompi_tpu.parallel.train import (build_train_step, init_params,
                                         model_dims)

    rng = np.random.RandomState(7)
    spec2 = MeshSpec(dp=1, pp=2, sp=2, tp=2)
    spec1 = MeshSpec(dp=1, pp=1, sp=2, tp=2)
    dims = model_dims(spec2, layers=2)
    x = rng.normal(0, 1, (dims["batch"], dims["seq"], dims["d"]))
    params = init_params(spec2, seed=3, layers=2)

    results = {}
    for name, spec, ndev in (("pp2", spec2, 8), ("pp1", spec1, 4)):
        mesh, _ = make_mesh(jax.devices()[:ndev], spec)
        step, place = build_train_step(mesh, spec, layers=2)
        pd, xd = place(params, x)
        p1, l1 = step(pd, xd)
        results[name] = (float(l1), {k: np.asarray(v)
                                     for k, v in p1.items()})
    l2, p2 = results["pp2"]
    l1_, p1_ = results["pp1"]
    np.testing.assert_allclose(l2, l1_, rtol=1e-5)
    for k in p2:
        np.testing.assert_allclose(p2[k], p1_[k], rtol=1e-4, atol=1e-6,
                                   err_msg=f"param {k} diverged")


@pytest.mark.slow
def test_dryrun_spec_override_and_16dev():
    """The driver-facing dryrun accepts a mesh-spec override (pp=2 on 8
    devices) and the 16-device default mesh — where pp activates on its
    own — runs a descending composed step."""
    import __graft_entry__ as g

    g.dryrun_multichip(8, spec="dp=1,pp=2,sp=2,tp=2")
    g.dryrun_multichip(16)   # default_axis_sizes(16) -> all 4 axes active


def test_causal_ring_and_ulysses_match_masked_reference():
    """causal=True on both SP schemes == unsharded lower-triangle
    attention — the mask composes from GLOBAL positions across ring
    steps (shard-offset block bias), not local ones."""
    import jax
    import jax.numpy as jnp
    from ompi_tpu.base.jaxenv import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ompi_tpu.parallel.model import (_full_attention, ring_attention,
                                         ulysses_attention)

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    b, h, s, hd = 2, 2 * ndev, 4 * ndev, 8
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (b, h, s, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, hd), jnp.float32)
    spec = P(None, None, "sp", None)

    def run(fn):
        body = lambda qq, kk, vv: fn(qq, kk, vv, "sp", ndev)
        return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))(q, k, v)

    ref = np.asarray(_full_attention(q, k, v, causal=True))
    got_ring = run(lambda *a: ring_attention(*a, use_flash=False,
                                             causal=True))
    np.testing.assert_allclose(np.asarray(got_ring), ref, rtol=2e-4,
                               atol=2e-5)
    got_ul = run(lambda *a: ulysses_attention(*a, causal=True))
    np.testing.assert_allclose(np.asarray(got_ul), ref, rtol=2e-4,
                               atol=2e-5)
    # flash path (interpreter off-TPU) agrees too
    got_flash = run(lambda *a: ring_attention(*a, use_flash=True,
                                              causal=True))
    np.testing.assert_allclose(np.asarray(got_flash), ref, rtol=2e-4,
                               atol=2e-5)


def test_causal_single_shard_and_gradients():
    """n_shards=1 causal == plain masked attention; gradients flow
    through the biased flash custom-VJP (recompute via the jnp twin)."""
    import jax
    import jax.numpy as jnp

    from ompi_tpu.parallel.model import _full_attention, ring_attention

    b, h, s, hd = 1, 2, 8, 4
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, h, s, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, h, s, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, h, s, hd), jnp.float32)
    ref = np.asarray(_full_attention(q, k, v, causal=True))
    for flash in (False, True):
        got = ring_attention(q, k, v, "sp", 1, use_flash=flash,
                             causal=True)
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5,
                                   atol=2e-5)

    def loss(fn, flash):
        return lambda qq: jnp.sum(
            fn(qq, k, v, "sp", 1, use_flash=flash, causal=True) ** 2)

    g_flash = jax.grad(loss(ring_attention, True))(q)
    g_jnp = jax.grad(loss(ring_attention, False))(q)
    np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_jnp),
                               rtol=2e-4, atol=2e-5)


def test_causal_train_step_var():
    """--mca parallel_causal 1 flows into the composed train step and
    changes the loss trajectory (masked attention is a different
    program), while still descending."""
    import jax

    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel.dryrun import parse_spec, run_training_step

    var = registry.lookup("otpu_parallel_causal")
    assert var is not None
    old = var.value
    try:
        devs = jax.devices()[:4]
        spec = parse_spec("dp=2,pp=1,sp=2,tp=1")
        var.set(False)
        base = run_training_step(devs, spec)
        var.set(True)
        causal = run_training_step(devs, spec)
        assert np.isfinite(causal)
        # masked attention is a genuinely different program: same init,
        # same data, different loss
        assert abs(causal - base) > 1e-6, (causal, base)
    finally:
        var.set(old)


def test_remat_var_matches_baseline_loss():
    """--mca parallel_remat 1 must change only WHERE activations come
    from (recompute vs store): the loss trajectory is bit-comparable."""
    import jax

    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel.dryrun import parse_spec, run_training_step

    var = registry.lookup("otpu_parallel_remat")
    assert var is not None
    devs = jax.devices()[:4]
    spec = parse_spec("dp=2,pp=1,sp=2,tp=1")
    old = var.value
    try:
        var.set(False)
        base = run_training_step(devs, spec)
        var.set(True)
        remat = run_training_step(devs, spec)
        np.testing.assert_allclose(remat, base, rtol=1e-6)
    finally:
        var.set(old)


def test_compute_dtype_bf16_descends():
    """--mca parallel_compute_dtype bfloat16: the composed step still
    trains (finite loss, close to the f32 program) with half-width
    activations and per-block param casts — including combined with
    causal masking and remat (the production stack)."""
    import jax

    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel.dryrun import parse_spec, run_training_step

    var = registry.lookup("otpu_parallel_compute_dtype")
    assert var is not None
    devs = jax.devices()[:4]
    spec = parse_spec("dp=2,pp=1,sp=2,tp=1")
    old = var.value
    causal = registry.lookup("otpu_parallel_causal")
    remat = registry.lookup("otpu_parallel_remat")
    old_c, old_r = causal.value, remat.value
    try:
        var.set("float32")
        base = run_training_step(devs, spec)
        var.set("bfloat16")
        lo = run_training_step(devs, spec)
        assert np.isfinite(lo)
        # bf16 rounding makes a different (but close) program
        np.testing.assert_allclose(lo, base, rtol=0.1)
        # the production combination: bf16 + causal + remat must
        # compose (regression: the f32 causal bias once promoted the
        # bf16 scan carry and broke lax.scan's type invariant)
        causal.set(True)
        remat.set(True)
        combo = run_training_step(devs, spec)
        assert np.isfinite(combo)
    finally:
        var.set(old)
        causal.set(old_c)
        remat.set(old_r)


def test_zero1_matches_baseline_and_shards_state():
    """--mca parallel_zero1 1: reduce-scatter grads, dp-sharded
    momentum, masked-psum param rebuild — loss parity with the
    allreduce baseline at momentum 0, and the state really is one
    (chunk,) block per (dp, pp, tp) shard."""
    import jax

    from ompi_tpu.base.var import registry
    from ompi_tpu.parallel.dryrun import (make_step_and_args, parse_spec,
                                          run_training_step)

    z = registry.lookup("otpu_parallel_zero1")
    mvar = registry.lookup("otpu_parallel_momentum")
    old_z, old_m = z.value, mvar.value
    devs = jax.devices()[:8]
    try:
        for s in ("dp=2,pp=2,sp=2,tp=1", "dp=2,pp=1,sp=2,tp=2"):
            spec = parse_spec(s)
            z.set(False)
            mvar.set(0.0)
            base = run_training_step(devs, spec)
            z.set(True)
            np.testing.assert_allclose(run_training_step(devs, spec),
                                       base, rtol=1e-6)
            mvar.set(0.9)
            assert np.isfinite(run_training_step(devs, spec))
        # structural: carried state is (params, m) with the sharded spec
        z.set(True)
        step, args, _ = make_step_and_args(
            devs, parse_spec("dp=2,pp=1,sp=2,tp=2"))
        (params, m), x = args
        assert tuple(m.sharding.spec) == (("dp", "pp", "tp"),)
        txt = step.lower(*args).as_text()
        assert "reduce-scatter" in txt or "reduce_scatter" in txt
    finally:
        z.set(old_z)
        mvar.set(old_m)
