"""otpu-verify tests: the interprocedural passes (view-escape,
mpi-typestate, collective-matching) fire on their bad fixtures and stay
quiet on the good twins, the call graph resolves the shapes the passes
lean on, and the weave interleaving explorer re-finds each reverted
PR 6 race deterministically — replaying from its printed schedule
string — while the fixed twins exhaust their bounded schedule space
clean."""
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from ompi_tpu import analysis
from ompi_tpu.analysis import weave

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def run_pass(name, *paths):
    res = analysis.lint([str(p) for p in paths], select=[name])
    assert not res.errors, res.errors
    return res.findings


# ---------------------------------------------------------------------------
# the three new passes on their fixture twins
# ---------------------------------------------------------------------------

def test_view_escape_interprocedural_families():
    bad = run_pass("view-escape", FIXTURES / "escape_ip" / "bad.py")
    msgs = " | ".join(f.message for f in bad)
    assert "returns a borrowed view straight from 'pack_borrow()'" in msgs
    assert "is stored on 'self' without an owning copy" in msgs
    assert "is returned without an owning copy" in msgs
    assert "whose parameter 'payload' escapes" in msgs
    assert "captured by deferred callback" in msgs
    assert "acquired through fill_scratch()" in msgs
    # the multi-hop chain (remember2 -> head2 -> head -> pack_borrow)
    # needs the worklist fixpoint to actually propagate
    assert "'data' (from Wire.head2())" in msgs
    assert len(bad) == 8, bad
    assert not run_pass("view-escape", FIXTURES / "escape_ip" / "good.py")


def test_typestate_request_lifecycle():
    bad = run_pass("mpi-typestate", FIXTURES / "typestate" / "bad.py")
    msgs = " | ".join(f.message for f in bad)
    for what in ("started but never waited", "freed twice",
                 "used after free()", "started twice",
                 "Pready is send-side only",
                 "pready() on inactive request",
                 "observable on the receive side only",
                 "never waited/tested in this function"):
        assert what in msgs, (what, msgs)
    assert not run_pass("mpi-typestate", FIXTURES / "typestate" / "good.py")


def test_typestate_win_epochs_and_refcounts():
    bad = run_pass("mpi-typestate", FIXTURES / "typestate" / "bad.py")
    msgs = " | ".join(f.message for f in bad)
    for what in ("closes a passive-target epoch that was never opened",
                 "opened here but never closed",
                 "outside a passive-target epoch",
                 "PSCW 'win.start()' epoch is never closed",
                 "no paired 'instance.release'",
                 "guarded handoff"):
        assert what in msgs, (what, msgs)
    assert len(bad) == 14, bad


def test_typestate_annotation_overrides_defaults(tmp_path):
    """The automaton is DECLARED in the api module (_TYPESTATE) and the
    pass consumes the declaration, not a hardcoded list: a tree whose
    request.py renames the nonblocking creator is checked against the
    renamed automaton."""
    (tmp_path / "request.py").write_text(
        '_TYPESTATE = {"create_active": ["fire"]}\n')
    (tmp_path / "use.py").write_text(
        "def f(comm, buf):\n"
        "    comm.fire(buf)\n")
    bad = run_pass("mpi-typestate", tmp_path)
    assert len(bad) == 1, bad
    assert "'fire()' request is discarded" in bad[0].message
    # without the annotation, 'fire' means nothing
    (tmp_path / "request.py").write_text("_X = 1\n")
    assert not run_pass("mpi-typestate", tmp_path)


def test_collective_matching_deadlock_shapes():
    bad = run_pass("collective-matching", FIXTURES / "coll_match" / "bad.py")
    msgs = " | ".join(f.message for f in bad)
    assert "only some arms of a rank-conditional branch" in msgs
    assert "skipped by the rank-conditional return" in msgs
    symbols = {f.symbol for f in bad}
    for sym in ("one_armed_bcast", "mismatched_arms", "early_return_skips",
                "unresolved_rank_is_conservative", "nested_early_return",
                "count_mismatch", "mismatched_elif_ladder"):
        assert sym in symbols, (sym, symbols)
    assert len(bad) == 10, bad
    assert not run_pass("collective-matching",
                        FIXTURES / "coll_match" / "good.py")


def test_callgraph_survives_circular_reexports(tmp_path):
    """A circular from-import (compat-shim shape) must be unresolvable,
    not a RecursionError that takes down the whole lint run."""
    (tmp_path / "a.py").write_text(
        "from b import helper\n\n"
        "def use(x):\n"
        "    return helper(x)\n")
    (tmp_path / "b.py").write_text("from a import helper\n")
    res = analysis.lint([str(tmp_path)],
                        select=["view-escape", "mpi-typestate"])
    assert not res.errors
    assert not res.findings


def test_callgraph_resolves_the_load_bearing_shapes():
    from ompi_tpu.analysis import callgraph

    pkg = analysis.load_package(
        [str(REPO / "ompi_tpu" / "analysis"),
         str(REPO / "ompi_tpu" / "mca" / "accelerator" / "jax_acc.py")])
    graph = callgraph.build(pkg)
    mod = pkg.find("analysis/scenarios.py")
    assert mod is not None
    info = graph.function_at(mod, "_RevertedCheckoutPool.acquire")
    assert info is not None
    import ast

    calls = [n for n in ast.walk(info.node) if isinstance(n, ast.Call)]
    resolved = {graph.resolve_call(info, c).qual
                for c in calls if graph.resolve_call(info, c) is not None}
    # self-method on the subclass AND an inherited method through the
    # package-local base walk
    assert "_RevertedCheckoutPool._checkout_window" in resolved
    assert "_StagingPool._class_of" in resolved
    # one shared graph per package object (every pass reuses it)
    assert callgraph.build(pkg) is graph


# ---------------------------------------------------------------------------
# weave: the explorer itself
# ---------------------------------------------------------------------------

class _Box:
    pass


def _toy_scenario(bound=2):
    def setup():
        s = _Box()
        s.counter = 0
        return s

    def bump(s):
        v = s.counter
        weave.pause("rmw")
        s.counter = v + 1

    def check(s):
        assert s.counter == 2, f"lost update: {s.counter}"

    return weave.Scenario("toy-rmw", setup, [bump, bump], check=check,
                          preemption_bound=bound)


def test_weave_finds_toy_race_and_replays_it():
    sc = _toy_scenario()
    res = weave.explore(sc)
    assert res.failed and res.kind == "check"
    assert res.schedule and res.schedule.startswith("toy-rmw@pb2:")
    rep = weave.replay(sc, res.schedule)
    assert rep.failed and rep.kind == "check"
    assert rep.schedule == res.schedule


def test_weave_exploration_is_deterministic():
    sc = _toy_scenario()
    a = weave.explore(sc)
    b = weave.explore(sc)
    assert (a.failed, a.schedule, a.schedules) \
        == (b.failed, b.schedule, b.schedules)


def test_weave_locked_twin_exhausts_clean():
    def setup():
        s = _Box()
        s.counter = 0
        s.lock = weave.make_lock("ctr")
        return s

    def bump(s):
        with s.lock:
            v = s.counter
            weave.pause("rmw")
            s.counter = v + 1

    def check(s):
        assert s.counter == 2

    sc = weave.Scenario("toy-rmw-locked", setup, [bump, bump],
                        check=check, preemption_bound=3)
    res = weave.explore(sc)
    assert not res.failed and res.exhausted
    assert res.schedules > 1          # the space was actually explored


def test_weave_detects_deadlock_with_description():
    def setup():
        s = _Box()
        s.a = weave.make_lock("a")
        s.b = weave.make_lock("b")
        return s

    def ab(s):
        with s.a:
            weave.pause("mid")
            with s.b:
                pass

    def ba(s):
        with s.b:
            weave.pause("mid")
            with s.a:
                pass

    sc = weave.Scenario("toy-deadlock", setup, [ab, ba],
                        preemption_bound=1)
    res = weave.explore(sc)
    assert res.failed and res.kind == "deadlock"
    assert "waiting-lock" in str(res.error)
    rep = weave.replay(sc, res.schedule)
    assert rep.failed and rep.kind == "deadlock"


def test_weave_schedule_string_round_trip():
    s = weave.format_schedule("staging-checkout", 2, [0, 0, 1, 1, 0])
    assert s == "staging-checkout@pb2:0.0.1.1.0"
    name, bound, choices = weave.parse_schedule(s)
    assert (name, bound, choices) == ("staging-checkout", 2,
                                      [0, 0, 1, 1, 0])
    with pytest.raises(ValueError):
        weave.parse_schedule("no-bound:0.1")


def test_weave_replay_mismatch_is_loud():
    sc = _toy_scenario()
    res = weave.replay(sc, "toy-rmw@pb2:0.7.7.7")
    assert res.failed and res.kind == "replay-mismatch"
    with pytest.raises(ValueError):
        weave.replay(sc, "other-scenario@pb2:0")


def test_weave_try_acquire_declines_instead_of_blocking():
    """acquire(blocking=False) on an instrumented lock keeps its
    try-acquire semantics: the probe declines (returns False) when the
    lock is held instead of silently becoming a blocking wait — so a
    scenario over code like libnbc's `_adv_lock.acquire(blocking=False)`
    neither deadlocks nor serializes a path the real code skips."""
    def setup():
        s = _Box()
        s.lock = weave.make_lock("l")
        s.probes = []
        return s

    def holder(s):
        with s.lock:
            weave.pause("held")
            weave.pause("held2")

    def prober(s):
        got = s.lock.acquire(blocking=False)
        s.probes.append(got)
        if got:
            s.lock.release()

    sc = weave.Scenario("try-acquire", setup, [holder, prober],
                        preemption_bound=2)
    res = weave.explore(sc)
    assert not res.failed, res.summary()   # a probe never deadlocks
    assert res.exhausted


def test_weave_teardown_leaves_no_threads_behind():
    """Killed scenario threads — including ones HOLDING a WeaveLock at
    deadlock time, whose with-block unwind re-enters the lock release —
    must exit promptly instead of re-parking forever (the 5s-join-leak
    regression)."""
    import time

    from ompi_tpu.analysis import scenarios

    t0 = time.monotonic()
    res = weave.explore(scenarios.get("coord-fence"))
    elapsed = time.monotonic() - t0
    assert res.failed and res.kind == "deadlock"
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("weave-")]
    assert not leaked, leaked
    assert elapsed < 4.0, f"teardown stalled: {elapsed:.2f}s"


def test_weave_instrument_skips_condition_guards():
    """A _guarded_by attribute backed by a Condition (the CoordServer
    family) must be left untouched — WeaveLock has no wait()/notify(),
    so clobbering it would crash the first wait mid-schedule.  Plain
    mutex guards on the same object are still wrapped."""
    class _CondGuarded:
        _guarded_by = {"_kv": "_kv_cond", "_q": "_qlock"}

        def __init__(self):
            self._kv_cond = threading.Condition()
            self._qlock = threading.Lock()

    seen = {}

    def setup():
        obj = weave.instrument(_CondGuarded())
        seen["cond"] = obj._kv_cond
        seen["lock"] = obj._qlock
        return obj

    sc = weave.Scenario("cond-skip", setup, [lambda s: None],
                        preemption_bound=0)
    res = weave.explore(sc)
    assert not res.failed
    assert isinstance(seen["cond"], threading.Condition)   # untouched
    assert isinstance(seen["lock"], weave.WeaveLock)       # wrapped


def test_weave_timed_acquire_keeps_may_fail_contract():
    """acquire(timeout=...) on a held instrumented lock declines (the
    real code's timed-out fallback) instead of parking forever and
    mis-reporting a deadlock."""
    def setup():
        s = _Box()
        s.lock = weave.make_lock("l")
        s.results = []
        return s

    def holder(s):
        with s.lock:
            weave.pause("held")

    def timed(s):
        got = s.lock.acquire(timeout=0.5)
        s.results.append(got)
        if got:
            s.lock.release()

    sc = weave.Scenario("timed-acquire", setup, [holder, timed],
                        preemption_bound=2)
    res = weave.explore(sc)
    assert not res.failed, res.summary()
    assert res.exhausted


def test_weave_primitives_are_identity_outside_a_run():
    assert weave.active() is None
    weave.pause("nothing")            # immediate no-op
    weave.signal("nothing")
    lock = weave.make_lock("plain")
    assert isinstance(lock, type(threading.RLock()))
    from ompi_tpu.mca.accelerator.jax_acc import _StagingPool

    pool = _StagingPool(max_bytes=1 << 20, enabled=True)
    before = pool._lock
    assert weave.instrument(pool) is pool
    assert pool._lock is before       # untouched: no wrapper off-run


# ---------------------------------------------------------------------------
# the three PR 6 races, reverted: weave re-finds each deterministically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,kind", [
    ("staging-checkout", "check"),
    ("tcp-conns", "exception"),
    ("coord-fence", "deadlock"),
])
def test_reverted_pr6_race_refound_and_replayable(name, kind):
    from ompi_tpu.analysis import scenarios

    sc = scenarios.get(name)
    res = weave.explore(sc)
    assert res.failed, res.summary()
    assert res.kind == kind, res.summary()
    assert res.schedule and res.schedule.startswith(f"{name}@pb")
    # the printed schedule string replays the failure deterministically
    for _ in range(2):
        rep = weave.replay(sc, res.schedule)
        assert rep.failed and rep.kind == kind, rep.summary()
        assert rep.schedule == res.schedule
    # and a fresh exploration converges on the same schedule
    again = weave.explore(sc)
    assert again.schedule == res.schedule
    assert again.schedules == res.schedules


@pytest.mark.parametrize("name", [
    "staging-checkout-fixed", "tcp-conns-fixed", "coord-fence-fixed"])
def test_fixed_twin_has_no_failing_schedule(name):
    from ompi_tpu.analysis import scenarios

    sc = scenarios.get(name)
    res = weave.explore(sc)
    assert not res.failed, res.summary()
    assert res.exhausted
    assert res.schedules > 1


def test_reverted_checkout_shape_refound_statically():
    """The acceptance pin: the checkout-outside-lock revert is caught by
    the STATIC layer too — lock-discipline on the naked insert, and the
    mpi-typestate guarded-handoff rule on the pop -> re-register
    window."""
    res = analysis.lint([str(REPO / "ompi_tpu" / "analysis"
                             / "scenarios.py")],
                        select=["mpi-typestate", "lock-discipline"])
    handoff = [f for f in res.findings
               if f.rule == "mpi-typestate"
               and "guarded handoff" in f.message]
    assert len(handoff) == 1
    assert handoff[0].symbol == "_RevertedCheckoutPool.acquire"
    naked = [f for f in res.findings
             if f.rule == "lock-discipline"
             and f.symbol == "_RevertedCheckoutPool._checkout_window"]
    assert naked, res.findings
    # the real (fixed) pool is clean under both rules
    res = analysis.lint([str(REPO / "ompi_tpu" / "mca" / "accelerator"
                             / "jax_acc.py")],
                        select=["mpi-typestate", "lock-discipline"])
    assert not res.findings, [f.format() for f in res.findings]


def test_scenarios_cli_expectations_hold():
    """`python -m ompi_tpu.analysis.scenarios` exits 0 exactly when all
    reverted scenarios FAIL and all fixed twins pass."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.analysis.scenarios"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("ok   ") == 6, r.stdout
    assert "replay:" in r.stdout


def test_scenarios_cli_bad_input_is_friendly():
    """Typo'd scenario names and malformed schedules are argparse
    errors, not tracebacks."""
    for argv in (["no-such-scenario"],
                 ["--replay", "no-such-scenario@pb2:0.0"],
                 ["--replay", "not-a-schedule"]):
        r = subprocess.run(
            [sys.executable, "-m", "ompi_tpu.analysis.scenarios"]
            + argv,
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r.returncode == 2, (argv, r.returncode, r.stderr)
        assert "Traceback" not in r.stderr, (argv, r.stderr)


def test_lint_parsable_timings_keep_stdout_clean():
    """--timings under --parsable must not corrupt the machine stream:
    timing rows ride on stderr."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.otpu_lint",
         str(FIXTURES / "hot" / "good.py"), "--no-suppressions",
         "--parsable", "--timings"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ms" not in r.stdout, r.stdout
    assert "total:" in r.stderr, r.stderr
