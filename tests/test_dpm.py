"""dpm — spawn / connect / accept / merge + the ULFM recovery loop.

Re-creates the reference's dynamic-process capability tests
(``ompi/dpm/dpm.c``): children get their own COMM_WORLD, talk to the
parent over the spawn intercommunicator, merge into one intracomm, and —
the payoff VERDICT round 1 asked for — a killed rank is replaced by
shrink + spawn + merge re-forming a full-size world under
``tpurun --enable-recovery``.
"""
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

import ompi_tpu
from ompi_tpu.api.errors import ErrorClass, MpiError

REPO = Path(__file__).resolve().parent.parent


def _tpurun(n, args, timeout=120, extra=()):
    env = dict(os.environ)
    env.pop("OTPU_RANK", None)
    env.pop("OTPU_NPROCS", None)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "-n", str(n),
         *extra, *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)


def test_spawn_parent_child_pingpong(tmp_path):
    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        parent = ompi_tpu.get_parent()
        assert parent is not None
        assert parent.remote_size == 2      # the spawning comm had 2 ranks
        assert w.size == 2                  # children's own COMM_WORLD
        if w.rank == 0:
            buf = np.zeros(1, np.float64)
            parent.recv(buf, 0, tag=5)      # from parent rank 0
            parent.send(buf * 2, 0, tag=6)
        w.barrier()
        print(f"child {w.rank} OK")
    """))
    parent = tmp_path / "parent.py"
    parent.write_text(textwrap.dedent(f"""
        import sys
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        inter = w.spawn([sys.executable, {str(child)!r}], 2)
        assert inter.is_inter and inter.remote_size == 2
        if w.rank == 0:
            inter.send(np.array([21.0]), 0, tag=5)   # to child rank 0
            buf = np.zeros(1, np.float64)
            inter.recv(buf, 0, tag=6)
            assert buf[0] == 42.0, buf
        w.barrier()
        print(f"parent {{w.rank}} OK")
    """))
    r = _tpurun(2, [sys.executable, str(parent)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("parent") == 2 and r.stdout.count("child") == 2


def test_spawn_merge_allreduce(tmp_path):
    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        inter = ompi_tpu.get_parent()
        full = inter.merge(high=True)       # children rank AFTER parents
        assert full.size == 3
        assert full.rank == 2               # 2 parents + me
        out = full.allreduce(np.array([float(full.rank + 1)]))
        assert out[0] == 6.0, out
        print("child merged OK")
    """))
    parent = tmp_path / "parent.py"
    parent.write_text(textwrap.dedent(f"""
        import sys
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        inter = w.spawn([sys.executable, {str(child)!r}], 1)
        full = inter.merge(high=False)
        assert full.size == 3 and full.rank == w.rank
        out = full.allreduce(np.array([float(full.rank + 1)]))
        assert out[0] == 6.0, out
        print(f"parent merged OK rank {{w.rank}}")
    """))
    r = _tpurun(2, [sys.executable, str(parent)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("merged OK") == 3


def test_connect_accept(tmp_path):
    """Two halves of one job meet over a named port (MPI_Comm_accept/
    connect) and exchange a message across the new intercomm."""
    script = tmp_path / "ca.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        side = w.split(0 if w.rank < 2 else 1)
        if w.rank < 2:
            inter = side.accept("ca-test-port")
        else:
            inter = side.connect("ca-test-port")
        assert inter.is_inter and inter.remote_size == 2
        if side.rank == 0:
            if w.rank < 2:
                buf = np.zeros(1, np.int64)
                inter.recv(buf, 0, tag=1)
                assert buf[0] == 77
            else:
                inter.send(np.array([77], np.int64), 0, tag=1)
        w.barrier()
        print(f"ca OK rank {w.rank}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("ca OK") == 4


def test_recovery_shrink_spawn_merge(tmp_path):
    """The full elastic-recovery loop: rank 1 dies, survivors revoke +
    shrink to a 2-rank world, spawn a replacement, and merge back to a
    full-size 3-rank communicator that does real work."""
    replacement = tmp_path / "replacement.py"
    replacement.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        inter = ompi_tpu.get_parent()
        full = inter.merge(high=True)
        assert full.size == 3
        out = full.allreduce(np.array([1.0]))
        assert out[0] == 3.0, out
        print("replacement joined OK")
    """))
    script = tmp_path / "recover.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        import numpy as np, ompi_tpu
        w = ompi_tpu.init()
        r = w.rank
        if r == 1:
            os._exit(1)                     # die before doing anything
        from ompi_tpu.api.errors import MpiError
        # survivors: wait for the failure report, then recover
        deadline = time.time() + 30
        while time.time() < deadline:
            failed = w.get_failed()
            if failed.size:
                break
            time.sleep(0.1)
        assert w.get_failed().size == 1
        w.revoke()
        survivors = w.shrink()
        assert survivors.size == 2
        inter = survivors.spawn(
            [sys.executable, {str(replacement)!r}], 1)
        full = inter.merge(high=False)
        assert full.size == 3
        out = full.allreduce(np.array([1.0]))
        assert out[0] == 3.0, out
        print(f"recovered OK rank {{r}}")
    """))
    r = _tpurun(3, [sys.executable, str(script)], timeout=120,
                extra=("--enable-recovery",))
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("recovered OK") == 2
    assert "replacement joined OK" in r.stdout


class _FakeSpawnClient:
    """Coord-client stand-in for the spawn partial-failure paths: a
    configurable rank allocation and a join KV that never fills."""

    def __init__(self, ranks, job="job9"):
        self._ranks, self._job = list(ranks), job

    def fetch_add(self, rank, key, delta):
        return 0                      # first bridge CID: _DPM_CID_BASE

    def spawn(self, cmd, n, env=None):
        return list(self._ranks), self._job

    def get(self, rank, key, wait=True, timeout=60.0):
        return None                   # the join marker never appears


@pytest.fixture
def inproc_world():
    from ompi_tpu.runtime import init as rt

    rt.reset_for_testing()
    w = ompi_tpu.init()
    yield w
    rt.reset_for_testing()


def test_spawn_short_rank_list_releases_cid(inproc_world):
    """A launcher that allocates fewer ranks than requested must raise a
    loud ERR_SPAWN and give the reserved bridge CID back — not hand the
    caller a short-sized intercommunicator."""
    from ompi_tpu import dpm
    from ompi_tpu.runtime import init as rt

    w = inproc_world
    old = getattr(w.rte, "client", None)
    w.rte.client = _FakeSpawnClient(ranks=[100])   # 1 of 2 requested
    try:
        with pytest.raises(MpiError) as ei:
            w.spawn([sys.executable, "-c", "pass"], 2)
        assert ei.value.error_class is ErrorClass.ERR_SPAWN
        assert "allocated 1 of 2" in str(ei.value)
        assert rt.is_cid_free(dpm._DPM_CID_BASE + 0), \
            "failed spawn leaked its reserved bridge CID"
    finally:
        w.rte.client = old


def test_spawn_join_timeout_releases_cid(inproc_world):
    """Children that never reach the runtime (die during join) must trip
    the join-handshake timeout into ERR_SPAWN with the CID released."""
    from ompi_tpu import dpm
    from ompi_tpu.base.var import registry
    from ompi_tpu.runtime import init as rt

    w = inproc_world
    var = registry.lookup("otpu_dpm_spawn_timeout")
    old_t, old_client = var.value, getattr(w.rte, "client", None)
    var.set(0.2)
    w.rte.client = _FakeSpawnClient(ranks=[100, 101])
    try:
        with pytest.raises(MpiError) as ei:
            w.spawn([sys.executable, "-c", "pass"], 2)
        assert ei.value.error_class is ErrorClass.ERR_SPAWN
        assert "did not join" in str(ei.value)
        assert rt.is_cid_free(dpm._DPM_CID_BASE + 0)
    finally:
        var.set(old_t)
        w.rte.client = old_client


def test_spawn_child_dies_during_join(tmp_path):
    """Multi-process regression: a child that exits before reaching the
    runtime turns into ERR_SPAWN at the parent (fast, via the
    launcher's proc_failed report) — and the parent's world remains
    fully usable afterwards."""
    script = tmp_path / "deadspawn.py"
    script.write_text(textwrap.dedent("""
        import sys
        import numpy as np, ompi_tpu
        from ompi_tpu.api.errors import ErrorClass, MpiError
        from ompi_tpu.base.var import registry
        import ompi_tpu.dpm                  # registers the timeout var
        w = ompi_tpu.init()
        registry.set("otpu_dpm_spawn_timeout", 30.0)
        try:
            w.spawn([sys.executable, "-c", "import sys; sys.exit(3)"], 1)
            raise AssertionError("spawn of a dying child succeeded")
        except MpiError as e:
            assert e.error_class is ErrorClass.ERR_SPAWN, e
        out = np.asarray(w.allreduce(np.ones(1)))
        assert out[0] == w.size
        print(f"SPAWNFAIL OK {w.rank}", flush=True)
    """))
    r = _tpurun(1, [sys.executable, str(script)], timeout=120,
                extra=("--enable-recovery",))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SPAWNFAIL OK" in r.stdout


def test_publish_lookup_name(tmp_path):
    """MPI_Publish_name / Lookup_name / Unpublish_name: connect via a
    SERVICE name instead of a pre-shared port string
    (``ompi/mpi/c/publish_name.c``)."""
    script = tmp_path / "pub.py"
    script.write_text(textwrap.dedent("""
        import numpy as np, ompi_tpu
        from ompi_tpu import dpm
        from ompi_tpu.api.errors import MpiError
        w = ompi_tpu.init()
        side = w.split(0 if w.rank < 2 else 1)
        if w.rank < 2:
            port = dpm.open_port(w)
            if side.rank == 0:
                dpm.publish_name("calc-svc", port, w)
            inter = side.accept(port)
            if side.rank == 0:
                dpm.unpublish_name("calc-svc", w)
                try:
                    dpm.lookup_name("calc-svc", w)
                    raise AssertionError("lookup after unpublish")
                except MpiError:
                    pass
        else:
            port = dpm.lookup_name("calc-svc", w, wait=True)
            inter = side.connect(port)
        assert inter.is_inter and inter.remote_size == 2
        w.barrier()
        print(f"pub OK rank {w.rank}")
    """))
    r = _tpurun(4, [sys.executable, str(script)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("pub OK") == 4
