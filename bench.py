#!/usr/bin/env python
"""OSU-style allreduce benchmark (BASELINE.md config #3).

Measures bus bandwidth of the framework's MPI_Allreduce path (coll/xla →
``lax.psum`` over the ICI mesh) on float32 payloads and compares it against
raw hand-written ``jax.lax.psum`` — the ``vs_baseline`` ratio is framework
bandwidth / raw-XLA bandwidth (north star: ≥0.8 at ≥4MB, BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _bus_bw_gbs(nbytes: int, ndev: int, seconds: float) -> float:
    # OSU bus-bandwidth convention for allreduce: 2*(n-1)/n * bytes moved
    factor = 2.0 * (ndev - 1) / ndev if ndev > 1 else 1.0
    return factor * nbytes / seconds / 1e9


def _time_fn(fn, arg, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(arg)
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(arg)
        jax.block_until_ready(out)
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def main() -> None:
    devices = jax.devices()
    ndev = len(devices)
    nelem = (16 << 20) // 4  # 16 MB float32 per rank
    mesh = jax.sharding.Mesh(np.array(devices), ("x",))

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    def raw_psum(x):
        return shard_map(
            lambda a: jax.lax.psum(a, "x"), mesh=mesh,
            in_specs=P("x"), out_specs=P(),
        )(x)

    x = jnp.ones((ndev * nelem,), jnp.float32)
    x = jax.device_put(
        x, jax.sharding.NamedSharding(mesh, P("x")))
    raw_t = _time_fn(raw_psum, x)
    raw_bw = _bus_bw_gbs(nelem * 4, ndev, raw_t)

    # Framework path: eager allreduce through the full stack (comm vtable →
    # coll selection → coll/xla compiled program cache).
    try:
        import ompi_tpu
        from ompi_tpu.mca.coll.xla import XlaCollModule

        world = ompi_tpu.init()
        xla_mod = next((m for m in world.coll_modules
                        if isinstance(m, XlaCollModule)), None)
        if xla_mod is None:
            raise RuntimeError("coll/xla did not select on COMM_WORLD")
        xd = xla_mod.make_world_array(
            np.ones((world.size, nelem), np.float32))
        fw_t = _time_fn(lambda a: world.allreduce_array(a), xd)
        ompi_tpu.finalize()
        fw_bw = _bus_bw_gbs(nelem * 4, ndev, fw_t)
        value, vs = fw_bw, (fw_bw / raw_bw if raw_bw else 0.0)
    except Exception as exc:
        # report the raw number but an honest 0.0 ratio: the framework
        # path did NOT run, so claiming parity would be false
        print(f"framework path unavailable ({exc}); reporting raw psum "
              "with vs_baseline=0", file=sys.stderr)
        value, vs = raw_bw, 0.0

    print(json.dumps({
        "metric": "osu_allreduce_bus_bw_16MB_f32",
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
